"""Upgrade engine: per-node FSM, throttling, skip labels (reference
vendored ``pkg/upgrade`` + ``controllers/upgrade_controller.go``)."""

import pytest

from tests.conftest import make_tpu_node
from tpu_operator import consts
from tpu_operator.api.v1.clusterpolicy_types import (
    DrainSpec,
    UpgradePolicySpec,
)
from tpu_operator.kube import FakeClient
from tpu_operator.upgrade import upgrade_state as us

NS = "tpu-operator"
APP = "tpu-libtpu-daemonset"
DESIRED_HASH = "new-hash"


def driver_ds():
    return {
        "apiVersion": "apps/v1",
        "kind": "DaemonSet",
        "metadata": {"name": APP, "namespace": NS},
        "spec": {
            "selector": {"matchLabels": {"app": APP}},
            "template": {
                "metadata": {
                    "annotations": {consts.LAST_APPLIED_HASH_ANNOTATION: DESIRED_HASH}
                },
                "spec": {},
            },
            "updateStrategy": {"type": "OnDelete"},
        },
    }


def driver_pod(node, h):
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": f"libtpu-{node}",
            "namespace": NS,
            "labels": {"app": APP},
            "annotations": {consts.LAST_APPLIED_HASH_ANNOTATION: h},
        },
        "spec": {"nodeName": node},
        "status": {"phase": "Running"},
    }


def workload_pod(name, node):
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": name,
            "namespace": "default",
            # managed pod: a controller will recreate it after eviction
            "ownerReferences": [{"kind": "Job", "name": "train", "uid": "j1"}],
        },
        "spec": {
            "nodeName": node,
            "containers": [
                {"name": "train", "resources": {"limits": {"google.com/tpu": "4"}}}
            ],
        },
        "status": {"phase": "Running"},
    }


def validator_pod(node):
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": f"validator-{node}",
            "namespace": NS,
            "labels": {"app": "tpu-operator-validator"},
        },
        "spec": {"nodeName": node},
        "status": {"phase": "Running"},
    }


@pytest.fixture()
def cluster():
    client = FakeClient([{"apiVersion": "v1", "kind": "Namespace", "metadata": {"name": NS}}])
    for i in (1, 2, 3, 4):
        node = make_tpu_node(f"node-{i}")
        node["metadata"]["labels"][
            consts.DEPLOY_LABEL_PREFIX + consts.COMPONENT_LIBTPU
        ] = "true"
        client.create(node)
        client.create(driver_pod(f"node-{i}", "stale-hash"))
    client.create(driver_ds())
    return client


def pump(mgr, policy, times=12):
    for _ in range(times):
        state = mgr.build_state()
        mgr.apply_state(state, policy)
    return mgr


def node_state(client, name):
    return client.get("v1", "Node", name)["metadata"]["labels"].get(
        consts.UPGRADE_STATE_LABEL
    )


def test_detects_stale_nodes(cluster):
    mgr = us.ClusterUpgradeStateManager(cluster, NS)
    state = mgr.build_state()
    assert state.count(us.STATE_UPGRADE_REQUIRED) == 4


def test_fresh_nodes_marked_done(cluster):
    # node-1's pod already runs the desired revision
    pod = cluster.get("v1", "Pod", "libtpu-node-1", NS)
    pod["metadata"]["annotations"][consts.LAST_APPLIED_HASH_ANNOTATION] = DESIRED_HASH
    cluster.update(pod)
    mgr = us.ClusterUpgradeStateManager(cluster, NS)
    state = mgr.build_state()
    assert state.count(us.STATE_UPGRADE_REQUIRED) == 3
    assert state.count(us.STATE_DONE) == 1


def test_full_fsm_walk_single_node(cluster):
    mgr = us.ClusterUpgradeStateManager(cluster, NS)
    policy = UpgradePolicySpec(
        auto_upgrade=True, max_parallel_upgrades=1, max_unavailable="25%"
    )
    cluster.create(workload_pod("train-1", "node-1"))

    # walk the FSM; simulate the DaemonSet controller restarting the operand
    # pod with the new hash, and the validator coming up. With
    # maxUnavailable=25% only one node is in flight at a time, so 4 nodes
    # need ~4×7 steps.
    for _ in range(36):
        state = mgr.build_state()
        mgr.apply_state(state, policy)
        for i in (1, 2, 3, 4):
            n = f"node-{i}"
            if cluster.get_or_none("v1", "Pod", f"libtpu-{n}", NS) is None:
                cluster.create(driver_pod(n, DESIRED_HASH))
                cluster.create(validator_pod(n))

    for i in (1, 2, 3, 4):
        assert node_state(cluster, f"node-{i}") == us.STATE_DONE, f"node-{i}"
    # workload pod was evicted along the way
    assert cluster.get_or_none("v1", "Pod", "train-1", "default") is None
    # nodes uncordoned at the end
    for i in (1, 2, 3, 4):
        node = cluster.get("v1", "Node", f"node-{i}")
        assert not node.get("spec", {}).get("unschedulable", False)


def test_max_parallel_throttling(cluster):
    mgr = us.ClusterUpgradeStateManager(cluster, NS)
    policy = UpgradePolicySpec(
        auto_upgrade=True, max_parallel_upgrades=2, max_unavailable="100%"
    )
    state = mgr.build_state()
    mgr.apply_state(state, policy)
    active = sum(
        1
        for i in (1, 2, 3, 4)
        if node_state(cluster, f"node-{i}") not in (us.STATE_UPGRADE_REQUIRED, None)
    )
    assert active == 2


def test_skip_label(cluster):
    node = cluster.get("v1", "Node", "node-1")
    node["metadata"]["labels"][consts.UPGRADE_SKIP_LABEL] = "true"
    cluster.update(node)
    mgr = us.ClusterUpgradeStateManager(cluster, NS)
    state = mgr.build_state()
    assert state.count(us.STATE_UPGRADE_REQUIRED) == 3
    pump(mgr, UpgradePolicySpec(auto_upgrade=True, max_unavailable="100%"), 2)
    assert node_state(cluster, "node-1") is None


def test_skip_drain_label(cluster):
    node = cluster.get("v1", "Node", "node-2")
    node["metadata"]["labels"][consts.UPGRADE_SKIP_DRAIN_LABEL] = "true"
    cluster.update(node)
    cluster.create(workload_pod("train-2", "node-2"))
    mgr = us.ClusterUpgradeStateManager(cluster, NS)
    policy = UpgradePolicySpec(
        auto_upgrade=True,
        max_parallel_upgrades=4,
        max_unavailable="100%",
        drain=DrainSpec(enable=True),
    )
    pump(mgr, policy, 4)
    # node-2 passed drain-required without evicting the workload...
    assert cluster.get_or_none("v1", "Pod", "train-2", "default") is not None
    # ...but pod-deletion-required still deleted TPU pods before that state.
    # (drain skip only skips the drain step)


def test_uncordon_defers_during_host_maintenance(cluster):
    """A maintenance window opening mid-upgrade owns the cordon: the FSM
    parks in uncordon-required (uncordoning would hand the scheduler a
    node about to lose its chips, and the maintenance handler — which
    found the node already cordoned — will not restore it at all-clear)
    and finishes only once the window clears."""
    client = cluster
    mgr = us.ClusterUpgradeStateManager(client, NS)
    for i in (1, 2, 3, 4):
        client.create(validator_pod(f"node-{i}"))
    policy = UpgradePolicySpec(auto_upgrade=True, max_parallel_upgrades=4,
                               max_unavailable="100%")

    # walk node-1 to validation-required, then open a maintenance window
    for _ in range(6):
        mgr.apply_state(mgr.build_state(), policy)
    node = client.get("v1", "Node", "node-1")
    node["metadata"]["labels"][consts.MAINTENANCE_STATE_LABEL] = "pending"
    client.update(node)

    for _ in range(6):
        mgr.apply_state(mgr.build_state(), policy)
    # everyone else finished; node-1 parks cordoned in uncordon-required
    for i in (2, 3, 4):
        assert node_state(client, f"node-{i}") == us.STATE_DONE
    assert node_state(client, "node-1") == us.STATE_UNCORDON_REQUIRED
    assert client.get("v1", "Node", "node-1")["spec"]["unschedulable"] is True

    # the window clears (the handler leaves the node cordoned: it found
    # it cordoned); the FSM then finishes its own cordon
    node = client.get("v1", "Node", "node-1")
    del node["metadata"]["labels"][consts.MAINTENANCE_STATE_LABEL]
    client.update(node)
    mgr.apply_state(mgr.build_state(), policy)
    assert node_state(client, "node-1") == us.STATE_DONE
    assert not client.get("v1", "Node", "node-1")["spec"].get(
        "unschedulable", False
    )


def test_parse_max_unavailable():
    assert us.parse_max_unavailable("25%", 4) == 1
    assert us.parse_max_unavailable("50%", 4) == 2
    assert us.parse_max_unavailable(2, 4) == 2
    assert us.parse_max_unavailable("3", 4) == 3
    assert us.parse_max_unavailable(None, 4) == 4
    assert us.parse_max_unavailable("0%", 4) == 0


def test_pod_requests_tpu():
    assert us.pod_requests_tpu(workload_pod("x", "n"))
    assert not us.pod_requests_tpu(
        {"spec": {"containers": [{"resources": {"limits": {"cpu": "1"}}}]}}
    )
    sub = workload_pod("y", "n")
    sub["spec"]["containers"][0]["resources"]["limits"] = {
        "google.com/tpu-2x2": "1"
    }
    assert us.pod_requests_tpu(sub)


def test_upgrade_reconciler_gates(cluster, monkeypatch):
    import yaml, os
    monkeypatch.setenv(consts.OPERATOR_NAMESPACE_ENV, NS)
    from tpu_operator.upgrade.upgrade_controller import UpgradeReconciler

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(repo, "config", "samples", "v1_clusterpolicy.yaml")) as f:
        cr = yaml.safe_load(f)
    cr["spec"]["libtpu"]["upgradePolicy"] = {"autoUpgrade": False}
    cluster.create(cr)
    # seed a stale state label to prove cleanup
    node = cluster.get("v1", "Node", "node-1")
    node["metadata"]["labels"][consts.UPGRADE_STATE_LABEL] = us.STATE_DONE
    cluster.update(node)

    r = UpgradeReconciler(cluster, NS)
    result = r.reconcile()
    assert result.requeue_after is None
    assert node_state(cluster, "node-1") is None

    cr = cluster.get(consts.API_VERSION, "ClusterPolicy", "cluster-policy")
    cr["spec"]["libtpu"]["upgradePolicy"] = {"autoUpgrade": True}
    cluster.update(cr)
    result = r.reconcile()
    assert result.requeue_after == 120.0


def _age_node_state(client, name, seconds):
    """Backdate the state-entry annotation to simulate an overstayed state."""
    from datetime import datetime, timedelta, timezone

    node = client.get("v1", "Node", name)
    then = datetime.now(timezone.utc) - timedelta(seconds=seconds)
    node["metadata"].setdefault("annotations", {})[
        consts.UPGRADE_STATE_SINCE_ANNOTATION
    ] = then.strftime("%Y-%m-%dT%H:%M:%SZ")
    client.update(node)


def test_drain_timeout_marks_failed(cluster):
    """A node whose drain can't clear inside drain.timeoutSeconds becomes
    upgrade-failed (terminal, cordoned) instead of wedging forever."""
    mgr = us.ClusterUpgradeStateManager(cluster, NS)
    # an unmanaged workload pod blocks drain without force
    cluster.create(
        {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {"name": "naked", "namespace": "default"},
            "spec": {
                "nodeName": "node-1",
                "containers": [
                    {"resources": {"limits": {"google.com/tpu": "4"}}}
                ],
            },
        }
    )
    policy = UpgradePolicySpec(
        auto_upgrade=True,
        max_parallel_upgrades=1,
        max_unavailable="100%",
        drain=DrainSpec(enable=True, timeout_seconds=300),
    )
    pump(mgr, policy, times=6)
    assert node_state(cluster, "node-1") == us.STATE_DRAIN_REQUIRED
    _age_node_state(cluster, "node-1", 301)
    pump(mgr, policy, times=1)
    assert node_state(cluster, "node-1") == us.STATE_FAILED
    # stays cordoned for operator intervention
    assert cluster.get("v1", "Node", "node-1")["spec"]["unschedulable"]
    # a Warning Event names the cause on the node
    events = [
        e
        for e in cluster.list("v1", "Event", NS)
        if e.get("reason") == "UpgradeDrainTimeout"
        and e.get("involvedObject", {}).get("name") == "node-1"
    ]
    assert events and events[0]["type"] == "Warning"
    # terminal: further pumps don't move it
    pump(mgr, policy, times=3)
    assert node_state(cluster, "node-1") == us.STATE_FAILED


def test_validation_timeout_marks_failed(cluster):
    """Validator never converging fails the node after the fixed budget."""
    mgr = us.ClusterUpgradeStateManager(cluster, NS)
    policy = UpgradePolicySpec(
        auto_upgrade=True, max_parallel_upgrades=1, max_unavailable="100%"
    )
    pump(mgr, policy, times=8)  # no validator pod exists -> stuck validating
    assert node_state(cluster, "node-1") == us.STATE_VALIDATION_REQUIRED
    _age_node_state(cluster, "node-1", us.VALIDATION_TIMEOUT_S + 1)
    pump(mgr, policy, times=1)
    assert node_state(cluster, "node-1") == us.STATE_FAILED


def test_wait_for_jobs_timeout_proceeds(cluster):
    """waitForCompletion.timeoutSeconds exhausted -> stop waiting, move on."""
    mgr = us.ClusterUpgradeStateManager(cluster, NS)
    cluster.create(
        {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": "long-job",
                "namespace": "default",
                "labels": {"job-class": "batch"},
                "ownerReferences": [{"kind": "Job", "name": "j", "uid": "u"}],
            },
            "spec": {"nodeName": "node-1"},
            "status": {"phase": "Running"},
        }
    )
    policy = UpgradePolicySpec(
        auto_upgrade=True,
        max_parallel_upgrades=1,
        max_unavailable="100%",
        wait_for_completion={
            "podSelector": "job-class=batch",
            "timeoutSeconds": 600,
        },
    )
    pump(mgr, policy, times=4)
    assert node_state(cluster, "node-1") == us.STATE_WAIT_FOR_JOBS_REQUIRED
    _age_node_state(cluster, "node-1", 601)
    pump(mgr, policy, times=1)
    assert node_state(cluster, "node-1") in (
        us.STATE_POD_DELETION_REQUIRED,
        us.STATE_DRAIN_REQUIRED,
        us.STATE_POD_RESTART_REQUIRED,
    )


def test_failed_node_reenters_after_label_clear(cluster):
    """Clearing the state label is the documented recovery path."""
    mgr = us.ClusterUpgradeStateManager(cluster, NS)
    provider = mgr.provider
    node = cluster.get("v1", "Node", "node-1")
    provider.set_state(node, us.STATE_FAILED)
    provider.clear_state(node)
    node = cluster.get("v1", "Node", "node-1")
    assert consts.UPGRADE_STATE_LABEL not in node["metadata"]["labels"]
    assert consts.UPGRADE_STATE_SINCE_ANNOTATION not in node["metadata"].get(
        "annotations", {}
    )
    policy = UpgradePolicySpec(auto_upgrade=True, max_unavailable="100%")
    pump(mgr, policy, times=1)
    # stale pod -> re-enters at upgrade-required (or beyond)
    assert node_state(cluster, "node-1") is not None


def test_drain_timeout_applies_with_default_policy(cluster):
    """With drain unconfigured (None) draining is still active, so the
    DrainSpec default budget must apply — otherwise an undrainable node
    wedges forever on default config."""
    mgr = us.ClusterUpgradeStateManager(cluster, NS)
    cluster.create(
        {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {"name": "naked2", "namespace": "default"},
            "spec": {
                "nodeName": "node-1",
                "containers": [
                    {"resources": {"limits": {"google.com/tpu": "4"}}}
                ],
            },
        }
    )
    policy = UpgradePolicySpec(
        auto_upgrade=True, max_parallel_upgrades=1, max_unavailable="100%"
    )  # drain omitted
    pump(mgr, policy, times=6)
    assert node_state(cluster, "node-1") == us.STATE_DRAIN_REQUIRED
    _age_node_state(cluster, "node-1", 301)  # past DrainSpec default 300s
    pump(mgr, policy, times=1)
    assert node_state(cluster, "node-1") == us.STATE_FAILED


def test_unstamped_timed_state_gets_stamped_then_times_out(cluster):
    """A node already parked in a timed state by an older operator (label
    present, no since-annotation) must start its clock on first sight and
    still time out eventually."""
    mgr = us.ClusterUpgradeStateManager(cluster, NS)
    cluster.create(
        {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {"name": "naked3", "namespace": "default"},
            "spec": {
                "nodeName": "node-1",
                "containers": [
                    {"resources": {"limits": {"google.com/tpu": "4"}}}
                ],
            },
        }
    )
    # hand-write the label only (pre-upgrade operator state)
    node = cluster.get("v1", "Node", "node-1")
    node["metadata"]["labels"][consts.UPGRADE_STATE_LABEL] = (
        us.STATE_DRAIN_REQUIRED
    )
    cluster.update(node)
    policy = UpgradePolicySpec(
        auto_upgrade=True,
        max_parallel_upgrades=1,
        max_unavailable="100%",
        drain=DrainSpec(enable=True, timeout_seconds=300),
    )
    pump(mgr, policy, times=1)
    # first sight stamped the clock instead of timing out or wedging
    node = cluster.get("v1", "Node", "node-1")
    assert consts.UPGRADE_STATE_SINCE_ANNOTATION in node["metadata"].get(
        "annotations", {}
    )
    assert node_state(cluster, "node-1") == us.STATE_DRAIN_REQUIRED
    _age_node_state(cluster, "node-1", 301)
    pump(mgr, policy, times=1)
    assert node_state(cluster, "node-1") == us.STATE_FAILED


def test_precordoned_node_stays_cordoned_after_upgrade(cluster):
    """A node the admin cordoned before the upgrade must finish the FSM
    still cordoned (reference UpgradeInitialStateAnnotation,
    upgrade_state.go:419-429,869-897)."""
    node = cluster.get("v1", "Node", "node-2")
    node.setdefault("spec", {})["unschedulable"] = True
    cluster.update(node)

    mgr = us.ClusterUpgradeStateManager(cluster, NS)
    policy = UpgradePolicySpec(
        auto_upgrade=True, max_parallel_upgrades=4, max_unavailable="100%"
    )
    for _ in range(12):
        state = mgr.build_state()
        mgr.apply_state(state, policy)
        for i in (1, 2, 3, 4):
            n = f"node-{i}"
            if cluster.get_or_none("v1", "Pod", f"libtpu-{n}", NS) is None:
                cluster.create(driver_pod(n, DESIRED_HASH))
                cluster.create(validator_pod(n))

    for i in (1, 2, 3, 4):
        assert node_state(cluster, f"node-{i}") == us.STATE_DONE, f"node-{i}"
    # node-2 kept its admin cordon; the others were uncordoned
    assert cluster.get("v1", "Node", "node-2")["spec"]["unschedulable"] is True
    for i in (1, 3, 4):
        node = cluster.get("v1", "Node", f"node-{i}")
        assert not node.get("spec", {}).get("unschedulable", False)
    # tracking annotation is consumed on completion
    assert consts.UPGRADE_INITIAL_STATE_ANNOTATION not in (
        cluster.get("v1", "Node", "node-2")["metadata"].get("annotations", {})
    )


def test_cleanup_strips_initial_state_annotation(cluster):
    node = cluster.get("v1", "Node", "node-1")
    node["metadata"]["labels"][consts.UPGRADE_STATE_LABEL] = us.STATE_DONE
    node["metadata"].setdefault("annotations", {})[
        consts.UPGRADE_INITIAL_STATE_ANNOTATION
    ] = "true"
    cluster.update(node)
    mgr = us.ClusterUpgradeStateManager(cluster, NS)
    mgr.cleanup_state_labels()
    node = cluster.get("v1", "Node", "node-1")
    assert consts.UPGRADE_STATE_LABEL not in node["metadata"]["labels"]
    assert consts.UPGRADE_INITIAL_STATE_ANNOTATION not in node["metadata"].get(
        "annotations", {}
    )


def test_stale_initial_state_annotation_cleared_on_reentry(cluster):
    """A leftover initial-state annotation from an aborted upgrade must not
    suppress uncordon when the node re-enters the FSM schedulable."""
    node = cluster.get("v1", "Node", "node-3")
    node["metadata"].setdefault("annotations", {})[
        consts.UPGRADE_INITIAL_STATE_ANNOTATION
    ] = "true"
    cluster.update(node)

    mgr = us.ClusterUpgradeStateManager(cluster, NS)
    policy = UpgradePolicySpec(
        auto_upgrade=True, max_parallel_upgrades=4, max_unavailable="100%"
    )
    for _ in range(12):
        state = mgr.build_state()
        mgr.apply_state(state, policy)
        for i in (1, 2, 3, 4):
            n = f"node-{i}"
            if cluster.get_or_none("v1", "Pod", f"libtpu-{n}", NS) is None:
                cluster.create(driver_pod(n, DESIRED_HASH))
                cluster.create(validator_pod(n))

    assert node_state(cluster, "node-3") == us.STATE_DONE
    node = cluster.get("v1", "Node", "node-3")
    # schedulable again: the stale annotation was discarded on entry
    assert not node.get("spec", {}).get("unschedulable", False)
    assert consts.UPGRADE_INITIAL_STATE_ANNOTATION not in node["metadata"].get(
        "annotations", {}
    )


def test_wait_for_jobs_set_based_selector(cluster):
    """waitForCompletion.podSelector is user-authored apiserver grammar:
    a set-based term like ``job-class in (batch, train)`` must hold the
    node in wait-for-jobs while a matching pod runs (the round-2 parser
    silently dropped non-equality terms, matching nothing)."""
    mgr = us.ClusterUpgradeStateManager(cluster, NS)
    cluster.create(
        {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": "setsel-job",
                "namespace": "default",
                "labels": {"job-class": "train"},
                "ownerReferences": [{"kind": "Job", "name": "j", "uid": "u"}],
            },
            "spec": {"nodeName": "node-1"},
            "status": {"phase": "Running"},
        }
    )
    policy = UpgradePolicySpec(
        auto_upgrade=True,
        max_parallel_upgrades=1,
        max_unavailable="100%",
        wait_for_completion={
            "podSelector": "job-class in (batch, train)",
            "timeoutSeconds": 600,
        },
    )
    pump(mgr, policy, times=4)
    assert node_state(cluster, "node-1") == us.STATE_WAIT_FOR_JOBS_REQUIRED
    # the job finishes -> the very next pass moves on
    cluster.delete("v1", "Pod", "setsel-job", "default")
    pump(mgr, policy, times=1)
    assert node_state(cluster, "node-1") != us.STATE_WAIT_FOR_JOBS_REQUIRED


def test_wait_for_jobs_malformed_selector_fails_closed(cluster):
    """A malformed podSelector must FAIL CLOSED: the gate exists to
    protect running jobs from the drain, so reading it as matching
    nothing would disrupt exactly the workloads it shields. The node
    holds in wait-for-jobs (never an unhandled 400 aborting the pass)
    until the wait budget expires, which proceeds loudly as designed."""
    mgr = us.ClusterUpgradeStateManager(cluster, NS)
    policy = UpgradePolicySpec(
        auto_upgrade=True,
        max_parallel_upgrades=1,
        max_unavailable="100%",
        wait_for_completion={
            "podSelector": "job-class in (batch",  # unbalanced paren
            "timeoutSeconds": 600,
        },
    )
    pump(mgr, policy, times=5)
    assert node_state(cluster, "node-1") == us.STATE_WAIT_FOR_JOBS_REQUIRED
    # the timed budget still bounds the hold: expiry proceeds
    _age_node_state(cluster, "node-1", 601)
    pump(mgr, policy, times=1)
    assert node_state(cluster, "node-1") != us.STATE_WAIT_FOR_JOBS_REQUIRED


def test_vanished_node_does_not_abort_pass(cluster):
    """A node deleted between build_state and apply_state (autoscaler
    scale-down, chaos churn) must be SKIPPED, not abort the whole pass —
    the 40-min soak found upgrade throughput collapsing behind per-pass
    NotFoundError aborts while 117 nodes waited their turn."""
    mgr = us.ClusterUpgradeStateManager(cluster, NS)
    policy = UpgradePolicySpec(
        auto_upgrade=True, max_parallel_upgrades=4, max_unavailable="100%"
    )
    state = mgr.build_state()
    assert len(state.node_states.get(us.STATE_UPGRADE_REQUIRED, [])) == 4
    # node-2 vanishes AFTER the state snapshot was taken
    cluster.delete("v1", "Node", "node-2")
    mgr.apply_state(state, policy)  # old behavior: NotFoundError aborts here
    # every surviving node progressed despite the vanished one
    for name in ("node-1", "node-3", "node-4"):
        assert node_state(cluster, name) == us.STATE_CORDON_REQUIRED, name
    # and the FSM keeps converging the survivors to done (kubelet role:
    # recreate operand pods at the new hash + validator pods)
    for _ in range(12):
        mgr.apply_state(mgr.build_state(), policy)
        for name in ("node-1", "node-3", "node-4"):
            if cluster.get_or_none("v1", "Pod", f"libtpu-{name}", NS) is None:
                cluster.create(driver_pod(name, DESIRED_HASH))
                cluster.create(validator_pod(name))
    for name in ("node-1", "node-3", "node-4"):
        assert node_state(cluster, name) == us.STATE_DONE, name


def test_persistently_conflicting_node_does_not_abort_pass(cluster):
    """A node whose label write keeps 409ing past mutate_with_retry's
    budget is skipped for this pass (retried next reconcile), never
    allowed to abort the other nodes' progress."""
    from tpu_operator.kube.client import ConflictError

    real_update = cluster.update

    def update(obj):
        if (
            obj.get("kind") == "Node"
            and obj["metadata"]["name"] == "node-2"
        ):
            raise ConflictError("scripted persistent 409")
        return real_update(obj)

    mgr = us.ClusterUpgradeStateManager(cluster, NS)
    policy = UpgradePolicySpec(
        auto_upgrade=True, max_parallel_upgrades=4, max_unavailable="100%"
    )
    # take the snapshot FIRST so node-2 is inside the FSM when the
    # conflicts start — this exercises apply_state's _node_step skip,
    # not build_state's entry guard
    state = mgr.build_state()
    cluster.update = update
    mgr.apply_state(state, policy)  # old behavior: ConflictError escapes
    for name in ("node-1", "node-3", "node-4"):
        assert node_state(cluster, name) == us.STATE_CORDON_REQUIRED, name
    # node-2's promotion was skipped (it stays at its entry state); once
    # its writes succeed again it progresses on the next pass
    assert node_state(cluster, "node-2") == us.STATE_UPGRADE_REQUIRED
    cluster.update = real_update
    mgr.apply_state(mgr.build_state(), policy)
    assert node_state(cluster, "node-2") == us.STATE_CORDON_REQUIRED

    # and build_state's own entry guard: conflicts during FSM entry defer
    # the node without aborting the snapshot. The conflicting node must be
    # SCHEDULABLE (an unschedulable one 409s earlier, inside the
    # initial-state set_annotation, which has its own guard) so the
    # scripted conflict lands on the set_state promotion itself.
    def update2(obj):
        if (
            obj.get("kind") == "Node"
            and obj["metadata"]["name"] == "node-2"
        ):
            raise ConflictError("scripted persistent 409")
        return real_update(obj)

    mgr2 = us.ClusterUpgradeStateManager(cluster, NS)
    # reset all nodes to unknown AND schedulable so build_state re-enters
    for i in (1, 2, 3, 4):
        n = cluster.get("v1", "Node", f"node-{i}")
        n["metadata"]["labels"].pop(consts.UPGRADE_STATE_LABEL, None)
        n["metadata"].get("annotations", {}).pop(
            consts.UPGRADE_INITIAL_STATE_ANNOTATION, None
        )
        n.setdefault("spec", {})["unschedulable"] = False
        cluster.update(n)
    cluster.update = update2
    state2 = mgr2.build_state()  # old behavior: aborts at node-2
    entered = {
        ns.node["metadata"]["name"]
        for ns in state2.node_states.get(us.STATE_UPGRADE_REQUIRED, [])
    }
    cluster.update = real_update
    assert "node-2" not in entered
    assert {"node-1", "node-3", "node-4"} <= entered


def test_wait_for_jobs_sees_pods_outside_scoped_cache(cluster):
    """The wait-for-jobs gate evaluates a USER selector over arbitrary
    pods; with the scoped Pod informer (operand + TPU pods only) the
    gate must read LIVE, or a non-TPU coordinator pod in a user
    namespace would be invisible and the node would drain under the job
    it shields (round-4 review finding)."""
    from tpu_operator.kube.cache import CachedClient

    cached = CachedClient(cluster, namespace=NS)
    assert cached.start_informers() is True
    mgr = us.ClusterUpgradeStateManager(cached, NS)
    # a plain (non-TPU) pod in a user namespace: the scoped informer
    # does NOT hold it...
    cluster.create(
        {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": "coordinator",
                "namespace": "default",
                "labels": {"app": "train-coordinator"},
                "ownerReferences": [{"kind": "Job", "name": "j", "uid": "u"}],
            },
            "spec": {
                "nodeName": "node-1",
                "containers": [{"name": "c", "resources": {}}],
            },
            "status": {"phase": "Running"},
        }
    )
    inf = cached._informers[("v1", "Pod")]
    assert all(
        o["metadata"]["name"] != "coordinator" for o in inf.list()
    ), "scoped informer unexpectedly holds the non-TPU pod"
    # ...but the gate still sees it and holds the node
    policy = UpgradePolicySpec(
        auto_upgrade=True,
        max_parallel_upgrades=1,
        max_unavailable="100%",
        wait_for_completion={
            "podSelector": "app=train-coordinator",
            "timeoutSeconds": 600,
        },
    )
    pump(mgr, policy, times=4)
    assert node_state(cluster, "node-1") == us.STATE_WAIT_FOR_JOBS_REQUIRED
    cluster.delete("v1", "Pod", "coordinator", "default")
    pump(mgr, policy, times=1)
    assert node_state(cluster, "node-1") != us.STATE_WAIT_FOR_JOBS_REQUIRED


# ---------------------------------------------------------------------------
# upgrade-failed is no longer terminal: bounded auto-retry + skip hatch
# (before: a failed node consumed maxUnavailable budget FOREVER and
# starved sibling slices until a human cleared the label)
# ---------------------------------------------------------------------------


def _fail_node_via_drain_timeout(cluster, mgr, policy, name="node-1"):
    cluster.create(
        {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {"name": f"naked-{name}", "namespace": "default"},
            "spec": {
                "nodeName": name,
                "containers": [
                    {"resources": {"limits": {"google.com/tpu": "4"}}}
                ],
            },
        }
    )
    pump(mgr, policy, times=6)
    assert node_state(cluster, name) == us.STATE_DRAIN_REQUIRED
    _age_node_state(cluster, name, policy.drain.timeout_seconds + 1)
    pump(mgr, policy, times=1)
    assert node_state(cluster, name) == us.STATE_FAILED


def test_failed_node_auto_retries_after_backoff(cluster):
    import json

    mgr = us.ClusterUpgradeStateManager(cluster, NS)
    policy = UpgradePolicySpec(
        auto_upgrade=True,
        max_parallel_upgrades=1,
        max_unavailable="25%",
        drain=DrainSpec(enable=True, timeout_seconds=300),
    )
    _fail_node_via_drain_timeout(cluster, mgr, policy)

    # not yet due (the failure re-stamped the state clock): stays failed
    pump(mgr, policy, times=2)
    assert node_state(cluster, "node-1") == us.STATE_FAILED

    # past the first backoff window -> auto-retry re-enters the FSM and
    # records the attempt in the annotation
    _age_node_state(cluster, "node-1", us.FAILED_RETRY_BASE_S + 1)
    pump(mgr, policy, times=1)
    assert node_state(cluster, "node-1") != us.STATE_FAILED
    ann = cluster.get("v1", "Node", "node-1")["metadata"]["annotations"]
    assert json.loads(ann[consts.UPGRADE_RETRY_ANNOTATION])["count"] == 1


def test_failed_retry_capped(cluster):
    """Past FAILED_RETRY_MAX the node stays failed — retries must be
    bounded, not a forever crash-loop of drains."""
    import json

    mgr = us.ClusterUpgradeStateManager(cluster, NS)
    node = cluster.get("v1", "Node", "node-1")
    node["metadata"].setdefault("annotations", {})[
        consts.UPGRADE_RETRY_ANNOTATION
    ] = json.dumps({"count": us.FAILED_RETRY_MAX})
    cluster.update(node)
    mgr.provider.set_state(cluster.get("v1", "Node", "node-1"), us.STATE_FAILED)
    _age_node_state(cluster, "node-1", us.FAILED_RETRY_CAP_S + 1)
    policy = UpgradePolicySpec(auto_upgrade=True, max_unavailable="100%")
    pump(mgr, policy, times=3)
    assert node_state(cluster, "node-1") == us.STATE_FAILED


def test_failed_node_no_longer_starves_pending_slices(cluster):
    """THE regression: with maxUnavailable=1 slice, a failed node used to
    pin the whole budget forever (admit=0, every sibling slice pending
    until a human intervened). The auto-retry returns the node to the
    pool, after which admission resumes."""
    mgr = us.ClusterUpgradeStateManager(cluster, NS)
    policy = UpgradePolicySpec(
        auto_upgrade=True,
        max_parallel_upgrades=1,
        max_unavailable="25%",  # 1 of the 4 single-host slices
        drain=DrainSpec(enable=True, timeout_seconds=300),
    )
    _fail_node_via_drain_timeout(cluster, mgr, policy)

    # the failed slice pins the budget: nothing else is admitted
    budget = us.slice_budget(mgr.build_state(), policy)
    assert budget.failed_sids == {"node-1"}
    assert budget.admit == 0
    for i in (2, 3, 4):
        assert node_state(cluster, f"node-{i}") == us.STATE_UPGRADE_REQUIRED

    # the drain blocker is fixed and the backoff elapses -> the node
    # auto-retries, the budget frees, and pending slices move again
    cluster.delete("v1", "Pod", "naked-node-1", "default")
    _age_node_state(cluster, "node-1", us.FAILED_RETRY_BASE_S + 1)
    pump(mgr, policy, times=1)
    budget = us.slice_budget(mgr.build_state(), policy)
    assert budget.failed_sids == set()
    assert budget.admit == 1
    pump(mgr, policy, times=1)
    active = sum(
        1
        for i in (1, 2, 3, 4)
        if node_state(cluster, f"node-{i}")
        not in (us.STATE_UPGRADE_REQUIRED, None)
    )
    assert active >= 1  # admission resumed — no longer starved


def test_skip_label_drops_failed_node_and_frees_budget(cluster):
    """The explicit escape hatch: UPGRADE_SKIP_LABEL on a failed node
    drops it from the FSM immediately (no backoff wait), releasing its
    budget share while leaving the node cordoned for inspection."""
    mgr = us.ClusterUpgradeStateManager(cluster, NS)
    policy = UpgradePolicySpec(
        auto_upgrade=True,
        max_parallel_upgrades=1,
        max_unavailable="25%",
        drain=DrainSpec(enable=True, timeout_seconds=300),
    )
    _fail_node_via_drain_timeout(cluster, mgr, policy)
    assert us.slice_budget(mgr.build_state(), policy).admit == 0

    node = cluster.get("v1", "Node", "node-1")
    node["metadata"]["labels"][consts.UPGRADE_SKIP_LABEL] = "true"
    cluster.update(node)
    pump(mgr, policy, times=1)
    node = cluster.get("v1", "Node", "node-1")
    assert consts.UPGRADE_STATE_LABEL not in node["metadata"]["labels"]
    assert consts.UPGRADE_RETRY_ANNOTATION not in node["metadata"].get(
        "annotations", {}
    )
    assert node["spec"]["unschedulable"]  # left cordoned for a human
    budget = us.slice_budget(mgr.build_state(), policy)
    assert budget.failed_sids == set()
    assert budget.admit == 1


def test_slice_budget_counts_remediation_quarantine(cluster):
    """Upgrades + repairs share ONE maxUnavailable pool: a slice whose
    member host the remediation FSM holds quarantined consumes upgrade
    admission exactly like an upgrade-failed slice."""
    node = cluster.get("v1", "Node", "node-2")
    node["metadata"]["labels"][
        consts.REMEDIATION_STATE_LABEL
    ] = consts.REMEDIATION_STATE_QUARANTINED
    cluster.update(node)
    mgr = us.ClusterUpgradeStateManager(cluster, NS)
    policy = UpgradePolicySpec(
        auto_upgrade=True, max_parallel_upgrades=4, max_unavailable="25%"
    )
    budget = us.slice_budget(mgr.build_state(), policy)
    assert budget.repair_sids == {"node-2"}
    assert budget.admit == 0  # the quarantined slice holds the whole cap
    mgr.apply_state(mgr.build_state(), policy)
    # nothing was admitted: combined disruptions stay within the cap
    for i in (1, 3, 4):
        assert node_state(cluster, f"node-{i}") == us.STATE_UPGRADE_REQUIRED

    # the quarantine lifts -> upgrades admit again
    node = cluster.get("v1", "Node", "node-2")
    del node["metadata"]["labels"][consts.REMEDIATION_STATE_LABEL]
    cluster.update(node)
    assert us.slice_budget(mgr.build_state(), policy).admit == 1


def test_upgrade_never_admits_a_quarantined_slice(cluster):
    """A remediation-quarantined slice must be excluded from PENDING,
    not just subtracted from headroom: admitting it would drain a
    chips-dead host into a guaranteed validation failure (upgrade-failed
    on a quarantined node deadlocks both FSMs until a human unpicks
    them)."""
    node = cluster.get("v1", "Node", "node-1")
    node["metadata"]["labels"][
        consts.REMEDIATION_STATE_LABEL
    ] = consts.REMEDIATION_STATE_QUARANTINED
    cluster.update(node)
    mgr = us.ClusterUpgradeStateManager(cluster, NS)
    policy = UpgradePolicySpec(
        auto_upgrade=True, max_parallel_upgrades=4, max_unavailable="50%"
    )
    budget = us.slice_budget(mgr.build_state(), policy)
    # headroom exists (cap 2, one repair slice) but the quarantined
    # slice is NOT pending — other slices get the remaining admission
    assert budget.admit == 1
    assert "node-1" not in budget.pending_sids
    pump(mgr, policy, times=3)
    assert node_state(cluster, "node-1") in (None, us.STATE_UPGRADE_REQUIRED)
    # node-1 was never cordoned by the upgrade FSM
    assert not cluster.get("v1", "Node", "node-1").get("spec", {}).get(
        "unschedulable", False
    )
