"""TPU host-maintenance handler (TPU-specific operand; no reference
analogue): metadata-driven cordon/label/evict ahead of a maintenance
window, restore on all-clear, the upgrade FSM's initial-state pattern
for pre-cordoned nodes, and crash recovery from the node label alone."""

import os

import pytest

os.environ.setdefault("OPERATOR_NAMESPACE", "tpu-operator")
os.environ.setdefault("UNIT_TEST", "true")

from tests.conftest import make_tpu_node
from tpu_operator import consts
from tpu_operator.kube import FakeClient
from tpu_operator.operands.maintenance import (
    EVENT_NONE,
    STATE_PENDING,
    MaintenanceHandler,
    read_maintenance_event,
)

NS = "tpu-operator"
NODE = "m-node-1"


def tpu_pod(name, owned=True, tpu=True):
    pod = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "nodeName": NODE,
            "containers": [
                {
                    "name": "c",
                    "resources": (
                        {"limits": {consts.TPU_RESOURCE: "4"}} if tpu else {}
                    ),
                }
            ],
        },
        "status": {"phase": "Running"},
    }
    if owned:
        pod["metadata"]["ownerReferences"] = [
            {"apiVersion": "batch/v1", "kind": "Job", "name": "j", "uid": "u1"}
        ]
    return pod


@pytest.fixture()
def env(monkeypatch):
    monkeypatch.setenv(consts.OPERATOR_NAMESPACE_ENV, NS)
    client = FakeClient(
        [
            {
                "apiVersion": "v1",
                "kind": "Namespace",
                "metadata": {"name": NS},
            },
            make_tpu_node(NODE),
        ]
    )
    client.create(tpu_pod("train-owned"))
    client.create(tpu_pod("train-adhoc", owned=False))
    client.create(tpu_pod("sidecar", tpu=False))

    feed = {"event": EVENT_NONE}
    handler = MaintenanceHandler(
        client, NODE, reader=lambda url: feed["event"]
    )
    return client, handler, feed


def node(client):
    return client.get("v1", "Node", NODE)


def test_window_cordons_labels_and_evicts(env):
    client, handler, feed = env
    feed["event"] = "TERMINATE_ON_HOST_MAINTENANCE"
    handler.reconcile_once()

    n = node(client)
    assert n["spec"]["unschedulable"] is True
    assert n["metadata"]["labels"][consts.MAINTENANCE_STATE_LABEL] == STATE_PENDING
    assert (
        n["metadata"]["annotations"][consts.MAINTENANCE_INITIAL_STATE_ANNOTATION]
        == "false"
    )
    # owned TPU pod evicted; unmanaged skipped (non-force drain
    # semantics); non-TPU pod untouched
    assert client.get_or_none("v1", "Pod", "train-owned", "default") is None
    assert client.get_or_none("v1", "Pod", "train-adhoc", "default") is not None
    assert client.get_or_none("v1", "Pod", "sidecar", "default") is not None
    # Warning Event names the window
    events = client.list("v1", "Event", NS)
    assert any(e.get("reason") == "HostMaintenanceImminent" for e in events)


def test_all_clear_restores(env):
    client, handler, feed = env
    feed["event"] = "MIGRATE_ON_HOST_MAINTENANCE"
    handler.reconcile_once()
    feed["event"] = EVENT_NONE
    handler.reconcile_once()

    n = node(client)
    assert not n["spec"].get("unschedulable", False)
    assert consts.MAINTENANCE_STATE_LABEL not in n["metadata"]["labels"]
    assert (
        consts.MAINTENANCE_INITIAL_STATE_ANNOTATION
        not in n["metadata"]["annotations"]
    )
    events = client.list("v1", "Event", NS)
    assert any(e.get("reason") == "HostMaintenanceCleared" for e in events)


def test_precordoned_node_stays_cordoned(env):
    client, handler, feed = env
    n = node(client)
    n.setdefault("spec", {})["unschedulable"] = True  # admin cordoned it
    client.update(n)

    feed["event"] = "TERMINATE_ON_HOST_MAINTENANCE"
    handler.reconcile_once()
    feed["event"] = EVENT_NONE
    handler.reconcile_once()

    n = node(client)
    assert n["spec"]["unschedulable"] is True, (
        "all-clear must restore the state the node was found in"
    )
    assert consts.MAINTENANCE_STATE_LABEL not in n["metadata"]["labels"]


def test_crash_recovery_from_label(env):
    """A handler restart during a window loses in-memory state; a fresh
    process must clean up from the node label alone once the window
    clears."""
    client, handler, feed = env
    feed["event"] = "TERMINATE_ON_HOST_MAINTENANCE"
    handler.reconcile_once()

    fresh = MaintenanceHandler(client, NODE, reader=lambda url: EVENT_NONE)
    fresh.reconcile_once()
    n = node(client)
    assert not n["spec"].get("unschedulable", False)
    assert consts.MAINTENANCE_STATE_LABEL not in n["metadata"]["labels"]


def test_restart_mid_window_reenters_idempotently(env, monkeypatch):
    """A fresh handler that starts while the window is still open re-runs
    entry idempotently: the cordon/label no-op, the eviction sweep clears
    stragglers a crashed predecessor left (the label proves the cordon
    happened, NOT that eviction completed), the pre-cordon annotation is
    preserved, and the Warning Event dedups instead of duplicating.

    The correlator window is pinned to 0 so every record reaches the
    store — this test is about re-entry deduping to ONE Event object
    (count bump), not about in-process write coalescing (covered in
    test_events_and_status.py)."""
    from tpu_operator.kube import events as events_mod

    monkeypatch.setattr(events_mod, "EVENT_REFRESH_INTERVAL_S", 0.0)
    client, handler, feed = env
    feed["event"] = "TERMINATE_ON_HOST_MAINTENANCE"
    handler.reconcile_once()

    client.create(tpu_pod("train-straggler"))
    fresh = MaintenanceHandler(
        client, NODE, reader=lambda url: "TERMINATE_ON_HOST_MAINTENANCE"
    )
    fresh.reconcile_once()
    # the straggler is evicted on re-entry
    assert client.get_or_none("v1", "Pod", "train-straggler", "default") is None
    n = node(client)
    # initial-state annotation survives re-entry (restore still works)
    assert (
        n["metadata"]["annotations"][consts.MAINTENANCE_INITIAL_STATE_ANNOTATION]
        == "false"
    )
    # deduped: one Event object, count bumped
    events = [
        e
        for e in client.list("v1", "Event", NS)
        if e.get("reason") == "HostMaintenanceImminent"
    ]
    assert len(events) == 1
    assert int(events[0].get("count", 1)) >= 2

    # and the all-clear still restores through the fresh process
    fresh2 = MaintenanceHandler(client, NODE, reader=lambda url: EVENT_NONE)
    fresh2.reconcile_once()
    n = node(client)
    assert not n["spec"].get("unschedulable", False)


def test_metadata_outage_holds_state(env):
    """EVENT_UNKNOWN (metadata unreachable) is neither an all-clear nor a
    window: mid-window it must NOT uncordon the doomed node, and in
    steady state it must not evict anything."""
    client, handler, feed = env
    feed["event"] = "TERMINATE_ON_HOST_MAINTENANCE"
    handler.reconcile_once()

    feed["event"] = None  # metadata server dies mid-window
    handler.reconcile_once()
    n = node(client)
    assert n["spec"]["unschedulable"] is True, (
        "a metadata outage mid-window must not read as an all-clear"
    )
    assert n["metadata"]["labels"][consts.MAINTENANCE_STATE_LABEL] == STATE_PENDING

    feed["event"] = EVENT_NONE  # real all-clear arrives
    handler.reconcile_once()
    assert not node(client)["spec"].get("unschedulable", False)


def test_no_evict_mode(env):
    client, handler, feed = env
    handler.evict = False
    feed["event"] = "TERMINATE_ON_HOST_MAINTENANCE"
    handler.reconcile_once()
    n = node(client)
    assert n["spec"]["unschedulable"] is True
    assert client.get_or_none("v1", "Pod", "train-owned", "default") is not None


def test_force_evicts_unmanaged(env):
    client, handler, feed = env
    handler.force = True
    feed["event"] = "TERMINATE_ON_HOST_MAINTENANCE"
    handler.reconcile_once()
    assert client.get_or_none("v1", "Pod", "train-adhoc", "default") is None


def test_metadata_unreachable_reads_unknown():
    """A dead metadata server reads as UNKNOWN — never as a maintenance
    signal, never as an all-clear."""
    assert (
        read_maintenance_event("http://127.0.0.1:1/nope", timeout_s=0.2)
        is None
    )


def _metadata_server(body: bytes, flavor: bool = True):
    """One-shot local HTTP server standing in for the GCE metadata
    endpoint; returns (thread, url)."""
    import http.server
    import threading

    class H(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            self.send_response(200)
            if flavor:
                self.send_header("Metadata-Flavor", "Google")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), H)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv, f"http://127.0.0.1:{srv.server_port}/maintenance-event"


def test_known_bodies_pass_through():
    for body in (b"NONE", b"MIGRATE_ON_HOST_MAINTENANCE", b""):
        srv, url = _metadata_server(body)
        try:
            got = read_maintenance_event(url, timeout_s=2)
            assert got == (body.decode() or EVENT_NONE)
        finally:
            srv.shutdown()


def test_arbitrary_200_body_reads_unknown():
    """A captive portal / proxy error page answering 200 with arbitrary
    text must NOT read as an active window — that would evict live
    training workloads on every poll (advisor finding, round 2)."""
    srv, url = _metadata_server(b"<html>hotel wifi login</html>")
    try:
        assert read_maintenance_event(url, timeout_s=2) is None
    finally:
        srv.shutdown()


def test_missing_metadata_flavor_header_reads_unknown():
    """A 200 lacking the Metadata-Flavor: Google marker is not the GCE
    metadata server — even if the body happens to say NONE."""
    srv, url = _metadata_server(b"NONE", flavor=False)
    try:
        assert read_maintenance_event(url, timeout_s=2) is None
    finally:
        srv.shutdown()


def test_all_clear_defers_uncordon_to_upgrade_fsm(env):
    """If the upgrade FSM cordoned the node mid-window, the maintenance
    all-clear must not uncordon it mid-drain/mid-libtpu-swap — the FSM
    owns the cordon until its own uncordon step (advisor finding,
    round 2: the reverse interleaving of upgrade_state's maintenance
    deferral)."""
    from tpu_operator.kube.client import mutate_with_retry
    from tpu_operator.upgrade.upgrade_state import STATE_DRAIN_REQUIRED

    client, handler, feed = env
    feed["event"] = "TERMINATE_ON_HOST_MAINTENANCE"
    handler.reconcile_once()

    # upgrade FSM takes the node mid-window: it finds the node already
    # cordoned (by us) and records initial-state=cordoned, exactly as
    # build_state does (upgrade_state.py:397-404)
    def fsm_cordon(node_obj):
        node_obj["metadata"]["labels"][consts.UPGRADE_STATE_LABEL] = (
            STATE_DRAIN_REQUIRED
        )
        node_obj["metadata"].setdefault("annotations", {})[
            consts.UPGRADE_INITIAL_STATE_ANNOTATION
        ] = "true"
        node_obj["spec"]["unschedulable"] = True
        return True

    mutate_with_retry(client, "v1", "Node", NODE, mutate=fsm_cordon)

    feed["event"] = EVENT_NONE
    handler.reconcile_once()
    n = node(client)
    assert n["spec"]["unschedulable"] is True, (
        "all-clear uncordoned a node the upgrade FSM still holds"
    )
    # maintenance bookkeeping is still cleaned up
    assert consts.MAINTENANCE_STATE_LABEL not in n["metadata"]["labels"]
    # ownership transfer, not just deferral: the FSM recorded OUR cordon
    # as the node's initial state; with the maintenance annotation popped,
    # nobody would ever uncordon unless the all-clear also clears the
    # FSM's initial-state memory so the FSM uncordons at completion
    assert (
        consts.UPGRADE_INITIAL_STATE_ANNOTATION
        not in n["metadata"].get("annotations", {})
    ), "FSM would skip its uncordon forever (permanent capacity loss)"
    events = client.list("v1", "Event", NS)
    assert any(
        "upgrade in progress" in e.get("message", "")
        for e in events
        if e.get("reason") == "HostMaintenanceCleared"
    )


def test_event_message_reflects_what_happened(env):
    """The Imminent event must not claim evictions that never happened
    (cordon-only mode / empty node)."""
    client, handler, feed = env
    handler.evict = False
    feed["event"] = "TERMINATE_ON_HOST_MAINTENANCE"
    handler.reconcile_once()
    events = client.list("v1", "Event", NS)
    msgs = [
        e["message"]
        for e in events
        if e.get("reason") == "HostMaintenanceImminent"
    ]
    assert msgs and all("eviction disabled" in m for m in msgs)
    assert not any("evicted" in m for m in msgs)


def test_fleet_gauge_counts_nodes_under_maintenance(env, monkeypatch):
    """The operator's fleet metrics expose how many nodes sit in an
    active maintenance window."""
    from prometheus_client import REGISTRY

    from tpu_operator.controllers.clusterpolicy_controller import (
        ClusterPolicyReconciler,
    )
    from tpu_operator.kube.testing import sample_clusterpolicy_path

    import yaml

    client, handler, feed = env
    with open(sample_clusterpolicy_path()) as f:
        cr = yaml.safe_load(f)
    cr["metadata"]["uid"] = "uid-cp"
    client.create(cr)
    rec = ClusterPolicyReconciler(client)

    rec.reconcile()
    assert REGISTRY.get_sample_value("tpu_operator_nodes_under_maintenance") == 0

    feed["event"] = "TERMINATE_ON_HOST_MAINTENANCE"
    handler.reconcile_once()
    rec.reconcile()
    assert REGISTRY.get_sample_value("tpu_operator_nodes_under_maintenance") == 1

    feed["event"] = EVENT_NONE
    handler.reconcile_once()
    rec.reconcile()
    assert REGISTRY.get_sample_value("tpu_operator_nodes_under_maintenance") == 0


def test_state_gating(monkeypatch):
    """Disabled (the default) deploys nothing; enabling deploys the DS
    with the deploy label driving its nodeSelector."""
    import yaml

    from tpu_operator.controllers.clusterpolicy_controller import (
        ClusterPolicyReconciler,
    )
    from tpu_operator.kube.testing import sample_clusterpolicy_path

    monkeypatch.setenv(consts.OPERATOR_NAMESPACE_ENV, NS)
    with open(sample_clusterpolicy_path()) as f:
        cr = yaml.safe_load(f)
    cr["metadata"]["uid"] = "uid-cp"
    client = FakeClient(
        [{"apiVersion": "v1", "kind": "Namespace", "metadata": {"name": NS}}]
    )
    client.create(cr)
    client.create(make_tpu_node(NODE))
    rec = ClusterPolicyReconciler(client)
    rec.reconcile()
    names = {d["metadata"]["name"] for d in client.list("apps/v1", "DaemonSet", NS)}
    assert "tpu-maintenance-handler" not in names  # opt-in default off

    cp = client.get("tpu.k8s.io/v1", "ClusterPolicy", "cluster-policy")
    cp["spec"]["maintenanceHandler"]["enabled"] = True
    client.update(cp)
    rec.reconcile()
    names = {d["metadata"]["name"] for d in client.list("apps/v1", "DaemonSet", NS)}
    assert "tpu-maintenance-handler" in names
    # the deploy-label bus drives scheduling
    n = client.get("v1", "Node", NODE)
    assert (
        n["metadata"]["labels"].get(
            consts.DEPLOY_LABEL_PREFIX + consts.COMPONENT_MAINTENANCE_HANDLER
        )
        == "true"
    )


def _guard_pdb(client, min_available=1):
    client.create(
        {
            "apiVersion": "policy/v1",
            "kind": "PodDisruptionBudget",
            "metadata": {"name": "train-pdb", "namespace": "default"},
            "spec": {"minAvailable": min_available, "selector": {}},
        }
    )


def test_pdb_vetoed_eviction_retries_while_window_open(env):
    """A disruption budget vetoing the pre-maintenance sweep must not be
    one-shot: the handler keeps retrying every poll while the window is
    open (the budget may free up before the host dies), and the Event
    reports the veto instead of claiming success."""
    client, handler, feed = env
    client.delete("v1", "Pod", "train-adhoc", "default")  # focus on owned
    # the empty selector covers the sidecar too: 2 healthy pods, so
    # minAvailable=2 means zero disruptions allowed
    _guard_pdb(client, min_available=2)

    feed["event"] = "TERMINATE_ON_HOST_MAINTENANCE"
    handler.reconcile_once()
    # vetoed: the pod survives, the event tells the truth
    assert client.get_or_none("v1", "Pod", "train-owned", "default") is not None
    events = client.list("v1", "Event", NS)
    msgs = [
        e["message"]
        for e in events
        if e.get("reason") == "HostMaintenanceImminent"
    ]
    assert msgs and any("vetoed by a disruption budget" in m for m in msgs)
    assert not any("1 TPU workload pod(s) evicted" in m for m in msgs)

    # the budget frees up mid-window -> the NEXT poll evicts
    pdb = client.get("policy/v1", "PodDisruptionBudget", "train-pdb", "default")
    pdb["spec"]["minAvailable"] = 0
    client.update(pdb)
    handler.reconcile_once()
    assert client.get_or_none("v1", "Pod", "train-owned", "default") is None


def test_force_evicts_past_pdb_on_doomed_host(env):
    """FORCE_EVICT=true means force: with the host termination imminent,
    a PDB veto falls back to deletion (kubectl --disable-eviction
    semantics) rather than stranding the pod to die with the node."""
    client, handler, feed = env
    handler.force = True
    _guard_pdb(client, min_available=3)

    feed["event"] = "TERMINATE_ON_HOST_MAINTENANCE"
    handler.reconcile_once()
    assert client.get_or_none("v1", "Pod", "train-owned", "default") is None
    assert client.get_or_none("v1", "Pod", "train-adhoc", "default") is None


def test_skipped_unmanaged_not_reported_as_evicted(env):
    """The Event must not count skipped unmanaged pods as evictions."""
    client, handler, feed = env
    client.delete("v1", "Pod", "train-owned", "default")  # leave only adhoc
    feed["event"] = "TERMINATE_ON_HOST_MAINTENANCE"
    handler.reconcile_once()
    events = client.list("v1", "Event", NS)
    msgs = [
        e["message"]
        for e in events
        if e.get("reason") == "HostMaintenanceImminent"
    ]
    assert msgs and all("unmanaged pod(s) left alone" in m for m in msgs)
    assert not any("evicted" in m for m in msgs)


def test_slice_flip_on_member_maintenance(monkeypatch):
    """Unit: a member of a 4-host slice entering maintenance proactively
    flips tpu.slice.ready=false on EVERY member before the drain and
    records one per-slice Event naming window + host; the all-clear
    records the per-slice clear Event (the aggregate restores the
    verdict)."""
    monkeypatch.setenv(consts.OPERATOR_NAMESPACE_ENV, NS)
    client = FakeClient(
        [{"apiVersion": "v1", "kind": "Namespace", "metadata": {"name": NS}}]
    )
    members = [f"s-host-{i}" for i in range(1, 5)]
    for n in members:
        client.create(
            make_tpu_node(
                n,
                extra_labels={
                    consts.TFD_SLICE_ID_LABEL: "slice-m",
                    consts.TFD_SLICE_HOSTS_LABEL: "4",
                    consts.SLICE_READY_LABEL: "true",
                },
            )
        )
    feed = {"event": "TERMINATE_ON_HOST_MAINTENANCE"}
    handler = MaintenanceHandler(
        client, "s-host-2", reader=lambda url: feed["event"]
    )
    handler.reconcile_once()

    # every member flipped BEFORE the outage, not just the doomed host
    for n in members:
        node = client.get("v1", "Node", n)
        assert (
            node["metadata"]["labels"][consts.SLICE_READY_LABEL] == "false"
        ), n
    events = client.list("v1", "Event", NS)
    sched = [
        e for e in events if e.get("reason") == "SliceMaintenanceScheduled"
    ]
    assert len(sched) == 1, [e.get("reason") for e in events]
    msg = sched[0]["message"]
    assert "slice-m" in msg and "s-host-2" in msg and "TERMINATE" in msg, msg

    # all-clear: per-slice clear Event recorded
    feed["event"] = EVENT_NONE
    handler.reconcile_once()
    events = client.list("v1", "Event", NS)
    cleared = [
        e for e in events if e.get("reason") == "SliceMaintenanceCleared"
    ]
    assert len(cleared) == 1 and "slice-m" in cleared[0]["message"]


def test_single_host_maintenance_does_not_touch_slice_labels(monkeypatch):
    """A single-host node's verdict is the aggregate's alone: the handler
    must not write slice.ready or emit slice Events for a slice of one."""
    monkeypatch.setenv(consts.OPERATOR_NAMESPACE_ENV, NS)
    client = FakeClient(
        [{"apiVersion": "v1", "kind": "Namespace", "metadata": {"name": NS}}]
    )
    client.create(make_tpu_node(NODE))
    handler = MaintenanceHandler(
        client, NODE, reader=lambda url: "MIGRATE_ON_HOST_MAINTENANCE"
    )
    handler.reconcile_once()
    node = client.get("v1", "Node", NODE)
    assert consts.SLICE_READY_LABEL not in node["metadata"]["labels"]
    assert not any(
        e.get("reason") == "SliceMaintenanceScheduled"
        for e in client.list("v1", "Event", NS)
    )


def test_slice_maintenance_end_to_end_over_the_wire():
    """VERDICT r4 item 6 done-criterion on kubesim with the full Manager:
    4-host slice, maintenance on one host → the slice goes not-ready
    with the window named in a per-slice Event while the operator AGREES
    (it does not flip the verdict back while the window is open);
    restored to ready after the all-clear."""
    import time

    from tests.conftest import running_operator, wait_until
    from tpu_operator.kube.kubesim import KubeSim, KubeSimServer, make_client
    from tpu_operator.kube.testing import seed_cluster

    members = [f"w-host-{i}" for i in range(1, 5)]
    server = KubeSimServer(KubeSim(bookmark_interval_s=1.0)).start()
    client = make_client(server.port)
    client.GET_RETRY_BACKOFF_S = 0.05
    seed_cluster(client, NS, node_names=())
    for n in members:
        client.create(
            make_tpu_node(
                n,
                extra_labels={
                    consts.TFD_SLICE_ID_LABEL: "slice-w",
                    consts.TFD_SLICE_HOSTS_LABEL: "4",
                },
            )
        )

    def slice_ready_labels():
        return {
            n: (
                client.get("v1", "Node", n)["metadata"].get("labels", {})
            ).get(consts.SLICE_READY_LABEL)
            for n in members
        }

    try:
        with running_operator(client, NS, members):
            assert wait_until(
                lambda: set(slice_ready_labels().values()) == {"true"}, 120
            ), slice_ready_labels()

            feed = {"event": "TERMINATE_ON_HOST_MAINTENANCE"}
            handler = MaintenanceHandler(
                client, members[1], reader=lambda url: feed["event"]
            )
            handler.reconcile_once()
            assert wait_until(
                lambda: set(slice_ready_labels().values()) == {"false"}, 30
            ), slice_ready_labels()

            # the operator AGREES while the window is open: the verdict
            # must hold false across several reconcile rounds
            held = []

            def still_false():
                held.append(set(slice_ready_labels().values()))
                return held[-1] != {"false"}

            assert not wait_until(still_false, 5), (
                f"operator flipped the slice back mid-window: {held[-1]}"
            )
            events = client.list("v1", "Event", NS)
            assert any(
                e.get("reason") == "SliceMaintenanceScheduled"
                and "slice-w" in e.get("message", "")
                and members[1] in e.get("message", "")
                for e in events
            ), [e.get("reason") for e in events]

            # all-clear → the operator restores the verdict
            feed["event"] = EVENT_NONE
            handler.reconcile_once()
            assert wait_until(
                lambda: set(slice_ready_labels().values()) == {"true"}, 60
            ), slice_ready_labels()
            node = client.get("v1", "Node", members[1])
            assert not node.get("spec", {}).get("unschedulable", False)
    finally:
        server.stop()
