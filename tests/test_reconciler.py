"""ClusterPolicy reconciler semantics (reference
``controllers/clusterpolicy_controller.go``): singleton, requeue cadences,
status updates, node-event predicates."""

import os

import pytest
import yaml

from tests.conftest import make_cpu_node, make_tpu_node
from tpu_operator import consts
from tpu_operator.api.v1.clusterpolicy_types import State
from tpu_operator.controllers.clusterpolicy_controller import (
    REQUEUE_NO_LABELS_S,
    REQUEUE_NOT_READY_S,
    ClusterPolicyReconciler,
    node_event_needs_reconcile,
)
from tpu_operator.kube import FakeClient

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ASSETS = os.path.join(REPO, "assets")
NS = "tpu-operator"


def load_cr(name="cluster-policy"):
    with open(os.path.join(REPO, "config", "samples", "v1_clusterpolicy.yaml")) as f:
        obj = yaml.safe_load(f)
    obj["metadata"]["name"] = name
    obj["metadata"]["uid"] = f"uid-{name}"
    return obj


@pytest.fixture()
def env(monkeypatch):
    monkeypatch.setenv(consts.OPERATOR_NAMESPACE_ENV, NS)


def simulate_kubelet(client):
    for ds in client.list("apps/v1", "DaemonSet", NS):
        ds["status"] = {
            "desiredNumberScheduled": 1,
            "numberUnavailable": 0,
            "updatedNumberScheduled": 1,
        }
        client.update_status(ds)
        if ds["spec"].get("updateStrategy", {}).get("type") == "OnDelete":
            app = ds["spec"]["selector"]["matchLabels"]["app"]
            h = ds["spec"]["template"]["metadata"].get("annotations", {}).get(
                consts.LAST_APPLIED_HASH_ANNOTATION
            )
            pod = {
                "apiVersion": "v1",
                "kind": "Pod",
                "metadata": {
                    "name": f"{app}-0",
                    "namespace": NS,
                    "labels": {"app": app},
                    "annotations": {consts.LAST_APPLIED_HASH_ANNOTATION: h},
                },
                "spec": {"nodeName": "tpu-node-1"},
                "status": {"phase": "Running"},
            }
            existing = client.get_or_none("v1", "Pod", pod["metadata"]["name"], NS)
            if existing is None:
                client.create(pod)


def test_reconcile_to_ready(env):
    client = FakeClient(
        [
            {"apiVersion": "v1", "kind": "Namespace", "metadata": {"name": NS}},
            make_tpu_node("tpu-node-1"),
        ]
    )
    client.create(load_cr())
    r = ClusterPolicyReconciler(client, assets_dir=ASSETS)
    # first pass: DaemonSets created but not scheduled -> notReady, 5s requeue
    result = r.reconcile()
    assert result.requeue_after == REQUEUE_NOT_READY_S
    cr = client.get(consts.API_VERSION, "ClusterPolicy", "cluster-policy")
    assert cr["status"]["state"] == State.NOT_READY
    assert cr["status"]["namespace"] == NS
    # kubelet runs everything -> ready
    simulate_kubelet(client)
    result = r.reconcile()
    assert result.ready
    cr = client.get(consts.API_VERSION, "ClusterPolicy", "cluster-policy")
    assert cr["status"]["state"] == State.READY


def test_singleton_stable_across_reconciles(env):
    """Primary selection must not flip-flop as status writes bump
    resourceVersions (regression: sort by creationTimestamp, not rv)."""
    client = FakeClient(
        [
            {"apiVersion": "v1", "kind": "Namespace", "metadata": {"name": NS}},
            make_tpu_node("tpu-node-1"),
        ]
    )
    client.create(load_cr("a-policy"))
    client.create(load_cr("b-policy"))
    r = ClusterPolicyReconciler(client, assets_dir=ASSETS)
    for _ in range(3):
        r.reconcile()
        primary = client.get(consts.API_VERSION, "ClusterPolicy", "a-policy")
        extra = client.get(consts.API_VERSION, "ClusterPolicy", "b-policy")
        assert primary["status"]["state"] != State.IGNORED
        assert extra["status"]["state"] == State.IGNORED


def test_singleton_extra_cr_ignored(env):
    client = FakeClient(
        [
            {"apiVersion": "v1", "kind": "Namespace", "metadata": {"name": NS}},
            make_tpu_node("tpu-node-1"),
        ]
    )
    client.create(load_cr("cluster-policy"))
    client.create(load_cr("cluster-policy-2"))
    r = ClusterPolicyReconciler(client, assets_dir=ASSETS)
    r.reconcile()
    extra = client.get(consts.API_VERSION, "ClusterPolicy", "cluster-policy-2")
    assert extra["status"]["state"] == State.IGNORED
    primary = client.get(consts.API_VERSION, "ClusterPolicy", "cluster-policy")
    assert primary["status"]["state"] != State.IGNORED


def test_no_tpu_labels_polls_45s(env):
    client = FakeClient(
        [
            {"apiVersion": "v1", "kind": "Namespace", "metadata": {"name": NS}},
            make_cpu_node("cpu-1"),
        ]
    )
    client.create(load_cr())
    r = ClusterPolicyReconciler(client, assets_dir=ASSETS)
    result = r.reconcile()
    assert result.requeue_after == REQUEUE_NO_LABELS_S


def test_no_cr_is_noop(env):
    client = FakeClient()
    r = ClusterPolicyReconciler(client, assets_dir=ASSETS)
    result = r.reconcile()
    assert result.requeue_after is None and not result.ready


def test_node_event_predicates():
    tpu = make_tpu_node("n1")
    cpu = make_cpu_node("n2")
    assert node_event_needs_reconcile("ADDED", None, tpu)
    assert not node_event_needs_reconcile("ADDED", None, cpu)
    assert node_event_needs_reconcile("DELETED", tpu, tpu)
    # irrelevant label change -> no reconcile
    new = make_tpu_node("n1")
    new["metadata"]["labels"]["unrelated"] = "x"
    assert not node_event_needs_reconcile("MODIFIED", tpu, new)
    # deploy-label tamper -> reconcile (reference restores labels)
    new2 = make_tpu_node("n1")
    new2["metadata"]["labels"][consts.DEPLOY_LABEL_PREFIX + "libtpu"] = "false"
    assert node_event_needs_reconcile("MODIFIED", tpu, new2)


def test_step_exception_is_isolated_and_records_failure_metric(
    env, monkeypatch
):
    """An exception inside a state step no longer aborts the pass: the
    state is isolated (recorded under status.erroredStates + a Degraded
    condition), the remaining states still run, and the run lands in the
    reconcile metrics as failed (reference reconciliation_status=-1
    semantics) with a requeue instead of a raise."""
    client = FakeClient(
        [
            {"apiVersion": "v1", "kind": "Namespace", "metadata": {"name": NS}},
            make_tpu_node("tpu-node-1"),
        ]
    )
    client.create(load_cr())
    r = ClusterPolicyReconciler(client, assets_dir=ASSETS)
    recorded = []
    monkeypatch.setattr(
        r.metrics, "observe_reconcile", lambda v: recorded.append(v)
    )

    real_run = r.ctrl.run_state

    def boom(state):
        # run_state is the per-state entry point both step() and the
        # DAG-wave executor (run_states) go through
        if state == "state-metricsd":
            raise RuntimeError("control exploded")
        return real_run(state)

    monkeypatch.setattr(r.ctrl, "run_state", boom)
    res = r.reconcile()  # must NOT raise
    assert res.requeue_after is not None
    assert recorded[-1] == -1
    cr = client.get(consts.API_VERSION, "ClusterPolicy", "cluster-policy")
    errored = cr["status"]["erroredStates"]
    assert errored == [
        {"state": "state-metricsd", "error": "RuntimeError: control exploded"}
    ]
    degraded = next(
        c for c in cr["status"]["conditions"] if c["type"] == "Degraded"
    )
    assert degraded["status"] == "True"
    assert degraded["reason"] == "StatesErrored"
    assert "state-metricsd" in degraded.get("message", "")
    # the pass CONTINUED: states after the errored one still deployed
    # their operands (tpu-feature-discovery comes after state-metricsd)
    assert client.get_or_none(
        "apps/v1", "DaemonSet", "tpu-feature-discovery", NS
    ) is not None
    # a warning Event names the degradation
    reasons = {e["reason"] for e in client.list("v1", "Event", NS)}
    assert "StatesDegraded" in reasons

    # the fault cleared: the next pass drops the Degraded condition and
    # the erroredStates block
    monkeypatch.setattr(r.ctrl, "run_state", real_run)
    r.reconcile()
    cr = client.get(consts.API_VERSION, "ClusterPolicy", "cluster-policy")
    assert "erroredStates" not in cr["status"]
    degraded = next(
        c for c in cr["status"]["conditions"] if c["type"] == "Degraded"
    )
    assert degraded["status"] == "False"


# ---------------------------------------------------------------------------
# zero-copy read path: frozen views, explicit-copy writers, the per-pass
# snapshot, and the get_runtime falsy-list fix (ISSUE 1)
# ---------------------------------------------------------------------------


def _cached(client):
    from tpu_operator.kube.cache import CachedClient

    cached = CachedClient(client, namespace=NS)
    assert cached.start_informers() is True
    return cached


def test_reconcile_converges_behind_frozen_cache(env):
    """The full reconcile loop must run to Ready against the zero-copy
    CachedClient — every mutator goes through the explicit-copy path, so
    the always-on write guard stays silent (acceptance criterion: no
    cached-view mutation escapes)."""
    client = FakeClient(
        [
            {"apiVersion": "v1", "kind": "Namespace", "metadata": {"name": NS}},
            make_tpu_node("tpu-node-1"),
        ]
    )
    client.create(load_cr())
    cached = _cached(client)
    r = ClusterPolicyReconciler(cached, assets_dir=ASSETS)
    r.reconcile()
    simulate_kubelet(client)
    assert r.reconcile().ready
    # labeling went through the copy path and CONVERGED on the apiserver
    node = client.get("v1", "Node", "tpu-node-1")
    assert node["metadata"]["labels"][consts.TPU_PRESENT_LABEL] == "true"
    # snapshot observability recorded a pass with shared reads
    stats = r.ctrl.snapshot_stats()
    assert stats["hits_total"] > 0
    assert stats["last_pass"]["hit_rate"] > 0


def test_label_tpu_nodes_thaws_only_dirty_nodes(env):
    """label_tpu_nodes reads shared frozen views and pays a copy only
    for nodes whose labels actually change: second pass (steady state)
    writes nothing and copies nothing."""
    client = FakeClient(
        [
            {"apiVersion": "v1", "kind": "Namespace", "metadata": {"name": NS}},
            make_tpu_node("tpu-node-1"),
            make_tpu_node("tpu-node-2"),
        ]
    )
    client.create(load_cr())
    cached = _cached(client)
    r = ClusterPolicyReconciler(cached, assets_dir=ASSETS)
    r.reconcile()
    writes = []
    orig_update = cached.update

    def counting_update(obj, **kw):
        writes.append(obj["metadata"]["name"])
        return orig_update(obj, **kw)

    cached.update = counting_update
    before = cached.read_stats()["copied_reads"]
    r.reconcile()
    node_writes = [w for w in writes if w.startswith("tpu-node")]
    assert node_writes == [], f"steady state re-labeled: {node_writes}"
    # the CR status read pays its explicit copies; the node labeling
    # pass itself adds none (2 nodes scanned, 0 thawed)
    assert cached.read_stats()["copied_reads"] - before <= 4


def test_get_runtime_serves_listed_empty_cluster(env):
    """The falsy-list bug: ``_nodes_cache == []`` means 'listed, zero
    nodes' and must NOT fall back to a fresh list per call."""
    client = FakeClient(
        [{"apiVersion": "v1", "kind": "Namespace", "metadata": {"name": NS}}]
    )
    client.create(load_cr())
    r = ClusterPolicyReconciler(client, assets_dir=ASSETS)
    r.reconcile()  # no TPU nodes: init ran, cache is a REAL empty list
    assert r.ctrl._nodes_cache == []
    calls = []
    orig_list = client.list

    def counting_list(av, kind, *a, **kw):
        calls.append(kind)
        return orig_list(av, kind, *a, **kw)

    client.list = counting_list
    assert r.ctrl.get_runtime() == "containerd"  # spec default, no list
    assert r.ctrl.get_runtime() == "containerd"
    assert "Node" not in calls, "listed-empty cluster re-listed per call"


def test_snapshot_shares_node_scans_across_states(env):
    """One pass, one node list: the 18 states' readiness checks share
    the snapshot's memo instead of each listing the fleet."""
    client = FakeClient(
        [
            {"apiVersion": "v1", "kind": "Namespace", "metadata": {"name": NS}},
            make_tpu_node("tpu-node-1"),
        ]
    )
    client.create(load_cr())
    cached = _cached(client)
    r = ClusterPolicyReconciler(cached, assets_dir=ASSETS)
    r.reconcile()
    simulate_kubelet(client)
    node_inf = cached._informers[("v1", "Node")]
    before = node_inf.read_stats()["lists"]
    assert r.reconcile().ready
    node_lists = node_inf.read_stats()["lists"] - before
    # init lists once; everything else hits the snapshot memo. Allow a
    # small constant for non-state readers, but the pass must not scale
    # list count with the 18 states.
    assert node_lists <= 3, f"{node_lists} node lists in one pass"
    # the memo demonstrably shared reads within the pass (how many
    # depends on where the pass resumed in the 18-state walk)
    assert r.ctrl.last_snapshot_stats["hits"] >= 1


def test_snapshot_lifecycle_scoped_to_pass(env):
    """begin_pass/end_pass bracket reconcile: outside a pass the
    controller has no snapshot (direct step() callers see fallback
    reads), and each pass gets a FRESH memo."""
    client = FakeClient(
        [
            {"apiVersion": "v1", "kind": "Namespace", "metadata": {"name": NS}},
            make_tpu_node("tpu-node-1"),
        ]
    )
    client.create(load_cr())
    r = ClusterPolicyReconciler(client, assets_dir=ASSETS)
    assert r.ctrl.snapshot is None
    r.reconcile()
    assert r.ctrl.snapshot is None, "snapshot leaked past end_pass"
    first = r.ctrl.last_snapshot_stats
    r.reconcile()
    assert r.ctrl.snapshot is None
    assert r.ctrl.snapshot_hits_total >= first["hits"]
