# Single-platform image builds for the host arch (reference
# native-only.mk slot). Selected with DIST=native-only; useful on CI
# runners without binfmt/qemu and for fast local iteration. Plain
# `docker build` always targets the host platform — no PLATFORMS knob.

builder:
	@true  # plain docker build needs no builder setup

define build_image
	$(DOCKER) build \
	  --build-arg VERSION=$(VERSION) --build-arg GIT_COMMIT=$(GIT_COMMIT) \
	  -f $(1) -t $(2) .
endef

define push_image
	$(DOCKER) push $(2)
endef
