{{- define "tpu-operator.name" -}}
tpu-operator
{{- end -}}

{{- define "tpu-operator.operator-image" -}}
{{ .Values.operatorDeployment.repository }}/{{ .Values.operatorDeployment.image }}:{{ .Values.operatorDeployment.version }}
{{- end -}}
