// tpu-metricsd — native metrics hostengine (the DCGM hostengine slot).
//
// The reference deploys DCGM's C++ hostengine on :5555 and points
// dcgm-exporter at it (reference controllers/object_controls.go:95-98,
// 1441-1495). This is the TPU-native equivalent: a small C++ daemon that
// owns node-local telemetry collection and serves it to in-cluster readers.
//
//   * chip presence / PCI / NUMA via the same enumeration the rest of the
//     stack uses (libtpuinfo.cpp, compiled in),
//   * generic sysfs telemetry probes per chip (best-effort reads that fail
//     silently when a file is absent),
//   * on-chip counters merged from the JAX sampler side-file: the TPU
//     runtime is single-client, so anything needing the chip itself lives
//     in the (Python/JAX) sampler, which drops a JSON file this daemon
//     embeds verbatim — the hostengine/reader split, with the chip-owning
//     process decoupled from the serving process,
//   * HTTP endpoints: /healthz, /json (full snapshot), /metrics
//     (Prometheus text),
//   * atomic drop-file publication for file-based readers (validator,
//     libtpuinfo merge path).
//
// Plain POSIX sockets; sequential accept loop (scrape traffic only).

#include <arpa/inet.h>
#include <netinet/in.h>
#include <signal.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <unistd.h>

#include <atomic>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

// C ABI from libtpuinfo.cpp (compiled into this binary).
extern "C" {
int tpuinfo_chip_count(const char* dev_root);
int tpuinfo_summary_json(const char* dev_root, char* buf, int buf_len);
}

namespace {

std::atomic<bool> g_stop{false};

void on_signal(int) { g_stop = true; }

std::string read_file(const std::string& path, size_t max = 1 << 20) {
  FILE* f = std::fopen(path.c_str(), "r");
  if (!f) return "";
  std::string out;
  char buf[4096];
  size_t n;
  while ((n = std::fread(buf, 1, sizeof(buf), f)) > 0) {
    out.append(buf, n);
    if (out.size() > max) break;
  }
  std::fclose(f);
  while (!out.empty() && (out.back() == '\n' || out.back() == ' ')) out.pop_back();
  return out;
}

// Split a JSON array of flat objects into the objects' substrings (balanced
// braces; nested objects stay inside their parent). Per-object key lookups
// below keep chip attribution correct even when a key is present on only
// some chips — a positional key scan would misalign them.
std::vector<std::string> split_objects(const std::string& json) {
  std::vector<std::string> out;
  int depth = 0;
  size_t start = 0;
  bool in_string = false;
  for (size_t i = 0; i < json.size(); ++i) {
    char ch = json[i];
    if (in_string) {
      if (ch == '\\') ++i;
      else if (ch == '"') in_string = false;
      continue;
    }
    if (ch == '"') in_string = true;
    else if (ch == '{') {
      if (depth == 0) start = i;
      ++depth;
    } else if (ch == '}') {
      if (--depth == 0) out.push_back(json.substr(start, i - start + 1));
    }
  }
  return out;
}

// `"key":<number>` lookup inside ONE flat object; nan when absent.
double find_number(const std::string& obj, const std::string& key) {
  std::string needle = "\"" + key + "\":";
  size_t pos = obj.find(needle);
  if (pos == std::string::npos) return std::nan("");
  return std::atof(obj.c_str() + pos + needle.size());
}

// The `"key":[...]` array substring of an object ("" when absent).
std::string extract_array(const std::string& json, const std::string& key) {
  std::string needle = "\"" + key + "\":";
  size_t pos = json.find(needle);
  if (pos == std::string::npos) return "";
  pos = json.find('[', pos + needle.size());
  if (pos == std::string::npos) return "";
  int depth = 0;
  bool in_string = false;
  for (size_t i = pos; i < json.size(); ++i) {
    char ch = json[i];
    if (in_string) {
      if (ch == '\\') ++i;
      else if (ch == '"') in_string = false;
      continue;
    }
    if (ch == '"') in_string = true;
    else if (ch == '[') ++depth;
    else if (ch == ']' && --depth == 0) return json.substr(pos, i - pos + 1);
  }
  return "";
}

struct Snapshot {
  std::string json;        // full /json body
  std::string prometheus;  // /metrics body
};

class Collector {
 public:
  Collector(std::string dev_root, std::string sample_file, std::string drop_file,
            double sample_max_age_s = 60.0)
      : dev_root_(std::move(dev_root)),
        sample_file_(std::move(sample_file)),
        drop_file_(std::move(drop_file)),
        sample_max_age_s_(sample_max_age_s) {}

  void collect_once() {
    std::vector<char> buf(1 << 20);
    std::string chips = "[]";
    if (tpuinfo_summary_json(dev_root_.c_str(), buf.data(), (int)buf.size()) == 0)
      chips = buf.data();
    int count = tpuinfo_chip_count(dev_root_.c_str());
    std::string sample = read_file(sample_file_);
    bool have_sample = !sample.empty() && sample.front() == '{';
    // age gate: a dead sampler must read as MISSING, not as its last
    // value forever — the side-file's own "ts" stamp decides. A sample
    // without a ts is treated as un-ageable and rejected the same way.
    double sample_age = -1;
    if (have_sample) {
      double ts = find_number(sample, "ts");
      if (std::isnan(ts)) {
        have_sample = false;
      } else {
        sample_age = (double)::time(nullptr) - ts;
        if (sample_age > sample_max_age_s_) have_sample = false;
      }
    }
    collections_++;

    std::string json = "{\"source\":\"tpu-metricsd-native\",\"ts\":" +
                       std::to_string((long)::time(nullptr)) +
                       ",\"chip_count\":" + std::to_string(count < 0 ? 0 : count) +
                       ",\"chips\":" + chips;
    if (have_sample) json += ",\"sample\":" + sample;
    json += "}";

    std::string prom;
    auto gauge = [&prom](const std::string& name, const std::string& help,
                         const std::string& labels, double v) {
      if (prom.find("# HELP " + name + " ") == std::string::npos) {
        prom += "# HELP " + name + " " + help + "\n# TYPE " + name + " gauge\n";
      }
      char num[64];
      std::snprintf(num, sizeof(num), "%.10g", v);
      prom += name + (labels.empty() ? "" : "{" + labels + "}") + " " + num + "\n";
    };
    gauge("tpu_metricsd_chips", "Visible TPU chip device nodes", "",
          count < 0 ? 0 : count);
    gauge("tpu_metricsd_collections_total", "Collection passes", "",
          (double)collections_);
    gauge("tpu_metricsd_last_collect_ts_seconds", "Last collection time", "",
          (double)::time(nullptr));
    size_t pos = 0;
    for (const std::string& chip : split_objects(chips)) {
      double idx = find_number(chip, "index");
      int chip_id = std::isnan(idx) ? (int)pos : (int)idx;
      ++pos;
      // source label = provenance (sampler / sysfs / devfs): a dashboard
      // must be able to tell a measured number from a presence fact
      std::string label =
          "chip=\"" + std::to_string(chip_id) + "\",source=\"devfs\"";
      gauge("tpu_chip_present", "Chip device node visible", label, 1);
      double numa = find_number(chip, "numa_node");
      if (!std::isnan(numa))
        gauge("tpu_chip_numa_node", "Chip NUMA affinity",
              "chip=\"" + std::to_string(chip_id) + "\",source=\"sysfs\"",
              numa);
    }
    if (sample_age >= 0)
      gauge("tpu_metricsd_sample_age_seconds",
            "Age of the sampler side-file", "", sample_age);
    if (have_sample) {
      gauge("tpu_metricsd_sample_fresh", "Sampler side-file present and fresh",
            "", 1);
      size_t si = 0;
      for (const std::string& entry : split_objects(extract_array(sample, "chips"))) {
        double idx = find_number(entry, "index");
        int chip_id = std::isnan(idx) ? (int)si : (int)idx;
        ++si;
        std::string label =
            "chip=\"" + std::to_string(chip_id) + "\",source=\"sampler\"";
        double util = find_number(entry, "tensorcore_util");
        if (!std::isnan(util))
          gauge("tpu_tensorcore_utilization_percent",
                "TensorCore utilization % (from chip-owning sampler)", label,
                util);
        double duty = find_number(entry, "duty_cycle");
        if (!std::isnan(duty))
          gauge("tpu_duty_cycle_percent",
                "TensorCore duty cycle % (from chip-owning sampler)", label,
                duty);
        double hbm = find_number(entry, "hbm_used");
        if (!std::isnan(hbm))
          gauge("tpu_hbm_used_bytes", "HBM bytes in use (from sampler)", label,
                hbm);
        double hbm_total = find_number(entry, "hbm_total");
        if (!std::isnan(hbm_total))
          gauge("tpu_hbm_total_bytes", "HBM capacity bytes (from sampler)",
                label, hbm_total);
      }
    } else {
      gauge("tpu_metricsd_sample_fresh", "Sampler side-file present and fresh",
            "", 0);
    }

    {
      std::lock_guard<std::mutex> lock(mu_);
      snap_.json = json;
      snap_.prometheus = prom;
    }
    write_drop_file(json);
  }

  Snapshot snapshot() {
    std::lock_guard<std::mutex> lock(mu_);
    return snap_;
  }

 private:
  void write_drop_file(const std::string& payload) {
    if (drop_file_.empty()) return;
    size_t slash = drop_file_.find_last_of('/');
    if (slash != std::string::npos && slash > 0) {
      std::string dir = drop_file_.substr(0, slash);  // mkdir -p, no system()
      for (size_t i = 1; i <= dir.size(); ++i) {
        if (i == dir.size() || dir[i] == '/') {
          std::string prefix = dir.substr(0, i);
          if (!prefix.empty()) ::mkdir(prefix.c_str(), 0755);
        }
      }
    }
    std::string tmp = drop_file_ + ".tmp";
    FILE* f = std::fopen(tmp.c_str(), "w");
    if (!f) return;
    std::fwrite(payload.data(), 1, payload.size(), f);
    std::fclose(f);
    std::rename(tmp.c_str(), drop_file_.c_str());
  }

  std::string dev_root_;
  std::string sample_file_;
  std::string drop_file_;
  double sample_max_age_s_;
  std::mutex mu_;
  Snapshot snap_;
  long collections_ = 0;
};

void respond(int fd, const char* status, const std::string& content_type,
             const std::string& body) {
  std::string head = "HTTP/1.1 " + std::string(status) +
                     "\r\nContent-Type: " + content_type +
                     "\r\nContent-Length: " + std::to_string(body.size()) +
                     "\r\nConnection: close\r\n\r\n";
  (void)!::write(fd, head.data(), head.size());
  (void)!::write(fd, body.data(), body.size());
}

void handle(int fd, Collector& collector) {
  char req[2048];
  ssize_t n = ::read(fd, req, sizeof(req) - 1);
  if (n <= 0) return;
  req[n] = '\0';
  char method[8] = {0}, path[256] = {0};
  std::sscanf(req, "%7s %255s", method, path);
  if (std::strcmp(method, "GET") != 0) {
    respond(fd, "405 Method Not Allowed", "text/plain", "GET only\n");
    return;
  }
  Snapshot snap = collector.snapshot();
  if (std::strcmp(path, "/healthz") == 0) {
    respond(fd, "200 OK", "text/plain", "ok\n");
  } else if (std::strcmp(path, "/metrics") == 0) {
    respond(fd, "200 OK", "text/plain; version=0.0.4", snap.prometheus);
  } else if (std::strcmp(path, "/") == 0 || std::strcmp(path, "/json") == 0) {
    respond(fd, "200 OK", "application/json", snap.json);
  } else {
    respond(fd, "404 Not Found", "text/plain", "not found\n");
  }
}

}  // namespace

int main(int argc, char** argv) {
  std::string dev_root = "/dev";
  std::string drop_file = "/run/tpu/metricsd.json";
  std::string sample_file = "/run/tpu/metricsd-sample.json";
  int port = 5555;
  double interval_s = 10.0;
  double sample_max_age_s = 60.0;
  bool once = false;

  for (int i = 1; i < argc; ++i) {
    std::string a = argv[i];
    auto next = [&]() -> const char* { return i + 1 < argc ? argv[++i] : ""; };
    if (a == "--dev-root") dev_root = next();
    else if (a == "--drop-file") drop_file = next();
    else if (a == "--sample-file") sample_file = next();
    else if (a == "--port") port = std::atoi(next());
    else if (a == "--interval") interval_s = std::atof(next());
    else if (a == "--sample-max-age") sample_max_age_s = std::atof(next());
    else if (a == "--once") once = true;
    else if (a == "--help" || a == "-h") {
      std::printf(
          "tpu-metricsd [--port N] [--dev-root D] [--drop-file F]\n"
          "             [--sample-file F] [--interval S] [--sample-max-age S] [--once]\n");
      return 0;
    }
  }

  Collector collector(dev_root, sample_file, drop_file, sample_max_age_s);
  collector.collect_once();
  if (once) {
    std::printf("%s\n", collector.snapshot().json.c_str());
    return 0;
  }

  ::signal(SIGINT, on_signal);
  ::signal(SIGTERM, on_signal);
  ::signal(SIGPIPE, SIG_IGN);

  int srv = ::socket(AF_INET, SOCK_STREAM, 0);
  if (srv < 0) { std::perror("socket"); return 1; }
  int opt = 1;
  ::setsockopt(srv, SOL_SOCKET, SO_REUSEADDR, &opt, sizeof(opt));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons((uint16_t)port);
  if (::bind(srv, (sockaddr*)&addr, sizeof(addr)) != 0) {
    std::perror("bind");
    return 1;
  }
  socklen_t len = sizeof(addr);
  ::getsockname(srv, (sockaddr*)&addr, &len);  // resolve --port 0
  if (::listen(srv, 16) != 0) { std::perror("listen"); return 1; }
  std::printf("tpu-metricsd listening on port %d (dev-root %s)\n",
              (int)ntohs(addr.sin_port), dev_root.c_str());
  std::fflush(stdout);

  std::thread loop([&] {
    while (!g_stop) {
      collector.collect_once();
      for (double waited = 0; waited < interval_s && !g_stop; waited += 0.2)
        ::usleep(200 * 1000);
    }
  });

  // accept with timeout so SIGTERM is honored promptly
  timeval tv{1, 0};
  ::setsockopt(srv, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  while (!g_stop) {
    int fd = ::accept(srv, nullptr, nullptr);
    if (fd < 0) continue;
    handle(fd, collector);
    ::close(fd);
  }
  ::close(srv);
  loop.join();
  return 0;
}
