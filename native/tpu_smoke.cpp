// tpu-smoke — the nvidia-smi-shaped probe used by the libtpu DaemonSet's
// startupProbe (assets/state-libtpu/0500_daemonset.yaml) and by hand on a
// node: prints the chip table and exits 0 when chips are visible, 2 when
// none are (the reference gates .driver-ctr-ready on `nvidia-smi`,
// assets/state-driver/0500_daemonset.yaml:132-140).

#include <cstdio>
#include <cstring>

extern "C" {
int tpuinfo_chip_count(const char* dev_root);
int tpuinfo_summary_json(const char* dev_root, char* buf, int buf_len);
int tpuinfo_metrics_json(const char* dev_root, char* buf, int buf_len);
}

int main(int argc, char** argv) {
  const char* dev_root = "/dev";
  bool json = false;
  for (int i = 1; i < argc; ++i) {
    if (std::strcmp(argv[i], "--dev-root") == 0 && i + 1 < argc) {
      dev_root = argv[++i];
    } else if (std::strcmp(argv[i], "--json") == 0) {
      json = true;
    } else if (std::strcmp(argv[i], "--help") == 0) {
      std::printf("usage: tpu-smoke [--dev-root DIR] [--json]\n");
      return 0;
    }
  }

  static char buf[16384];
  if (tpuinfo_summary_json(dev_root, buf, sizeof(buf)) != 0) {
    std::fprintf(stderr, "tpu-smoke: probe failed\n");
    return 1;
  }
  int n = tpuinfo_chip_count(dev_root);
  if (json) {
    std::printf("%s\n", buf);
  } else {
    std::printf("TPU chips visible: %d\n", n);
    std::printf("%s\n", buf);
  }
  return n > 0 ? 0 : 2;
}
