// libtpuinfo — native TPU chip probe (the NVML slot).
//
// The reference operator leans on NVML/DCGM (C/C++) inside its operand
// images for device enumeration and telemetry (SURVEY.md §2.3). This
// library is the TPU-native equivalent consumed via ctypes by the device
// plugin, feature discovery, metrics exporter and validator:
//
//   * chip enumeration from devfs (/dev/accel*, /dev/vfio/*),
//   * PCI identity + NUMA affinity from sysfs (/sys/class/accel),
//   * telemetry merge: the metrics daemon (which owns the chip through
//     libtpu) drops counters at /run/tpu/metricsd.json; this library joins
//     them with device presence — the DCGM hostengine/reader split.
//
// C ABI only; no exceptions across the boundary; caller provides buffers.

#include <dirent.h>
#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cerrno>

#include <algorithm>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

struct Chip {
  int index;
  std::string path;       // /dev/accelN or /dev/vfio/G
  std::string pci;        // 0000:00:04.0 ("" when unknown)
  std::string vendor;     // 0x1ae0 ("" when unknown)
  int numa = -1;
};

bool starts_with(const char* s, const char* prefix) {
  return std::strncmp(s, prefix, std::strlen(prefix)) == 0;
}

std::string read_trimmed(const std::string& path) {
  FILE* f = std::fopen(path.c_str(), "r");
  if (!f) return "";
  char buf[256];
  size_t n = std::fread(buf, 1, sizeof(buf) - 1, f);
  std::fclose(f);
  buf[n] = '\0';
  std::string out(buf);
  while (!out.empty() && (out.back() == '\n' || out.back() == ' ')) out.pop_back();
  return out;
}

std::string resolve_pci(const std::string& dev_name) {
  // /sys/class/accel/accelN/device -> ../../../0000:00:04.0
  std::string link = "/sys/class/accel/" + dev_name + "/device";
  char target[512];
  ssize_t n = ::readlink(link.c_str(), target, sizeof(target) - 1);
  if (n <= 0) return "";
  target[n] = '\0';
  std::string t(target);
  size_t pos = t.find_last_of('/');
  return pos == std::string::npos ? t : t.substr(pos + 1);
}

// Stable-id assignment shared by the accel and vfio branches: parsed
// names keep their numeric id; names that don't parse get ids past the
// max parsed one so a fallback can never collide with (and shadow) a
// real chip id.
template <typename ParseFn>
std::vector<int> stable_ids(const std::vector<std::string>& names, ParseFn parse) {
  int max_parsed = -1;
  std::vector<int> ids(names.size(), -1);
  for (size_t i = 0; i < names.size(); ++i) {
    int p = parse(names[i]);
    if (p >= 0) {
      ids[i] = p;
      if (p > max_parsed) max_parsed = p;
    }
  }
  int next = max_parsed;
  for (auto& v : ids)
    if (v < 0) v = ++next;
  return ids;
}

std::vector<Chip> enumerate_chips(const char* dev_root) {
  std::vector<Chip> chips;
  std::string root = dev_root && *dev_root ? dev_root : "/dev";

  DIR* d = ::opendir(root.c_str());
  if (d) {
    std::vector<std::string> names;
    while (dirent* e = ::readdir(d)) {
      if (starts_with(e->d_name, "accel") && std::strcmp(e->d_name, "accel") != 0)
        names.push_back(e->d_name);
    }
    ::closedir(d);
    std::sort(names.begin(), names.end());
    // stable id: the accelN suffix, NOT the enumeration position —
    // Allocate maps id N to /dev/accelN, and positional ids shift when
    // a node disappears (health/mounts would hit the wrong chip).
    // Strict whole-name parse: "accel0foo" must NOT claim id 0.
    auto ids = stable_ids(names, [](const std::string& n) {
      int parsed = -1, len = -1;
      if (std::sscanf(n.c_str(), "accel%d%n", &parsed, &len) == 1 &&
          len == (int)n.size() && parsed >= 0)
        return parsed;
      return -1;
    });
    for (size_t i = 0; i < names.size(); ++i) {
      const auto& name = names[i];
      Chip c;
      c.index = ids[i];
      c.path = root + "/" + name;
      c.pci = resolve_pci(name);
      if (!c.pci.empty()) {
        std::string sys = "/sys/class/accel/" + name + "/device/";
        c.vendor = read_trimmed(sys + "vendor");
        std::string numa = read_trimmed(sys + "numa_node");
        if (!numa.empty()) c.numa = std::atoi(numa.c_str());
      }
      chips.push_back(std::move(c));
    }
    std::sort(chips.begin(), chips.end(),
              [](const Chip& a, const Chip& b) { return a.index < b.index; });
  }
  if (!chips.empty()) return chips;

  // VM-passthrough hosts expose vfio groups instead of accel nodes.
  std::string vfio = root + "/vfio";
  d = ::opendir(vfio.c_str());
  if (d) {
    std::vector<std::string> names;
    while (dirent* e = ::readdir(d)) {
      if (std::strcmp(e->d_name, ".") == 0 || std::strcmp(e->d_name, "..") == 0 ||
          std::strcmp(e->d_name, "vfio") == 0)
        continue;
      names.push_back(e->d_name);
    }
    ::closedir(d);
    std::sort(names.begin(), names.end());
    // vfio group names are numeric: use them as stable ids; strict
    // whole-name parse ("noiommu-0" must not claim id 0)
    auto ids = stable_ids(names, [](const std::string& n) {
      char* end = nullptr;
      long p = std::strtol(n.c_str(), &end, 10);
      return (end && *end == '\0' && end != n.c_str() && p >= 0) ? (int)p : -1;
    });
    for (size_t i = 0; i < names.size(); ++i) {
      Chip c;
      c.index = ids[i];
      c.path = vfio + "/" + names[i];
      chips.push_back(std::move(c));
    }
    std::sort(chips.begin(), chips.end(),
              [](const Chip& a, const Chip& b) { return a.index < b.index; });
  }
  return chips;
}

void json_escape_into(std::string& out, const std::string& s) {
  for (char ch : s) {
    if (ch == '"' || ch == '\\') { out += '\\'; out += ch; }
    else if (ch == '\n') out += "\\n";
    else out += ch;
  }
}

int emit(const std::string& json, char* buf, int buf_len) {
  if (!buf || buf_len <= 0) return -2;
  if ((int)json.size() + 1 > buf_len) return -3;
  std::memcpy(buf, json.c_str(), json.size() + 1);
  return 0;
}

}  // namespace

extern "C" {

// Number of visible TPU chips; -1 on error.
int tpuinfo_chip_count(const char* dev_root) {
  return (int)enumerate_chips(dev_root).size();
}

// Per-chip JSON array: [{"index":0,"path":"/dev/accel0","pci_address":...,
// "vendor":...,"numa_node":...}, ...]. Returns 0, or <0 on buffer error.
int tpuinfo_summary_json(const char* dev_root, char* buf, int buf_len) {
  auto chips = enumerate_chips(dev_root);
  std::string out = "[";
  for (size_t i = 0; i < chips.size(); ++i) {
    const Chip& c = chips[i];
    if (i) out += ",";
    out += "{\"index\":" + std::to_string(c.index) + ",\"path\":\"";
    json_escape_into(out, c.path);
    out += "\"";
    if (!c.pci.empty()) {
      out += ",\"pci_address\":\"";
      json_escape_into(out, c.pci);
      out += "\"";
    }
    if (!c.vendor.empty()) {
      out += ",\"vendor\":\"";
      json_escape_into(out, c.vendor);
      out += "\"";
    }
    if (c.numa >= 0) out += ",\"numa_node\":" + std::to_string(c.numa);
    out += "}";
  }
  out += "]";
  return emit(out, buf, buf_len);
}

// Telemetry JSON: {"source":...,"chips":[{"index":N,"present":1,...}]}.
// Joins devfs presence with the metrics daemon's drop-file when present
// (the daemon owns the chip through libtpu; we never open it here).
int tpuinfo_metrics_json(const char* dev_root, char* buf, int buf_len) {
  auto chips = enumerate_chips(dev_root);

  std::string dropfile = read_trimmed("/run/tpu/metricsd.json");
  if (!dropfile.empty() && dropfile.front() == '{') {
    return emit(dropfile, buf, buf_len);
  }

  std::string out = "{\"source\":\"libtpuinfo\",\"chips\":[";
  for (size_t i = 0; i < chips.size(); ++i) {
    if (i) out += ",";
    out += "{\"index\":" + std::to_string(chips[i].index) + ",\"present\":1";
    if (chips[i].numa >= 0)
      out += ",\"numa_node\":" + std::to_string(chips[i].numa);
    out += "}";
  }
  out += "]}";
  return emit(out, buf, buf_len);
}

// Liveness probe: actually open+close the device node (non-blocking,
// read-only — never disturbs the libtpu client that owns the chip).
// Existence is not liveness: a wedged chip keeps its device node but
// fails the open (reference re-runs `nvidia-smi`, validator/metrics.go:
// 237-250). Takes the device PATH (not a positional index: enumeration
// order shifts when a node disappears, and health must never be
// attributed to the wrong chip). Returns 0 healthy, 1 busy-but-alive
// (EBUSY: a client owns it, which proves the driver path works;
// EPERM/EACCES: the device cgroup denied US, which says nothing about
// the chip), -errno on failure (ENOENT/ENXIO/EIO => gone or wedged).
int tpuinfo_device_probe_path(const char* path) {
  if (!path || !*path) return -EINVAL;
  // VFIO groups allow exactly ONE open file: never open() them — a
  // transient probe open could race the VM launcher's one-shot open of
  // its allocated group and fail the VM start. stat-only for those
  // (centralized here so every caller gets the rule).
  if (std::strstr(path, "/vfio/") != nullptr) {
    struct stat st;
    return ::stat(path, &st) == 0 ? 0 : -errno;
  }
  int fd = ::open(path, O_RDONLY | O_NONBLOCK | O_CLOEXEC);
  if (fd >= 0) {
    ::close(fd);
    return 0;
  }
  if (errno == EBUSY || errno == EPERM || errno == EACCES) return 1;
  return -errno;
}

}  // extern "C"
