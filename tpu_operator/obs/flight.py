"""Flight recorder: bounded rings of recent structured events + spans,
dumped on failure triggers for post-mortem causal timelines.

The event ring is ALWAYS on (an append to a bounded deque — there is
nothing to enable), fed by the low-rate diagnostic writes the system
already makes: per-pass label fan-outs, disruption-budget admissions
and releases, circuit-breaker trips, watch re-lists, remediation /
repartition FSM transitions, chaos injections, invariant violations.
The span ring fills only while tracing (``obs/trace.py``) is enabled.

``dump(reason)`` freezes both rings into a timestamped JSON file under
``TPU_OPERATOR_FLIGHT_DIR`` (default: ``<tmp>/tpu-operator-flight``)
and notifies the optional ``event_sink`` (the reconciler wires a
warning Event through it). Dumps are rate-limited per reason
(``TPU_OPERATOR_FLIGHT_MIN_INTERVAL_S``, default 30 s) so a flapping
trigger cannot turn the recorder into a disk-filling loop.

Triggers wired elsewhere:

* stall watchdog trip        — ``manager.Manager`` monitor thread;
* a state going Degraded     — ``clusterpolicy_controller``;
* chaos-soak invariant flag  — ``chaos.soak.InvariantChecker``.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

log = logging.getLogger("tpu-operator.flight")

DEFAULT_EVENT_CAPACITY = 4096
DEFAULT_SPAN_CAPACITY = 2048


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


class FlightRecorder:
    def __init__(
        self,
        event_capacity: int = DEFAULT_EVENT_CAPACITY,
        span_capacity: int = DEFAULT_SPAN_CAPACITY,
    ):
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=max(16, event_capacity))
        self._spans: deque = deque(maxlen=max(16, span_capacity))
        self.events_total = 0
        self.dumps_total = 0
        self.dump_errors = 0
        self.last_dump_path: Optional[str] = None
        # recent dump paths (bounded): the soak report lists them
        self.dump_paths: deque = deque(maxlen=32)
        self._last_dump_by_reason: Dict[str, float] = {}
        self.min_interval_s = _env_float(
            "TPU_OPERATOR_FLIGHT_MIN_INTERVAL_S", 30.0
        )
        self.dir = os.environ.get("TPU_OPERATOR_FLIGHT_DIR") or os.path.join(
            tempfile.gettempdir(), "tpu-operator-flight"
        )
        # optional notifier called as (reason, detail, path) after a
        # dump lands — the reconciler posts a warning Event through it;
        # a broken sink must never break the dump itself
        self.event_sink: Optional[Callable[[str, str, str], None]] = None

    # ------------------------------------------------------------------
    def record(self, kind: str, /, **fields: Any) -> None:
        """Append one structured event. Cheap enough for every budget
        admission / FSM transition / breaker trip; NOT meant for
        per-request traffic (that is the span ring's job). The event
        kind is positional-only and always wins over a same-named
        field — a caller cannot corrupt the taxonomy."""
        rec = dict(fields)
        rec["t"] = round(time.time(), 3)
        rec["kind"] = kind
        with self._lock:
            self._events.append(rec)
            self.events_total += 1

    def add_span(self, span_rec: Dict[str, Any]) -> None:
        """Sink for the tracer's completed spans (obs/trace.py)."""
        with self._lock:
            self._spans.append(span_rec)

    def snapshot(self) -> Dict[str, List[Dict[str, Any]]]:
        with self._lock:
            return {
                "events": list(self._events),
                "spans": list(self._spans),
            }

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._spans.clear()
            self._last_dump_by_reason.clear()

    # ------------------------------------------------------------------
    def dump(
        self,
        reason: str,
        detail: str = "",
        extra: Optional[Dict[str, Any]] = None,
    ) -> Optional[str]:
        """Freeze the rings to a timestamped JSON file. Returns the
        path, or None when rate-limited / failed. Never raises: the
        recorder fires from failure paths that must stay on their feet."""
        now = time.monotonic()
        with self._lock:
            last = self._last_dump_by_reason.get(reason)
            if last is not None and now - last < self.min_interval_s:
                return None
            self._last_dump_by_reason[reason] = now
        try:
            os.makedirs(self.dir, exist_ok=True)
            stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
            safe = "".join(
                ch if ch.isalnum() or ch in "-_." else "-" for ch in reason
            )[:80]
            path = os.path.join(
                self.dir, f"flight-{stamp}-{safe}-{os.getpid()}.json"
            )
            payload = {
                "reason": reason,
                "detail": detail,
                "ts": time.time(),
                "pid": os.getpid(),
            }
            if extra:
                payload["extra"] = extra
            payload.update(self.snapshot())
            tmp = f"{path}.tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f, default=str)
            os.replace(tmp, path)
        except Exception:
            with self._lock:
                self.dump_errors += 1
            log.exception("flight-recorder dump failed (%s)", reason)
            return None
        with self._lock:
            self.dumps_total += 1
            self.last_dump_path = path
            self.dump_paths.append(path)
        log.warning(
            "flight recorder dumped (%s%s): %s",
            reason,
            f" — {detail}" if detail else "",
            path,
        )
        sink = self.event_sink
        if sink is not None:
            try:
                sink(reason, detail, path)
            except Exception:
                log.debug("flight dump event sink failed", exc_info=True)
        return path

    # ------------------------------------------------------------------
    def dump_paths_snapshot(self) -> List[str]:
        """Locked copy of the recent dump paths — callers must never
        iterate the live ring while dump() may append from another
        thread (deque iteration raises on concurrent mutation)."""
        with self._lock:
            return list(self.dump_paths)

    def stats(self) -> Dict[str, Any]:
        """/debug/vars "flight" payload."""
        with self._lock:
            return {
                "events_buffered": len(self._events),
                "spans_buffered": len(self._spans),
                "events_total": self.events_total,
                "dumps_total": self.dumps_total,
                "dump_errors": self.dump_errors,
                "last_dump_path": self.last_dump_path,
                "dir": self.dir,
                "min_interval_s": self.min_interval_s,
            }


RECORDER = FlightRecorder()


def record(kind: str, /, **fields: Any) -> None:
    RECORDER.record(kind, **fields)


def dump(reason: str, detail: str = "", extra=None) -> Optional[str]:
    return RECORDER.dump(reason, detail, extra)


# completed spans flow into the post-mortem ring
from tpu_operator.obs import trace as _trace  # noqa: E402

_trace.span_sink = RECORDER.add_span
