"""One pruned-on-liveness log-once registry.

Three ad-hoc copies of the same idea grew independently — the no-TPU
DaemonSet skip set (``state_manager.no_tpu_skip_logged``), remediation's
``_logged`` (node, reason) pairs and repartition's slice log-once — each
with its own pruning bug class (unbounded growth under unique-name
churn, a rejoin inheriting the old suppression). ``LogOnce`` is the one
implementation:

* ``log(logger, key, msg, *args)`` — emit at INFO the first time ``key``
  is seen, DEBUG thereafter (the condition is still visible at debug
  level without logspamming steady state);
* ``clear(key)`` / ``discard(key)`` — the condition cleared: the next
  occurrence logs again (once per stretch, not once per process);
* ``prune(live)`` — retire keys whose subject left the world; a tuple
  key's subject is its first element, a plain key is its own subject.
  This is the liveness bound: lifecycle churn (preemption waves,
  unique join names) can never grow the registry past the live fleet;
* set-compatible surface (``in``, ``add``, ``clear()``, ``len``) so the
  registries it replaced keep their call sites and tests.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Hashable, Iterable, Optional, Set


class LogOnce:
    def __init__(self) -> None:
        self._seen: Set[Hashable] = set()
        self._lock = threading.Lock()

    # ------------------------------------------------------------------
    def log(
        self,
        logger: logging.Logger,
        key: Hashable,
        msg: str,
        *args: Any,
        level: int = logging.INFO,
    ) -> bool:
        """Log ``msg % args`` at ``level`` the first time ``key`` is
        seen (DEBUG on repeats). Returns True when the first-time line
        was emitted."""
        with self._lock:
            first = key not in self._seen
            if first:
                self._seen.add(key)
        logger.log(level if first else logging.DEBUG, msg, *args)
        return first

    # ------------------------------------------------------------------
    # set-compatible surface
    # ------------------------------------------------------------------
    def add(self, key: Hashable) -> None:
        with self._lock:
            self._seen.add(key)

    def discard(self, key: Hashable) -> None:
        with self._lock:
            self._seen.discard(key)

    def clear(self, key: Optional[Hashable] = None) -> None:
        """``clear()`` forgets everything (a transition boundary, e.g.
        TPU nodes appearing); ``clear(key)`` forgets one key."""
        with self._lock:
            if key is None:
                self._seen.clear()
            else:
                self._seen.discard(key)

    def discard_subject(self, subject: Hashable) -> int:
        """Retire every key whose subject IS ``subject`` (the inverse of
        ``prune``'s liveness sweep — event-speed cleanup when one
        subject leaves the world, e.g. a deleted node's remediation
        entries). Returns how many were dropped."""
        with self._lock:
            before = len(self._seen)
            self._seen = {
                k
                for k in self._seen
                if (k[0] if isinstance(k, tuple) and k else k) != subject
            }
            return before - len(self._seen)

    def prune(self, live: Iterable[Hashable]) -> int:
        """Retire keys whose subject is not in ``live``; returns how
        many were dropped. A tuple key's subject is ``key[0]`` (the
        (name, reason) convention); any other key is its own subject."""
        live_set = set(live)
        with self._lock:
            before = len(self._seen)
            self._seen = {
                k
                for k in self._seen
                if (k[0] if isinstance(k, tuple) and k else k) in live_set
            }
            return before - len(self._seen)

    def __contains__(self, key: Hashable) -> bool:
        with self._lock:
            return key in self._seen

    def __len__(self) -> int:
        with self._lock:
            return len(self._seen)
