"""Low-overhead reconcile tracing.

A span is ``(name, attrs, start/end monotonic ns, span id, parent id,
thread)``. Context propagation is a thread-local stack: a span opened
while another is live on the same thread becomes its child, and the
parent accumulates the child's wall time so the exporter can report
SELF time per layer (the layer is the span name's prefix before the
first ``.`` — ``pass.reconcile`` → layer ``pass``).

Cost model (the 50 ms steady-pass bench gate rides on this):

* **disabled** (the default): ``span()`` is one attribute load, one
  branch and the return of a shared no-op handle — no allocation, no
  lock, no clock read;
* **enabled**: two ``monotonic_ns`` reads, one small dict, one
  lock-guarded ring append per span. Spans are placed at pass/state/
  request granularity, never per node, so a steady 1000-node pass
  carries ~30 spans (~60 µs).

Export is Chrome trace-event JSON (``{"traceEvents": [...]}``), loadable
in Perfetto / ``chrome://tracing``. Completed spans also feed the flight
recorder's span ring (``obs/flight.py``) so a post-mortem dump carries
the recent causal timeline.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

_TL = threading.local()

# installed by obs/flight.py at import: every finished span record is
# offered to the flight recorder's bounded span ring
span_sink: Optional[Callable[[Dict[str, Any]], None]] = None


def _stack() -> List["_SpanHandle"]:
    st = getattr(_TL, "stack", None)
    if st is None:
        st = _TL.stack = []
    return st


class _NoopSpan:
    """Shared disabled-mode handle: every operation is a no-op."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, key: str, value: Any) -> None:
        pass


_NOOP = _NoopSpan()
NOOP = _NOOP  # public alias for callers threading a handle through


class _SpanHandle:
    __slots__ = (
        "tracer",
        "name",
        "attrs",
        "t0_ns",
        "span_id",
        "parent_id",
        "child_ns",
    )

    def __init__(self, tracer: "Tracer", name: str, attrs: Dict[str, Any]):
        self.tracer = tracer
        self.name = name
        self.attrs = attrs
        self.child_ns = 0

    def set(self, key: str, value: Any) -> None:
        """Attach an attribute discovered mid-span (e.g. retry count)."""
        self.attrs[key] = value

    def __enter__(self):
        stack = _stack()
        self.parent_id = stack[-1].span_id if stack else 0
        self.span_id = self.tracer._next_id()
        stack.append(self)
        self.t0_ns = time.monotonic_ns()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.monotonic_ns()
        stack = _stack()
        # tolerate a foreign pop (a handle leaked across threads/generators)
        if stack and stack[-1] is self:
            stack.pop()
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        dur = t1 - self.t0_ns
        if stack:
            stack[-1].child_ns += dur
        self.tracer._finish(self, t1, dur)
        return False


class Tracer:
    """Process-global span collector. ``enabled`` is the ONE branch the
    disabled fast path pays."""

    def __init__(self, capacity: Optional[int] = None):
        self.enabled = False
        if capacity is None:
            try:
                capacity = int(os.environ.get("TRACE_BUFFER_SPANS", "20000"))
            except ValueError:
                capacity = 20000
        self.capacity = max(64, capacity)
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=self.capacity)
        self._id = 0
        # monotonic base for trace-file timestamps (set on enable so a
        # long-lived process's export starts near zero)
        self._base_ns = time.monotonic_ns()
        self.spans_total = 0
        # cumulative per-layer accumulators: layer -> [count, total_ns,
        # self_ns]; mark_pass() diffs these into the last-pass summary
        self._layers: Dict[str, List[int]] = {}
        self._pass_mark: Dict[str, List[int]] = {}
        self.last_pass: Dict[str, Dict[str, float]] = {}

    # ------------------------------------------------------------------
    def enable(self) -> None:
        with self._lock:
            # re-base only while the buffer is empty: spans surviving a
            # disable/enable cycle (fleet_converge's overhead rounds)
            # must keep one common timebase or the export time-warps
            if not self._spans:
                self._base_ns = time.monotonic_ns()
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def reset(self) -> None:
        with self._lock:
            self._base_ns = time.monotonic_ns()
            self._spans.clear()
            self._layers = {}
            self._pass_mark = {}
            self.last_pass = {}
            self.spans_total = 0

    def _next_id(self) -> int:
        # races only produce duplicate display ids, never corruption; a
        # lock here would put contention on every span open
        self._id += 1
        return self._id

    def _finish(self, handle: _SpanHandle, t1_ns: int, dur_ns: int) -> None:
        layer = handle.name.split(".", 1)[0]
        rec = {
            "name": handle.name,
            "cat": layer,
            "ph": "X",
            "ts": (handle.t0_ns - self._base_ns) // 1000,
            "dur": max(0, dur_ns // 1000),
            "pid": 1,
            "tid": threading.get_ident() & 0xFFFF,
            "id": handle.span_id,
            "args": handle.attrs,
        }
        if handle.parent_id:
            rec["args"]["parent"] = handle.parent_id
        self_ns = max(0, dur_ns - handle.child_ns)
        with self._lock:
            self._spans.append(rec)
            self.spans_total += 1
            acc = self._layers.get(layer)
            if acc is None:
                acc = self._layers[layer] = [0, 0, 0]
            acc[0] += 1
            acc[1] += dur_ns
            acc[2] += self_ns
        sink = span_sink
        if sink is not None:
            try:
                sink(rec)
            except Exception:
                pass

    def _instant(self, name: str, attrs: Dict[str, Any]) -> None:
        rec = {
            "name": name,
            "cat": name.split(".", 1)[0],
            "ph": "i",
            "s": "t",
            "ts": (time.monotonic_ns() - self._base_ns) // 1000,
            "pid": 1,
            "tid": threading.get_ident() & 0xFFFF,
            "args": attrs,
        }
        with self._lock:
            self._spans.append(rec)
            self.spans_total += 1

    # ------------------------------------------------------------------
    def mark_pass(self) -> Dict[str, Dict[str, float]]:
        """Seal a reconcile pass: the per-layer (count, total, self-time)
        delta since the previous mark becomes ``last_pass`` — the
        summary /debug/vars "trace" and ``fleet_converge`` report."""
        with self._lock:
            out: Dict[str, Dict[str, float]] = {}
            for layer, acc in self._layers.items():
                prev = self._pass_mark.get(layer, (0, 0, 0))
                count = acc[0] - prev[0]
                if count <= 0:
                    continue
                out[layer] = {
                    "spans": count,
                    "total_ms": round((acc[1] - prev[1]) / 1e6, 3),
                    "self_ms": round((acc[2] - prev[2]) / 1e6, 3),
                }
            self._pass_mark = {k: list(v) for k, v in self._layers.items()}
            self.last_pass = out
            return out

    def stats(self) -> Dict[str, Any]:
        """/debug/vars "trace" payload."""
        with self._lock:
            layers = {
                layer: {
                    "spans": acc[0],
                    "total_ms": round(acc[1] / 1e6, 3),
                    "self_ms": round(acc[2] / 1e6, 3),
                }
                for layer, acc in sorted(self._layers.items())
            }
            return {
                "enabled": self.enabled,
                "spans_total": self.spans_total,
                "buffered": len(self._spans),
                "capacity": self.capacity,
                "last_pass": dict(self.last_pass),
                "layers": layers,
            }

    # ------------------------------------------------------------------
    def export_chrome(self, path: str) -> int:
        """Write the buffered spans as Chrome trace-event JSON (one
        object with a ``traceEvents`` array — the format Perfetto and
        chrome://tracing load directly). Returns the span count."""
        with self._lock:
            events = list(self._spans)
        payload = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"producer": "tpu-operator obs/trace.py"},
        }
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
        return len(events)


TRACER = Tracer()


def span(_span_name: str, **attrs: Any):
    """Open a span (context manager). Disabled tracing returns the
    shared no-op handle: one branch, zero allocation beyond the
    caller's kwargs. The positional parameter is underscored so
    ``name=``/``kind=`` stay usable as attribute keys."""
    t = TRACER
    if not t.enabled:
        return _NOOP
    return _SpanHandle(t, _span_name, attrs)


def instant(_span_name: str, **attrs: Any) -> None:
    """Record a zero-duration marker (Chrome instant event)."""
    t = TRACER
    if not t.enabled:
        return
    t._instant(_span_name, attrs)


def enable() -> None:
    TRACER.enable()


def disable() -> None:
    TRACER.disable()
