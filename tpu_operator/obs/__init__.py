"""Observability subsystem: reconcile tracing + flight recorder.

Two always-importable, dependency-free primitives threaded through the
hot path:

* ``trace`` — context-propagated spans (``trace.span("layer.op", k=v)``)
  with a disabled-mode cost of ONE branch, a bounded completed-span
  ring, Chrome trace-event export (Perfetto-loadable) and a per-pass
  self-time-by-layer summary for ``/debug/vars "trace"``;
* ``flight`` — an always-on bounded ring of structured events (label
  writes, budget admissions, breaker trips, watch re-lists, FSM
  transitions) plus the recent spans, dumped to a timestamped JSON
  file when the stall watchdog trips, a state goes Degraded, or the
  chaos-soak invariant checker flags a violation;
* ``logonce`` — the one pruned-on-liveness log-once registry shared by
  remediation, repartition and the no-TPU DaemonSet skip.

This package imports NOTHING from the rest of ``tpu_operator`` so every
layer (``kube/``, ``controllers/``, ``schedsim/``, ``chaos/``) may
instrument through it without cycles.
"""

from tpu_operator.obs import flight, trace  # noqa: F401  (wires span sink)
from tpu_operator.obs.logonce import LogOnce  # noqa: F401
from tpu_operator.obs.trace import instant, span  # noqa: F401
