"""tpu-operator: a TPU-native Kubernetes operator.

Provisions the full TPU software stack on cluster nodes through a single
cluster-scoped ``ClusterPolicy`` CRD reconciled by an ordered state machine:
libtpu installation, TPU runtime/CDI wiring, a device plugin advertising
``google.com/tpu``, TPU feature discovery (chip/ICI topology labels), a slice
partition manager, a libtpu metrics exporter, node validation whose
end-to-end proof is a JAX/XLA matmul, and a cordon/drain rolling upgrade
engine.

Architecture mirrors the NVIDIA GPU Operator (reference: ``main.go``,
``controllers/``, ``validator/``, ``assets/``) but is built TPU-native:
userspace libtpu instead of kernel driver builds, CDI instead of runtime
config rewriting, JAX instead of CUDA workloads, and ICI topology instead of
MOFED/GPUDirect fabric enablement.
"""

__version__ = "0.1.0"
