"""ctypes bindings for ``libtpuinfo.so`` — the native chip probe.

The NVML/DCGM slot (SURVEY.md §2.3): device enumeration, PCI topology and
utilization counters are native C++ (``native/libtpuinfo.cpp``), loaded here
via ctypes. Every call degrades gracefully: when the library is missing
(pure-Python deployments, CI) a Python sysfs/devfs fallback provides the
same data shape, so callers never branch.
"""

from __future__ import annotations

import ctypes
import glob
import json
import os
from typing import List, Optional

_LIB_NAMES = ("libtpuinfo.so",)
_SEARCH_DIRS = (
    os.path.join(os.path.dirname(__file__), "..", "..", "native", "out"),
    "/usr/local/lib",
    "/usr/lib",
)

_lib = None
_loaded = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _loaded
    if _loaded:
        return _lib
    _loaded = True
    candidates = [os.environ.get("LIBTPUINFO_PATH", "")]
    for d in _SEARCH_DIRS:
        for n in _LIB_NAMES:
            candidates.append(os.path.join(d, n))
    for path in candidates:
        if path and os.path.exists(path):
            try:
                lib = ctypes.CDLL(path)
                lib.tpuinfo_chip_count.restype = ctypes.c_int
                lib.tpuinfo_chip_count.argtypes = [ctypes.c_char_p]
                lib.tpuinfo_summary_json.restype = ctypes.c_int
                lib.tpuinfo_summary_json.argtypes = [
                    ctypes.c_char_p,
                    ctypes.c_char_p,
                    ctypes.c_int,
                ]
                lib.tpuinfo_metrics_json.restype = ctypes.c_int
                lib.tpuinfo_metrics_json.argtypes = [
                    ctypes.c_char_p,
                    ctypes.c_char_p,
                    ctypes.c_int,
                ]
                _lib = lib
                return _lib
            except OSError:
                continue
    return None


def native_available() -> bool:
    return _load() is not None


def chip_count(dev_root: str = "/dev") -> int:
    lib = _load()
    if lib is not None:
        n = lib.tpuinfo_chip_count(dev_root.encode())
        if n >= 0:
            return n
    return len(_py_devices(dev_root))


def chip_summary(dev_root: str = "/dev") -> List[dict]:
    """Per-chip dicts: {index, path, pci_address?, numa_node?, vendor?}."""
    lib = _load()
    if lib is not None:
        buf = ctypes.create_string_buffer(16384)
        rc = lib.tpuinfo_summary_json(dev_root.encode(), buf, len(buf))
        if rc == 0:
            try:
                return json.loads(buf.value.decode())
            except json.JSONDecodeError:
                pass
    return [
        {"index": i, "path": p, **_py_pci_info(p)}
        for i, p in enumerate(_py_devices(dev_root))
    ]


def metrics(dev_root: str = "/dev") -> dict:
    """Utilization counters; native gives real values, fallback gives
    presence-only (the exporter labels the source)."""
    lib = _load()
    if lib is not None:
        buf = ctypes.create_string_buffer(16384)
        rc = lib.tpuinfo_metrics_json(dev_root.encode(), buf, len(buf))
        if rc == 0:
            try:
                return json.loads(buf.value.decode())
            except json.JSONDecodeError:
                pass
    devs = _py_devices(dev_root)
    return {
        "source": "fallback",
        "chips": [{"index": i, "present": 1} for i in range(len(devs))],
    }


# ---------------------------------------------------------------------------
# pure-Python fallbacks
# ---------------------------------------------------------------------------


def _py_devices(dev_root: str) -> List[str]:
    accel = sorted(glob.glob(os.path.join(dev_root, "accel*")))
    if accel:
        return accel
    return [
        p
        for p in sorted(glob.glob(os.path.join(dev_root, "vfio", "*")))
        if os.path.basename(p) != "vfio"
    ]


def _py_pci_info(dev_path: str) -> dict:
    name = os.path.basename(dev_path)
    sys_dev = f"/sys/class/accel/{name}/device"
    out = {}
    try:
        target = os.readlink(sys_dev)
        out["pci_address"] = os.path.basename(target)
    except OSError:
        return out
    try:
        with open(os.path.join(sys_dev, "numa_node")) as f:
            out["numa_node"] = int(f.read().strip())
    except OSError:
        pass
    try:
        with open(os.path.join(sys_dev, "vendor")) as f:
            out["vendor"] = f.read().strip()
    except OSError:
        pass
    return out
