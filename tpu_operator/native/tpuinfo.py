"""ctypes bindings for ``libtpuinfo.so`` — the native chip probe.

The NVML/DCGM slot (SURVEY.md §2.3): device enumeration, PCI topology and
utilization counters are native C++ (``native/libtpuinfo.cpp``), loaded here
via ctypes. Every call degrades gracefully: when the library is missing
(pure-Python deployments, CI) a Python sysfs/devfs fallback provides the
same data shape, so callers never branch.
"""

from __future__ import annotations

import ctypes
import glob
import json
import os
from typing import List, Optional

_LIB_NAMES = ("libtpuinfo.so",)
_SEARCH_DIRS = (
    os.path.join(os.path.dirname(__file__), "..", "..", "native", "out"),
    "/usr/local/lib",
    "/usr/lib",
)

_lib = None
_loaded = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _loaded
    if _loaded:
        return _lib
    _loaded = True
    candidates = [os.environ.get("LIBTPUINFO_PATH", "")]
    for d in _SEARCH_DIRS:
        for n in _LIB_NAMES:
            candidates.append(os.path.join(d, n))
    for path in candidates:
        if path and os.path.exists(path):
            try:
                lib = ctypes.CDLL(path)
                lib.tpuinfo_chip_count.restype = ctypes.c_int
                lib.tpuinfo_chip_count.argtypes = [ctypes.c_char_p]
                lib.tpuinfo_summary_json.restype = ctypes.c_int
                lib.tpuinfo_summary_json.argtypes = [
                    ctypes.c_char_p,
                    ctypes.c_char_p,
                    ctypes.c_int,
                ]
                lib.tpuinfo_metrics_json.restype = ctypes.c_int
                lib.tpuinfo_metrics_json.argtypes = [
                    ctypes.c_char_p,
                    ctypes.c_char_p,
                    ctypes.c_int,
                ]
                try:
                    # added after v0.1: older .so builds lack the symbol
                    lib.tpuinfo_device_probe_path.restype = ctypes.c_int
                    lib.tpuinfo_device_probe_path.argtypes = [ctypes.c_char_p]
                except AttributeError:
                    pass
                _lib = lib
                return _lib
            except OSError:
                continue
    return None


def native_available() -> bool:
    return _load() is not None


def chip_count(dev_root: str = "/dev") -> int:
    lib = _load()
    if lib is not None:
        n = lib.tpuinfo_chip_count(dev_root.encode())
        if n >= 0:
            return n
    return len(_py_devices(dev_root))


def chip_summary(dev_root: str = "/dev") -> List[dict]:
    """Per-chip dicts: {index, path, pci_address?, numa_node?, vendor?}."""
    lib = _load()
    if lib is not None:
        buf = ctypes.create_string_buffer(16384)
        rc = lib.tpuinfo_summary_json(dev_root.encode(), buf, len(buf))
        if rc == 0:
            try:
                return json.loads(buf.value.decode())
            except json.JSONDecodeError:
                pass
    devs = _py_devices(dev_root)
    return sorted(
        (
            {"index": idx, "path": p, **_py_pci_info(p)}
            for idx, p in zip(_py_stable_indices(devs), devs)
        ),
        key=lambda c: c["index"],
    )


def metrics(dev_root: str = "/dev") -> dict:
    """Utilization counters; native gives real values, fallback gives
    presence-only (the exporter labels the source)."""
    lib = _load()
    if lib is not None:
        buf = ctypes.create_string_buffer(16384)
        rc = lib.tpuinfo_metrics_json(dev_root.encode(), buf, len(buf))
        if rc == 0:
            try:
                return json.loads(buf.value.decode())
            except json.JSONDecodeError:
                pass
    devs = _py_devices(dev_root)
    return {
        "source": "fallback",
        "chips": [
            {"index": idx, "present": 1}
            for idx in _py_stable_indices(devs)
        ],
    }


def device_probe_path(path: str, stat_only: bool = False) -> bool:
    """Liveness (not existence) of one device node: open+close it
    read-only/non-blocking. True when the open succeeds, the device is
    busy serving a client (EBUSY proves the driver path works), or the
    caller itself was denied (EPERM/EACCES: an unprivileged container's
    device cgroup says nothing about the chip); False when the node is
    gone or wedged (ENOENT/ENXIO/EIO...).

    Takes the device PATH, never a positional index — enumeration order
    shifts when a node disappears and health must not be attributed to
    the wrong chip. The TPU analogue of the reference re-running
    ``nvidia-smi`` through the driver chroot
    (``validator/metrics.go:237-250``) — a wedged chip with its device
    file still present must NOT read as healthy."""
    if not path:
        return False
    # VFIO groups allow exactly ONE open file: never open() them — a
    # transient probe open could race the VM launcher's one-shot open of
    # its allocated group. stat-only for those (and for callers that
    # know their paths are groups regardless of location: stat_only=True).
    # The native library applies the same /vfio/ rule; checking here too
    # keeps the contract in one Python place.
    if stat_only or os.sep + "vfio" + os.sep in path:
        try:
            os.stat(path)
            return True
        except OSError:
            return False
    lib = _load()
    if lib is not None and hasattr(lib, "tpuinfo_device_probe_path"):
        return lib.tpuinfo_device_probe_path(path.encode()) >= 0
    import errno

    try:
        fd = os.open(path, os.O_RDONLY | os.O_NONBLOCK)
        os.close(fd)
        return True
    except OSError as e:
        return e.errno in (errno.EBUSY, errno.EPERM, errno.EACCES)


# ---------------------------------------------------------------------------
# pure-Python fallbacks
# ---------------------------------------------------------------------------


def _py_stable_indices(paths: List[str]) -> List[int]:
    """Stable device ids: the numeric suffix of each node name (accelN /
    vfio group number), NOT the enumeration position — positions shift
    when a node disappears, and mounts/health keyed on them would hit
    the wrong chip. Non-parsing names get ids past the max parsed one so
    a fallback can never collide with (and shadow) a real chip id.
    Strict whole-name parse ("accel0foo"/"noiommu-0" must not claim an
    id). Mirrors the native enumeration."""
    import re

    parsed: List[Optional[int]] = []
    for p in paths:
        m = re.fullmatch(r"accel(\d+)|(\d+)", os.path.basename(p))
        parsed.append(int(m.group(1) or m.group(2)) if m else None)
    next_fallback = max((x for x in parsed if x is not None), default=-1)
    out = []
    for x in parsed:
        if x is None:
            next_fallback += 1
            x = next_fallback
        out.append(x)
    return out


def _py_devices(dev_root: str) -> List[str]:
    accel = sorted(glob.glob(os.path.join(dev_root, "accel*")))
    if accel:
        return accel
    return [
        p
        for p in sorted(glob.glob(os.path.join(dev_root, "vfio", "*")))
        if os.path.basename(p) != "vfio"
    ]


def _py_pci_info(dev_path: str) -> dict:
    name = os.path.basename(dev_path)
    sys_dev = f"/sys/class/accel/{name}/device"
    out = {}
    try:
        target = os.readlink(sys_dev)
        out["pci_address"] = os.path.basename(target)
    except OSError:
        return out
    try:
        with open(os.path.join(sys_dev, "numa_node")) as f:
            out["numa_node"] = int(f.read().strip())
    except OSError:
        pass
    try:
        with open(os.path.join(sys_dev, "vendor")) as f:
            out["vendor"] = f.read().strip()
    except OSError:
        pass
    return out
