"""Shared constants: the node-label bus and well-known paths.

Node labels are the operator's cross-layer communication mechanism, exactly
as in the reference (``controllers/state_manager.go:27-101``): feature
discovery publishes hardware facts, the operator converts them to
per-component deploy labels which are the DaemonSets' nodeSelectors, and the
upgrade engine runs its FSM through per-node state labels.
"""

# --- Version (single source: versions.mk) ------------------------------


def _read_version() -> str:
    """The release version lives in versions.mk (the central pin the
    Makefile, CSV generator and runtime defaults all share); installed
    packages without the file fall back to the last released value."""
    import os
    import re

    path = os.path.join(os.path.dirname(__file__), "..", "versions.mk")
    try:
        with open(path) as f:
            for line in f:
                m = re.match(r"VERSION \?=\s*(\S+)", line)
                if m:
                    return m.group(1)
    except OSError:
        pass
    return "0.2.0"


VERSION = _read_version()
DEFAULT_REGISTRY = "gcr.io/tpu-operator"
# the tag the release pipeline actually publishes (Makefile image table)
DEFAULT_JAX_WORKLOAD_IMAGE = (
    f"{DEFAULT_REGISTRY}/tpu-operator-jax-validator:{VERSION}"
)

# --- CRD ---------------------------------------------------------------
GROUP = "tpu.k8s.io"
API_VERSION = f"{GROUP}/v1"
CLUSTER_POLICY_KIND = "ClusterPolicy"
CRD_NAME = f"clusterpolicies.{GROUP}"

# --- resource names ----------------------------------------------------
TPU_RESOURCE = "google.com/tpu"  # what the device plugin advertises
TPU_SUBSLICE_RESOURCE_PREFIX = "google.com/tpu-"  # mixed-strategy subslices

# --- hardware-fact labels (published by NFD / GKE / TPU feature discovery;
#     reference analogue controllers/state_manager.go:40-44,97-101) -----
# GKE node pools carry these natively:
GKE_TPU_ACCELERATOR_LABEL = "cloud.google.com/gke-tpu-accelerator"  # e.g. tpu-v5-lite-podslice
GKE_TPU_TOPOLOGY_LABEL = "cloud.google.com/gke-tpu-topology"  # e.g. 2x4
GKE_NODEPOOL_LABEL = "cloud.google.com/gke-nodepool"  # all hosts of one multi-host slice share a pool
# NFD fallback: Google PCI vendor id 1ae0 present on the node
NFD_TPU_PCI_LABEL = "feature.node.kubernetes.io/pci-1ae0.present"
# emitted by the chart's TPU NodeFeatureRule (vendor 1ae0 + accelerator
# class 1200) for non-GKE clusters — see templates/nodefeaturerules.yaml
NFD_RULE_TPU_PCI_LABEL = "tpu.k8s.io/tpu.pci.present"
NFD_KERNEL_LABEL = "feature.node.kubernetes.io/kernel-version.full"
NFD_OS_LABEL = "feature.node.kubernetes.io/system-os_release.ID"
NFD_OS_VERSION_LABEL = "feature.node.kubernetes.io/system-os_release.VERSION_ID"

# --- operator-managed labels ------------------------------------------
TPU_PRESENT_LABEL = f"{GROUP}/tpu.present"
# per-component deploy labels = DaemonSet nodeSelectors
# (reference nvidia.com/gpu.deploy.*, controllers/state_manager.go:72-95)
DEPLOY_LABEL_PREFIX = f"{GROUP}/tpu.deploy."
COMPONENT_LIBTPU = "libtpu"
COMPONENT_RUNTIME = "tpu-runtime"
COMPONENT_DEVICE_PLUGIN = "device-plugin"
COMPONENT_METRICSD = "metricsd"
COMPONENT_METRICS_EXPORTER = "metrics-exporter"
COMPONENT_TFD = "tpu-feature-discovery"
COMPONENT_SLICE_MANAGER = "slice-manager"
COMPONENT_OPERATOR_VALIDATOR = "operator-validator"
COMPONENT_NODE_STATUS_EXPORTER = "node-status-exporter"
COMPONENT_VM_MANAGER = "vm-manager"
COMPONENT_VM_DEVICE_MANAGER = "vm-device-manager"
COMPONENT_VFIO_MANAGER = "vfio-manager"
COMPONENT_SANDBOX_DEVICE_PLUGIN = "sandbox-device-plugin"
COMPONENT_SANDBOX_VALIDATOR = "sandbox-validator"
COMPONENT_KATA_MANAGER = "kata-manager"
COMPONENT_MAINTENANCE_HANDLER = "maintenance-handler"

# container-workload components (reference gpuStateLabels["container"],
# controllers/state_manager.go:72-86)
CONTAINER_WORKLOAD_COMPONENTS = [
    COMPONENT_LIBTPU,
    COMPONENT_RUNTIME,
    COMPONENT_DEVICE_PLUGIN,
    COMPONENT_METRICSD,
    COMPONENT_METRICS_EXPORTER,
    COMPONENT_TFD,
    COMPONENT_SLICE_MANAGER,
    COMPONENT_OPERATOR_VALIDATOR,
    COMPONENT_NODE_STATUS_EXPORTER,
    COMPONENT_MAINTENANCE_HANDLER,
]
# vm-passthrough components (reference gpuStateLabels["vm-passthrough"],
# controllers/state_manager.go:87-95)
VM_WORKLOAD_COMPONENTS = [
    COMPONENT_VM_MANAGER,
    COMPONENT_VM_DEVICE_MANAGER,
    COMPONENT_VFIO_MANAGER,
    COMPONENT_SANDBOX_DEVICE_PLUGIN,
    COMPONENT_SANDBOX_VALIDATOR,
    COMPONENT_KATA_MANAGER,
]

# per-node workload override label (reference nvidia.com/gpu.workload.config)
WORKLOAD_CONFIG_LABEL = f"{GROUP}/tpu.workload.config"
WORKLOAD_CONTAINER = "container"
WORKLOAD_VM_PASSTHROUGH = "vm-passthrough"

# host-maintenance handling (TPU-specific; no reference analogue):
# pending while a metadata-announced window is imminent/active
MAINTENANCE_STATE_LABEL = f"{GROUP}/maintenance"
# whether the node was already cordoned when the window began (the
# upgrade FSM's initial-state pattern: the all-clear restores, not resets)
MAINTENANCE_INITIAL_STATE_ANNOTATION = f"{GROUP}/maintenance-initial-unschedulable"

# --- node-health remediation FSM (TPU-specific; no reference analogue,
#     reusing the upgrade FSM's durable node-label store pattern,
#     upgrade_state.go:419-429) -----------------------------------------
# per-node FSM state, persisted as a label so remediation survives
# operator restarts:
#   observed -> restart-operands -> revalidate -> cordon-drain ->
#   quarantined -> recovered | exhausted
REMEDIATION_STATE_LABEL = f"{GROUP}/remediation-state"
REMEDIATION_STATE_SINCE_ANNOTATION = f"{GROUP}/remediation-state-since"
# escalation bookkeeping: {"attempts": N, "retryAt": iso8601} JSON —
# jittered exponential backoff between escalation steps, attempt-capped
# by spec.remediation.maxAttempts
REMEDIATION_ATTEMPTS_ANNOTATION = f"{GROUP}/remediation-attempts"
# node was already cordoned when remediation quarantined it; recovery
# restores, not resets (the upgrade FSM's initial-state pattern)
REMEDIATION_INITIAL_STATE_ANNOTATION = (
    f"{GROUP}/remediation.node-initial-state.unschedulable"
)
# escape hatch: the remediator never touches a node carrying this
REMEDIATION_SKIP_LABEL = f"{GROUP}/remediation.skip"
# the quarantine primitive: a NoSchedule taint + matching label applied
# by cordon-drain, removed on recovery
REPAIR_TAINT_KEY = f"{GROUP}/repair"
REPAIR_LABEL = f"{GROUP}/repair"
REPAIR_PENDING = "pending"

REMEDIATION_STATE_OBSERVED = "observed"
REMEDIATION_STATE_RESTART = "restart-operands"
REMEDIATION_STATE_REVALIDATE = "revalidate"
REMEDIATION_STATE_CORDON_DRAIN = "cordon-drain"
REMEDIATION_STATE_QUARANTINED = "quarantined"
REMEDIATION_STATE_RECOVERED = "recovered"
REMEDIATION_STATE_EXHAUSTED = "exhausted"
# states whose node is disrupted (cordoned/tainted) — these consume the
# shared maxUnavailable disruption budget alongside upgrade-active and
# upgrade-failed nodes (upgrade_state.slice_budget counts both sides)
REMEDIATION_DISRUPTED_STATES = (
    REMEDIATION_STATE_CORDON_DRAIN,
    REMEDIATION_STATE_QUARANTINED,
    REMEDIATION_STATE_EXHAUSTED,
)

# slice partitioning label FSM (reference nvidia.com/mig.config[.state])
SLICE_CONFIG_LABEL = f"{GROUP}/tpu.slice.config"
SLICE_CONFIG_STATE_LABEL = f"{GROUP}/tpu.slice.config.state"
# fleet-level live re-partition roll (controllers/repartition.py): set on
# a node while the operator is rolling it to a changed named-slice layout
# — the THIRD consumer of the shared slice-unit disruption budget
# (upgrades + remediation + re-partition draw on one maxUnavailable pool,
# kube/disruption.py joint accounting). Cleared when the node's
# slice-manager reports the new layout applied.
REPARTITION_STATE_LABEL = f"{GROUP}/repartition-state"
REPARTITION_STATE_ROLLING = "rolling"

# per-node device-plugin config override (reference nvidia.com/device-plugin.config)
DEVICE_PLUGIN_CONFIG_LABEL = f"{GROUP}/device-plugin.config"

# upgrade FSM label (reference nvidia.com/gpu-driver-upgrade-state)
UPGRADE_STATE_LABEL = f"{GROUP}/libtpu-upgrade-state"
# the label's VALUES (reference upgrade consts.go:33-58). These are
# node-label wire protocol, not FSM internals: the disruption budget
# (kube/disruption.py) and the upgrade FSM (upgrade/upgrade_state.py)
# both read them, and kube/ may not import upward — so the canonical
# strings live here beside the label key; upgrade_state aliases them.
UPGRADE_STATE_UNKNOWN = ""
UPGRADE_STATE_UPGRADE_REQUIRED = "upgrade-required"
UPGRADE_STATE_CORDON_REQUIRED = "cordon-required"
UPGRADE_STATE_WAIT_FOR_JOBS_REQUIRED = "wait-for-jobs-required"
UPGRADE_STATE_POD_DELETION_REQUIRED = "pod-deletion-required"
UPGRADE_STATE_DRAIN_REQUIRED = "drain-required"
UPGRADE_STATE_POD_RESTART_REQUIRED = "pod-restart-required"
UPGRADE_STATE_VALIDATION_REQUIRED = "validation-required"
UPGRADE_STATE_UNCORDON_REQUIRED = "uncordon-required"
UPGRADE_STATE_DONE = "upgrade-done"
UPGRADE_STATE_FAILED = "upgrade-failed"
# states that hold a node DISRUPTED for the shared budget (between
# cordon and uncordon, exclusive of the terminal done/failed pair)
UPGRADE_ACTIVE_STATES = (
    UPGRADE_STATE_CORDON_REQUIRED,
    UPGRADE_STATE_WAIT_FOR_JOBS_REQUIRED,
    UPGRADE_STATE_POD_DELETION_REQUIRED,
    UPGRADE_STATE_DRAIN_REQUIRED,
    UPGRADE_STATE_POD_RESTART_REQUIRED,
    UPGRADE_STATE_VALIDATION_REQUIRED,
    UPGRADE_STATE_UNCORDON_REQUIRED,
)
# bounded auto-retry of upgrade-failed nodes: {"count": N} JSON — a failed
# node re-enters the FSM after a jittered exponential backoff instead of
# permanently consuming maxUnavailable budget (clear UPGRADE_STATE_LABEL or
# set UPGRADE_SKIP_LABEL to intervene by hand)
UPGRADE_RETRY_ANNOTATION = f"{GROUP}/libtpu-upgrade-retries"
# the libtpu version the node ran BEFORE the FSM admitted it into the
# current roll — written at admission (copied from TFD_LIBTPU_VERSION_LABEL)
# so the health-gated rollout orchestrator's automatic rollback target
# survives operator restarts (controllers/rollout.py)
UPGRADE_PREVIOUS_VERSION_ANNOTATION = f"{GROUP}/libtpu-previous-version"

# --- health-gated progressive rollouts (controllers/rollout.py) --------
# the rollout ledger on the ClusterPolicy: JSON {kind, target, previous,
# stage, state, ...} persisting canary→wave→fleet progress, the recorded
# rollback target and any failing health evidence across restarts
ROLLOUT_STATE_ANNOTATION = f"{GROUP}/rollout-state"
# per-node validator performance readings, published by the node-status
# exporter (validator/metrics.py) from the canonical jax/membw status
# payloads: JSON {"tflops": x, "gbps": y, "version": v} — the live
# evidence the rollout health gate compares against the baseline
VALIDATOR_PERF_ANNOTATION = f"{GROUP}/validator-perf"
# pre-roll copy of VALIDATOR_PERF_ANNOTATION, stamped when the upgrade
# FSM admits the node — the per-node baseline TFLOPS/membw deltas are
# measured against (survives restarts like every FSM fact)
VALIDATOR_PERF_BASELINE_ANNOTATION = f"{GROUP}/validator-perf-baseline"
# when the node entered its current FSM state (drives drain/validation
# timeouts -> upgrade-failed)
UPGRADE_STATE_SINCE_ANNOTATION = f"{GROUP}/libtpu-upgrade-state-since"
UPGRADE_SKIP_DRAIN_LABEL = f"{GROUP}/libtpu-upgrade-drain.skip"
UPGRADE_SKIP_LABEL = f"{GROUP}/libtpu-upgrade.skip"
# node was already cordoned when the upgrade began; uncordon is skipped so
# the node leaves the FSM in the state the operator found it (reference
# UpgradeInitialStateAnnotationKeyFmt, upgrade consts.go:27-28)
UPGRADE_INITIAL_STATE_ANNOTATION = (
    f"{GROUP}/libtpu-upgrade.node-initial-state.unschedulable"
)
UPGRADE_ENABLED_ANNOTATION = f"{GROUP}/libtpu-upgrade-enabled"

# feature-discovery published labels (GFD analogue)
TFD_LABEL_PREFIX = f"{GROUP}/tpu."
TFD_CHIP_TYPE_LABEL = f"{TFD_LABEL_PREFIX}chip-type"  # v4 | v5e | v5p | v6e
TFD_CHIP_COUNT_LABEL = f"{TFD_LABEL_PREFIX}chip-count"
TFD_HBM_GB_LABEL = f"{TFD_LABEL_PREFIX}hbm-gb"
TFD_TOPOLOGY_LABEL = f"{TFD_LABEL_PREFIX}topology"  # e.g. 2x2x1
TFD_SLICE_HOSTS_LABEL = f"{TFD_LABEL_PREFIX}slice-hosts"
TFD_WORKER_ID_LABEL = f"{TFD_LABEL_PREFIX}worker-id"
TFD_ICI_WRAP_LABEL = f"{TFD_LABEL_PREFIX}ici-wraparound"
TFD_LIBTPU_VERSION_LABEL = f"{TFD_LABEL_PREFIX}libtpu-version"
TFD_SLICE_ID_LABEL = f"{TFD_LABEL_PREFIX}slice-id"

# sharded scale-out (tpu_operator/shard.py): the node's consistent-hash
# shard, stamped by the owning replica's label pass — the server-side
# selector a journal-stale failover uses to re-list ONE shard's nodes
# instead of the world
SHARD_LABEL = f"{GROUP}/shard"

# slice-scoped aggregate readiness (no reference analogue — SURVEY.md §7
# "readiness semantics on multi-host slices"): all hosts of a pod-slice
# validated => every member node gets slice.ready=true
SLICE_READY_LABEL = f"{GROUP}/tpu.slice.ready"

# --- host paths --------------------------------------------------------
# status-file barrier directory (reference /run/nvidia/validations,
# validator/main.go:123-157)
VALIDATION_DIR = "/run/tpu/validations"
STATUS_FILE_LIBTPU = "libtpu-ready"
STATUS_FILE_RUNTIME = "runtime-ready"
STATUS_FILE_PLUGIN = "plugin-ready"
STATUS_FILE_JAX = "jax-ready"
STATUS_FILE_SLICE = "slice-ready"
STATUS_FILE_SLICE_WORKLOAD = "slice-workload-ready"
# diagnostic probes (opt-in / on-demand): surfaced by the node-status
# exporter as tpu_validator_probe_ready{probe=...}
PROBE_STATUS_FILES = (
    "slice-ready",
    "slice-workload-ready",
    "ici-ready",
    "ringattn-ready",
    "pipeline-ready",
    "moe-ready",
    "membw-ready",
    "flashattn-ready",
)
STATUS_FILE_LIBTPU_CTR = ".libtpu-ctr-ready"  # startupProbe barrier

LIBTPU_HOST_DIR = "/home/kubernetes/lib/tpu"
DEVICE_GLOB = "/dev/accel*"
VFIO_DIR = "/dev/vfio"

# proxy trusted-CA + libtpu artifact-source mounts (reference trusted-CA
# mount dir + driver repo/cert config mounts, object_controls.go:962-1050,
# 2770-2830)
TRUSTED_CA_MOUNT_DIR = "/etc/pki/tpu-operator/trusted-ca"
LIBTPU_REPO_CONFIG_DIR = "/etc/libtpu/repo.d"
LIBTPU_CERT_CONFIG_DIR = "/etc/libtpu/certs.d"

# --- misc --------------------------------------------------------------
OPERATOR_NAMESPACE_ENV = "OPERATOR_NAMESPACE"
DEFAULT_NAMESPACE = "tpu-operator"
LAST_APPLIED_HASH_ANNOTATION = f"{GROUP}/last-applied-hash"  # ref nvidia.com/last-applied-hash
OPERAND_VERSION_ANNOTATION = f"{GROUP}/operand-version"
PSA_LABEL_PREFIX = "pod-security.kubernetes.io/"

# TPU generations the libtpu fan-out understands (per-kernel analogue)
TPU_GENERATIONS = ["v4", "v5e", "v5p", "v6e"]

# map GKE accelerator label value -> generation
GKE_ACCELERATOR_TO_GENERATION = {
    "tpu-v4-podslice": "v4",
    "tpu-v5-lite-podslice": "v5e",
    "tpu-v5-lite-device": "v5e",
    "tpu-v5p-slice": "v5p",
    "tpu-v6e-slice": "v6e",
}
