"""Node-health remediation FSM — detect, quarantine, recover failing hosts.

SURVEY §5 calls failure detection the operator's weakest story: the
device plugin flips chips ``Unhealthy`` (``plugin/server.py``) and
``slice_status.host_allocatable_ok`` sees zero-allocatable hosts, but
before this controller **nothing acted on either signal** — a host with
dead chips kept its schedulable bit and its slice just read
``ready=false`` forever. This controller closes the loop, reusing the
upgrade engine's durable per-node-FSM pattern (node labels as the store,
``upgrade_state.go:419-429`` initial-state annotations, ``maxUnavailable``
budgeting) for *remediation* instead of upgrades.

**Health derivation** (pure, over the pass's in-hand node list plus ONE
namespace pod listing — no per-node reads):

* the kubelet advertises the TPU resource with **zero allocatable**
  (``host_allocatable_ok(node) is False``);
* an **operand pod on the node sits in CrashLoopBackOff**;
* the node carries the operator-validator deploy label but **no Running
  validator pod** backs it.

**The FSM** (persisted in ``tpu.k8s.io/remediation-state`` so it survives
operator restarts)::

    observed ──▶ restart-operands ──▶ revalidate ──▶ cordon-drain ──▶
    quarantined ──▶ recovered        (any step, on health returning)
                └──▶ exhausted       (attempt cap hit — flapping host)

Each escalation step is gated by a jittered exponential backoff and a
per-node attempt cap (``spec.remediation.maxAttempts``), both recorded in
the ``tpu.k8s.io/remediation-attempts`` annotation. Quarantine applies a
``tpu.k8s.io/repair=pending`` **NoSchedule taint + label** and cordons the
node (remembering whether it was already cordoned, the upgrade FSM's
initial-state pattern); the drain evicts TPU workload pods through the
Eviction subresource, so a PodDisruptionBudget veto (429) **defers** the
step instead of failing it. Recovery (chips reappear, validator passes)
uncordons, untaints and clears the FSM; the attempt record survives
recovery so a *flapping* host lands ``exhausted`` instead of looping.

**Two fleet-level guards**:

* a **remediation budget**: disruptions are counted in SLICE units over
  one JOINT disrupted set shared with rolling libtpu upgrades
  (``upgrade_state.slice_budget`` subtracts remediation-disrupted slices
  from upgrade admission and excludes them from pending; this controller
  counts upgrade-active/failed slices against its own admission). Each
  side enforces its own ``maxUnavailable`` over the joint set — with the
  two knobs equal (both default "25%") that is exactly one pool, and
  upgrades + repairs never jointly exceed the cap. One deliberate exception: ``exhausted`` entry (a
  flapping host past its attempt cap) quarantines WITHOUT waiting for
  budget headroom — the host's slice is already out of service either
  way, so fencing it reduces nothing, while leaving a known-bad flapper
  schedulable would; the exhausted slice still counts against both
  sides' admission from then on;
* a **systemic-failure breaker**: when at least
  ``spec.remediation.systemicThreshold`` of the TPU fleet turns unhealthy
  in one pass, remediation halts — zero drains, zero node writes — and
  the CR gets a ``Degraded/SystemicNodeFailure`` condition plus a Warning
  Event. A bad libtpu push must not drain the fleet.

**Interlocks**: the remediator never fights another actor's disruption —
nodes inside an announced host-maintenance window
(``tpu.k8s.io/maintenance=pending``), nodes with an in-flight (or failed)
libtpu-upgrade FSM state, and nodes carrying the
``tpu.k8s.io/remediation.skip`` escape hatch are skipped with a single
log-once note.
"""

from __future__ import annotations

import json
import logging
import random
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from tpu_operator import consts
from tpu_operator.obs import LogOnce, flight, trace
from tpu_operator.kube.client import (
    Client,
    ConflictError,
    NotFoundError,
    Obj,
    merge_taint,
    mutate_with_retry,
)

log = logging.getLogger("tpu-operator.remediation")

# a recovered node's attempt record decays after this much quiet time:
# flap detection must span recoveries, but a failure months later is a
# new incident, not attempt N+1 of the old one
ATTEMPTS_DECAY_S = 3600.0

# the systemic breaker never opens on a single unhealthy node, whatever
# the percentage arithmetic says about tiny fleets: one dead host is
# exactly what remediation exists for
BREAKER_MIN_NODES = 2

def _threshold_count(value, total: int) -> int:
    """Node count for the systemic threshold, rounding UP on percentages
    ("at least this fraction" semantics) — unlike the budget's
    ``parse_max_unavailable``, which floors by design: a floor here would
    open the breaker BELOW the configured fraction on odd-sized fleets
    (5 nodes at "50%" must need 3 unhealthy, not 2)."""
    import math

    if total <= 0:
        return 0
    if value is None:
        value = "50%"
    s = str(value).strip()
    if s.endswith("%"):
        try:
            pct = float(s[:-1])
        except ValueError:
            pct = 50.0
        return min(max(1, math.ceil(total * pct / 100.0)), total)
    try:
        return max(1, min(int(s), total))
    except ValueError:
        return total


def pod_crashlooping(pod: Obj) -> bool:
    """Whether any container sits in CrashLoopBackOff — the health
    signal shared by the verdict derivation here and the watch
    predicate in ``main.wire_event_sources`` (a pod entering/leaving
    crashloop must WAKE the reconciler: unlike chip death, it is a Pod
    event, which nothing else watches)."""
    for cs in pod.get("status", {}).get("containerStatuses") or []:
        waiting = (cs.get("state") or {}).get("waiting") or {}
        if waiting.get("reason") == "CrashLoopBackOff":
            return True
    return False


def _now_iso() -> str:
    from datetime import datetime, timezone

    return datetime.now(timezone.utc).isoformat()


def _parse_iso(ts: str) -> float:
    from datetime import datetime, timezone

    try:
        dt = datetime.fromisoformat(ts)
    except (TypeError, ValueError):
        return 0.0
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=timezone.utc)
    return dt.timestamp()


def _utc_now() -> float:
    return time.time()


@dataclass
class NodeVerdict:
    """One node's derived health this pass."""

    name: str
    node: Obj
    state: str  # current FSM label ("" = not in the FSM)
    reasons: List[str] = field(default_factory=list)
    skip_reason: Optional[str] = None  # interlock: why we must not act

    @property
    def unhealthy(self) -> bool:
        return bool(self.reasons)


@dataclass
class RemediationSummary:
    """What one remediation pass saw and did — feeds ``status.remediation``,
    the gauges, and the reconciler's requeue decision."""

    total: int = 0
    unhealthy: int = 0
    quarantined: int = 0
    exhausted: int = 0
    skipped: int = 0  # interlocked nodes left alone (log-once)
    errored: bool = False  # the pass itself raised (counts unknown)
    breaker_open: bool = False
    breaker_threshold: int = 0
    budget_cap: int = 0  # maxUnavailable in slice units
    disrupted_slices: int = 0  # upgrades + repairs jointly
    budget_deferred: int = 0  # drains the budget refused this pass
    unhealthy_hosts: List[str] = field(default_factory=list)
    # the slice ids behind disrupted_slices, INCLUDING escalations this
    # pass wrote — the same-pass repartition roll reads these so its
    # admission is not blind to quarantine labels still on the wire
    # (the pass-start node snapshot predates them)
    disrupted_sids: Set[str] = field(default_factory=set)
    # hosts THIS pass escalated into a disrupted state (cordon-drain /
    # exhausted entry): the same-pass rollout health gate reads these —
    # the quarantine labels are on the wire but not in the pass-start
    # node snapshot, and a canary quarantined in the very pass its
    # observation window elapses must block the promotion
    newly_disrupted_hosts: List[str] = field(default_factory=list)

    @property
    def active(self) -> bool:
        """Whether remediation has in-flight work (level-triggered
        requeue wanted even when the operands are all Ready — backoffs
        elapse without any cluster event to wake the reconciler). An
        errored pass counts: the retry needs a clock too."""
        return self.unhealthy > 0 or self.breaker_open or self.errored

    def status_block(self) -> Dict[str, object]:
        out: Dict[str, object] = {
            "unhealthy": self.unhealthy,
            "quarantined": self.quarantined,
            "exhausted": self.exhausted,
        }
        if self.breaker_open:
            out["breakerOpen"] = True
        return out


class NodeRemediationController:
    """Level-triggered remediation pass, one step per node per pass —
    wired into the reconcile pass after ``label_tpu_nodes`` (the node
    list it consumes is the pass's labeled list; no extra node reads)."""

    def __init__(self, client: Client, namespace: str = ""):
        self.client = client
        self.namespace = namespace
        # process-lifetime counters (gauges + /debug/vars)
        self.attempts_total = 0
        self.drains_vetoed_total = 0
        self.budget_deferred_total = 0
        self.breaker_opens_total = 0
        self.last_summary: Dict[str, object] = {}
        # log-once state: (node, reason-kind) pairs already noted; an
        # entry is dropped when the condition clears so a recurrence
        # logs again (once per stretch, not once per process)
        self._logged = LogOnce()
        self._breaker_was_open = False

    def forget_node(self, name: str) -> None:
        """Event-speed ledger prune for a deleted node (the keyed delta
        path routes node DELETEs here, controllers/delta.py): its
        log-once suppressions die with it so a same-named rejoin starts
        clean, without waiting for the next full pass's liveness
        ``prune``."""
        self._logged.discard_subject(name)

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------
    def stats(self) -> Dict[str, object]:
        """/debug/vars "remediation" payload."""
        return {
            "last_pass": self.last_summary,
            "attempts_total": self.attempts_total,
            "drains_vetoed_total": self.drains_vetoed_total,
            "budget_deferred_total": self.budget_deferred_total,
            "breaker_opens_total": self.breaker_opens_total,
        }

    # ------------------------------------------------------------------
    # the pass
    # ------------------------------------------------------------------
    def reconcile(
        self, tpu_nodes: List[Obj], spec, namespace: str
    ) -> Optional[RemediationSummary]:
        """One remediation pass over the labeled TPU node list. When
        remediation is disabled, strips any leftover FSM state and
        returns an all-zero summary (so a stale ``status.remediation``
        block clears); else the pass summary."""
        self.namespace = namespace
        if spec is None or not spec.is_enabled():
            self._cleanup_disabled(tpu_nodes)
            self.last_summary = {"enabled": False}
            return RemediationSummary(total=len(tpu_nodes))

        pods_by_node, validator_nodes = self._namespace_pods_by_node()
        verdicts = [
            self._verdict(node, pods_by_node, validator_nodes)
            for node in tpu_nodes
        ]
        verdicts.sort(key=lambda v: v.name)

        summary = RemediationSummary(total=len(verdicts))
        summary.unhealthy = sum(1 for v in verdicts if v.unhealthy)
        summary.unhealthy_hosts = [v.name for v in verdicts if v.unhealthy]

        # --- systemic-failure breaker --------------------------------
        # keyed on ACTIONABLE unhealthy nodes only: a rolling upgrade
        # legitimately takes validators/chips down on the nodes it owns
        # (interlocked = skipped anyway), and counting those would open
        # the breaker on every wide upgrade roll. Already-disrupted
        # nodes (quarantined/exhausted) are excluded too — the breaker
        # detects a fleet TURNING unhealthy at once, and independent
        # failures accumulating over weeks, each already contained,
        # must not add up to a false "systemic" verdict
        from tpu_operator.upgrade.upgrade_state import parse_max_unavailable

        actionable = sum(
            1
            for v in verdicts
            if v.unhealthy
            and not v.skip_reason
            and v.state not in consts.REMEDIATION_DISRUPTED_STATES
        )
        summary.breaker_threshold = max(
            BREAKER_MIN_NODES,
            _threshold_count(
                getattr(spec, "systemic_threshold", None), len(verdicts)
            ),
        )
        if actionable >= summary.breaker_threshold:
            summary.breaker_open = True
            self._open_breaker(summary)
            self._finish(summary, verdicts)
            return summary
        if self._breaker_was_open:
            self._breaker_was_open = False
            log.warning(
                "systemic-failure breaker closed: %d of %d TPU nodes "
                "unhealthy (threshold %d); remediation resumes",
                summary.unhealthy,
                summary.total,
                summary.breaker_threshold,
            )

        # --- shared disruption budget, in slice units ----------------
        from tpu_operator.controllers.slice_status import group_slices
        from tpu_operator.upgrade.upgrade_state import (
            ACTIVE_STATES as UPGRADE_ACTIVE,
        )
        from tpu_operator.upgrade.upgrade_state import STATE_FAILED

        slices = group_slices(tpu_nodes)
        slice_of = {
            member: sid
            for sid, info in slices.items()
            for member in info.member_nodes
        }
        from tpu_operator.kube.disruption import repartition_disrupted

        disrupted: Set[str] = set()
        for v in verdicts:
            labels = v.node.get("metadata", {}).get("labels", {}) or {}
            ustate = labels.get(consts.UPGRADE_STATE_LABEL, "")
            if (
                v.state in consts.REMEDIATION_DISRUPTED_STATES
                or ustate in UPGRADE_ACTIVE
                or ustate == STATE_FAILED
                # third consumer of the one pool: a slice mid live
                # re-partition roll consumes remediation headroom too
                or repartition_disrupted(v.node)
            ):
                disrupted.add(slice_of.get(v.name, v.name))
        max_unavailable = getattr(spec, "max_unavailable", None)
        summary.budget_cap = parse_max_unavailable(
            max_unavailable, len(slices)
        )

        # --- per-node FSM step ---------------------------------------
        for v in verdicts:
            try:
                self._step_node(v, spec, summary, disrupted, slice_of)
            except NotFoundError:
                log.info("node %s vanished mid-remediation-pass", v.name)
            except ConflictError:
                log.warning(
                    "node %s kept conflicting mid-remediation-pass; "
                    "retrying next reconcile",
                    v.name,
                )
        summary.disrupted_slices = len(disrupted)
        summary.disrupted_sids = set(disrupted)
        # retire log-once state for vanished nodes: lifecycle churn
        # (preemption waves deleting quarantined hosts) would otherwise
        # grow the set without bound, and a rejoin under the same name
        # would inherit the old suppression
        live = {v.name for v in verdicts}
        self._logged.prune(live)
        self._finish(summary, verdicts)
        return summary

    def _finish(self, summary: RemediationSummary, verdicts) -> None:
        # quarantine counts reflect post-step labels where we wrote them
        # this pass; a label we just set is mirrored in v.state
        summary.quarantined = sum(
            1
            for v in verdicts
            if v.state
            in (
                consts.REMEDIATION_STATE_CORDON_DRAIN,
                consts.REMEDIATION_STATE_QUARANTINED,
            )
        )
        summary.exhausted = sum(
            1
            for v in verdicts
            if v.state == consts.REMEDIATION_STATE_EXHAUSTED
        )
        summary.skipped = sum(1 for v in verdicts if v.skip_reason)
        self.last_summary = {
            "enabled": True,
            "total": summary.total,
            "unhealthy": summary.unhealthy,
            "unhealthy_hosts": summary.unhealthy_hosts,
            "quarantined": summary.quarantined,
            "exhausted": summary.exhausted,
            "skipped": summary.skipped,
            "breaker_open": summary.breaker_open,
            "breaker_threshold": summary.breaker_threshold,
            "budget_cap": summary.budget_cap,
            "disrupted_slices": summary.disrupted_slices,
        }

    # ------------------------------------------------------------------
    # health derivation (pure over in-hand objects)
    # ------------------------------------------------------------------
    def _namespace_pods_by_node(self):
        """ONE namespace pod listing for the whole pass (served by the
        scope-filtered Pod informer), indexed by node; also returns the
        set of nodes with a Running+ready validator pod."""
        from tpu_operator.controllers.slice_status import VALIDATOR_APP

        pods_by_node: Dict[str, List[Obj]] = {}
        validator_nodes: Set[str] = set()
        for pod in self.client.list("v1", "Pod", self.namespace):
            node = pod.get("spec", {}).get("nodeName")
            if not node:
                continue
            pods_by_node.setdefault(node, []).append(pod)
            if (pod.get("metadata", {}).get("labels") or {}).get(
                "app"
            ) == VALIDATOR_APP and pod.get("status", {}).get(
                "phase"
            ) == "Running":
                statuses = pod.get("status", {}).get("containerStatuses")
                if statuses is None or all(
                    cs.get("ready", True) for cs in statuses
                ):
                    validator_nodes.add(node)
        return pods_by_node, validator_nodes

    def _verdict(
        self,
        node: Obj,
        pods_by_node: Dict[str, List[Obj]],
        validator_nodes: Set[str],
    ) -> NodeVerdict:
        from tpu_operator.controllers.slice_status import host_allocatable_ok
        from tpu_operator.upgrade.upgrade_state import (
            ACTIVE_STATES as UPGRADE_ACTIVE,
        )
        from tpu_operator.upgrade.upgrade_state import STATE_FAILED

        name = node["metadata"]["name"]
        labels = node.get("metadata", {}).get("labels", {}) or {}
        v = NodeVerdict(
            name=name,
            node=node,
            state=labels.get(consts.REMEDIATION_STATE_LABEL, ""),
        )
        if host_allocatable_ok(node) is False:
            v.reasons.append(f"0 allocatable {consts.TPU_RESOURCE}")
        crash = sorted(
            p["metadata"]["name"]
            for p in pods_by_node.get(name, ())
            if pod_crashlooping(p)
            # same tpu-* operand filter as the restart rung: a user pod
            # crashlooping in the operator namespace is not a node-health
            # signal, and restarting operands could never clear it — the
            # FSM would escalate a healthy host all the way to quarantine
            and (
                (p["metadata"].get("labels") or {}).get("app") or ""
            ).startswith("tpu-")
        )
        if crash:
            v.reasons.append(
                "operand pod(s) in CrashLoopBackOff: " + ", ".join(crash)
            )
        if (
            labels.get(
                consts.DEPLOY_LABEL_PREFIX
                + consts.COMPONENT_OPERATOR_VALIDATOR
            )
            == "true"
            and name not in validator_nodes
        ):
            v.reasons.append("validator pod not Running")

        # interlocks: another actor owns this node's disruption
        if labels.get(consts.REMEDIATION_SKIP_LABEL) == "true":
            v.skip_reason = f"{consts.REMEDIATION_SKIP_LABEL}=true"
        elif labels.get(consts.MAINTENANCE_STATE_LABEL):
            v.skip_reason = "active host-maintenance window"
        elif (
            labels.get(consts.REPARTITION_STATE_LABEL)
            == consts.REPARTITION_STATE_ROLLING
        ):
            # a live re-partition pauses the node's chip clients on
            # purpose — the resulting zero-allocatable / validator-down
            # window is self-inflicted, not a node-health incident
            v.skip_reason = "in-flight slice re-partition roll"
        else:
            ustate = labels.get(consts.UPGRADE_STATE_LABEL, "")
            if ustate in UPGRADE_ACTIVE or ustate == STATE_FAILED:
                v.skip_reason = f"in-flight libtpu upgrade ({ustate})"
        return v

    # ------------------------------------------------------------------
    # FSM bookkeeping on the node object (labels + annotations)
    # ------------------------------------------------------------------
    def _read_attempts(self, node: Obj):
        """(attempts, retry_at_epoch) from the attempts annotation.

        Decay applies ONLY to a node that is OUT of the FSM (no state
        label): a record quiet for ``ATTEMPTS_DECAY_S`` after recovery
        reads as zero attempts — a relapse an hour later is a new
        incident, not attempt N+1 of the old one. A node mid-FSM never
        decays, however long the incident runs: decaying an ACTIVE
        record would erase the maxAttempts cap (long quarantines, large
        backoffs) and let a wedged host cycle restarts/drains forever.
        Recovery re-stamps ``updatedAt`` (``_touch_attempts``) so the
        quiet clock starts at recovery, not at the last escalation."""
        raw = (node["metadata"].get("annotations", {}) or {}).get(
            consts.REMEDIATION_ATTEMPTS_ANNOTATION, ""
        )
        if not raw:
            return 0, 0.0
        try:
            rec = json.loads(raw)
            attempts = int(rec.get("attempts", 0))
            retry_at = _parse_iso(rec.get("retryAt", ""))
            updated = _parse_iso(rec.get("updatedAt", ""))
        except (ValueError, TypeError, AttributeError):
            return 0, 0.0
        in_fsm = consts.REMEDIATION_STATE_LABEL in (
            node["metadata"].get("labels", {}) or {}
        )
        if (
            not in_fsm
            and updated
            and _utc_now() - updated > ATTEMPTS_DECAY_S
        ):
            return 0, 0.0
        return attempts, retry_at

    def _touch_attempts(self, name: str) -> None:
        """Re-stamp the attempt record's ``updatedAt`` without changing
        the count — called at recovery so the decay window measures
        quiet-time SINCE recovery."""

        def mutate(node):
            ann = node["metadata"].setdefault("annotations", {})
            raw = ann.get(consts.REMEDIATION_ATTEMPTS_ANNOTATION)
            if not raw:
                return False
            try:
                rec = json.loads(raw)
            except (ValueError, TypeError):
                return False
            rec["updatedAt"] = _now_iso()
            ann[consts.REMEDIATION_ATTEMPTS_ANNOTATION] = json.dumps(rec)
            return True

        mutate_with_retry(self.client, "v1", "Node", name, mutate=mutate)

    def _write_attempts(self, name: str, attempts: int, delay_s: float):
        """Persist the attempt count and the jittered next-step deadline
        (equal jitter: uniform(d/2, d)) — sampled ONCE and recorded, so
        an operator restart resumes the same clock."""
        retry_at = _utc_now() + random.uniform(delay_s / 2, delay_s)
        record = json.dumps(
            {
                "attempts": attempts,
                "retryAt": _iso_at(retry_at),
                "updatedAt": _now_iso(),
            }
        )

        def mutate(node):
            ann = node["metadata"].setdefault("annotations", {})
            if ann.get(consts.REMEDIATION_ATTEMPTS_ANNOTATION) == record:
                return False
            ann[consts.REMEDIATION_ATTEMPTS_ANNOTATION] = record
            return True

        mutate_with_retry(self.client, "v1", "Node", name, mutate=mutate)

    def _backoff_s(self, spec, attempts: int) -> float:
        base = getattr(spec, "backoff_seconds", None)
        base = 30.0 if base is None else float(base)  # 0 is a legal value
        return min(base * 16, base * (2**attempts))

    def _set_state(self, name: str, state: Optional[str]) -> None:
        """Write (or, with None, clear) the FSM label + since stamp."""

        def mutate(node):
            meta = node["metadata"]
            labels = meta.setdefault("labels", {})
            ann = meta.setdefault("annotations", {})
            if state is None:
                changed = False
                if consts.REMEDIATION_STATE_LABEL in labels:
                    del labels[consts.REMEDIATION_STATE_LABEL]
                    changed = True
                if consts.REMEDIATION_STATE_SINCE_ANNOTATION in ann:
                    del ann[consts.REMEDIATION_STATE_SINCE_ANNOTATION]
                    changed = True
                return changed
            if labels.get(consts.REMEDIATION_STATE_LABEL) == state:
                return False
            labels[consts.REMEDIATION_STATE_LABEL] = state
            ann[consts.REMEDIATION_STATE_SINCE_ANNOTATION] = _now_iso()
            return True

        mutate_with_retry(self.client, "v1", "Node", name, mutate=mutate)
        # flight timeline: every FSM transition is a causal post-mortem
        # event (low rate — at most one per unhealthy node per pass)
        flight.record(
            "remediation.fsm", node=name, state=state or "cleared"
        )
        if state in (
            consts.REMEDIATION_STATE_CORDON_DRAIN,
            consts.REMEDIATION_STATE_QUARANTINED,
        ):
            # the FSM just consumed (or confirmed) a shared-budget
            # disruption unit on this host's slice
            flight.record("budget.admit", owner="remediation", node=name)
        trace.instant("fsm.remediation_transition", node=name, state=state)
        if state is not None:
            log.info("node %s remediation-state -> %s", name, state)

    # ------------------------------------------------------------------
    # FSM actions
    # ------------------------------------------------------------------
    def _step_node(
        self,
        v: NodeVerdict,
        spec,
        summary: RemediationSummary,
        disrupted: Set[str],
        slice_of: Dict[str, str],
    ) -> None:
        name = v.name
        if v.skip_reason and (v.unhealthy or v.state):
            self._log_once(
                (name, "interlock"),
                "node %s: unhealthy/in-FSM but deferring to %s",
                name,
                v.skip_reason,
            )
            return
        self._logged.discard((name, "interlock"))

        if not v.unhealthy:
            self._step_healthy(v, spec)
            return

        max_attempts = int(getattr(spec, "max_attempts", 5) or 0)
        attempts, retry_at = self._read_attempts(v.node)
        now = _utc_now()
        state = v.state
        sid = slice_of.get(name, name)

        if state in ("", consts.REMEDIATION_STATE_RECOVERED):
            # (re-)entry: a fresh failure — or a relapse. A relapsed node
            # whose attempt budget is already spent is FLAPPING: it goes
            # straight to exhausted instead of burning another cycle of
            # restarts and drains.
            if attempts >= max_attempts > 0:
                self._enter_exhausted(v, summary, sid, disrupted)
                return
            self._set_state(name, consts.REMEDIATION_STATE_OBSERVED)
            self._write_attempts(
                name, attempts, self._backoff_s(spec, attempts)
            )
            v.state = consts.REMEDIATION_STATE_OBSERVED
            log.warning(
                "node %s unhealthy (%s); observing before remediation",
                name,
                "; ".join(v.reasons),
            )
            return

        if state == consts.REMEDIATION_STATE_OBSERVED:
            if now < retry_at:
                return  # dwell: debounce a transient blip
            self._set_state(name, consts.REMEDIATION_STATE_RESTART)
            v.state = consts.REMEDIATION_STATE_RESTART
            attempts += 1
            self.attempts_total += 1
            self._write_attempts(
                name, attempts, self._backoff_s(spec, attempts)
            )
            self._restart_operands(v)
            self._set_state(name, consts.REMEDIATION_STATE_REVALIDATE)
            v.state = consts.REMEDIATION_STATE_REVALIDATE
            return

        if state == consts.REMEDIATION_STATE_RESTART:
            # operator restarted mid-step: redo the (idempotent) restart
            self._restart_operands(v)
            self._set_state(name, consts.REMEDIATION_STATE_REVALIDATE)
            v.state = consts.REMEDIATION_STATE_REVALIDATE
            return

        if state == consts.REMEDIATION_STATE_REVALIDATE:
            if now < retry_at:
                return  # give the restarted operands time to validate
            if attempts >= max_attempts > 0:
                self._enter_exhausted(v, summary, sid, disrupted)
                return
            # escalate to cordon-drain — within the SHARED budget. A
            # slice already disrupted (sibling host mid-upgrade or
            # already quarantined) costs nothing extra; a fresh slice
            # needs headroom under the cap.
            if sid not in disrupted and len(disrupted) >= summary.budget_cap:
                summary.budget_deferred += 1
                self.budget_deferred_total += 1
                self._log_once(
                    (name, "budget"),
                    "node %s: cordon-drain deferred — %d slice(s) already "
                    "disrupted (upgrades + repairs) at the maxUnavailable "
                    "cap of %d",
                    name,
                    len(disrupted),
                    summary.budget_cap,
                )
                return
            self._logged.discard((name, "budget"))
            attempts += 1
            self.attempts_total += 1
            self._write_attempts(
                name, attempts, self._backoff_s(spec, attempts)
            )
            self._apply_quarantine(name)
            self._set_state(name, consts.REMEDIATION_STATE_CORDON_DRAIN)
            v.state = consts.REMEDIATION_STATE_CORDON_DRAIN
            disrupted.add(sid)
            summary.newly_disrupted_hosts.append(name)
            self._record_event(
                "Warning",
                "NodeQuarantined",
                f"node {name} cordoned and tainted "
                f"{consts.REPAIR_TAINT_KEY}={consts.REPAIR_PENDING} for "
                f"repair ({'; '.join(v.reasons)}); slice {sid} is degraded "
                f"until the host recovers",
                dedup_extra=name,
            )
            # fall through into the drain below
            state = consts.REMEDIATION_STATE_CORDON_DRAIN

        if state == consts.REMEDIATION_STATE_CORDON_DRAIN:
            disrupted.add(sid)
            self._apply_quarantine(name)  # idempotent (restart-safe)
            if self._drain(v):
                self._set_state(name, consts.REMEDIATION_STATE_QUARANTINED)
                v.state = consts.REMEDIATION_STATE_QUARANTINED
            return

        if state == consts.REMEDIATION_STATE_QUARANTINED:
            disrupted.add(sid)
            return  # hold until health returns (handled above) or a human acts

        if state == consts.REMEDIATION_STATE_EXHAUSTED:
            disrupted.add(sid)
            self._apply_quarantine(name)  # keep the quarantine asserted
            # keep draining too: workloads still pinned to the known-bad
            # host (e.g. an exhausted entry whose eviction was vetoed)
            # must not ride it until the chips die mid-job
            self._drain(v)
            return

    def _step_healthy(self, v: NodeVerdict, spec) -> None:
        """Health returned: unwind whatever the FSM had applied. An
        ``exhausted`` node stays quarantined — it flapped past the
        attempt cap, and only a human (clearing the state label or the
        attempts annotation) puts it back in service."""
        name = v.name
        state = v.state
        if not state:
            return
        if state == consts.REMEDIATION_STATE_EXHAUSTED:
            return
        if state == consts.REMEDIATION_STATE_RECOVERED:
            # stable through a full pass: leave the FSM (the attempts
            # record stays, decaying after ATTEMPTS_DECAY_S, so a flap
            # re-entering soon is recognized as one)
            self._set_state(name, None)
            v.state = ""
            return
        if state in (
            consts.REMEDIATION_STATE_CORDON_DRAIN,
            consts.REMEDIATION_STATE_QUARANTINED,
        ):
            self._lift_quarantine(name)
        # decay measures quiet-time from RECOVERY (flap detection wants
        # "relapsed soon after recovering", not "soon after escalating")
        self._touch_attempts(name)
        self._set_state(name, consts.REMEDIATION_STATE_RECOVERED)
        v.state = consts.REMEDIATION_STATE_RECOVERED
        self._record_event(
            "Normal",
            "NodeRemediationRecovered",
            f"node {name} is healthy again; quarantine lifted and "
            f"remediation state cleared",
            dedup_extra=name,
        )
        log.info("node %s recovered (was %s)", name, state)

    def _enter_exhausted(
        self,
        v: NodeVerdict,
        summary: RemediationSummary,
        sid: str,
        disrupted: Set[str],
    ) -> None:
        """Attempt cap hit: quarantine hard and stop escalating — a
        flapping host must not consume restarts and drains forever."""
        self._apply_quarantine(v.name)
        self._set_state(v.name, consts.REMEDIATION_STATE_EXHAUSTED)
        v.state = consts.REMEDIATION_STATE_EXHAUSTED
        disrupted.add(sid)
        summary.newly_disrupted_hosts.append(v.name)
        # a quarantine without a drain would leave already-scheduled TPU
        # jobs riding the known-bad host (NoSchedule only gates NEW
        # placement); best-effort here, retried from the exhausted hold
        self._drain(v)
        self._record_event(
            "Warning",
            "NodeRemediationExhausted",
            f"node {v.name} hit the remediation attempt cap and stays "
            f"quarantined ({'; '.join(v.reasons) or 'flapping health'}); "
            f"clear the {consts.REMEDIATION_STATE_LABEL} label after "
            f"repairing the host to return it to service",
            dedup_extra=v.name,
        )
        log.error(
            "node %s: remediation attempts exhausted; quarantined until "
            "a human intervenes",
            v.name,
        )

    def _restart_operands(self, v: NodeVerdict) -> None:
        """Delete the node's operand pods (the DaemonSets recreate them)
        — the cheapest remediation: a wedged plugin/validator often
        clears with a restart, and revalidation then proves it."""
        deleted = []
        for pod in self.client.list(
            "v1",
            "Pod",
            self.namespace,
            field_selector={"spec.nodeName": v.name},
        ):
            meta = pod["metadata"]
            app = (meta.get("labels") or {}).get("app") or ""
            if not app.startswith("tpu-"):
                # only operand (DaemonSet) pods — every operator-rendered
                # app is tpu-*; a user pod that merely lives in the
                # operator namespace must not be restarted
                continue
            if self.client.delete_if_exists(
                "v1", "Pod", meta["name"], meta.get("namespace", "")
            ):
                deleted.append(meta["name"])
        log.warning(
            "node %s: restarted %d operand pod(s) for remediation (%s)",
            v.name,
            len(deleted),
            ", ".join(deleted) or "none found",
        )

    def _apply_quarantine(self, name: str) -> None:
        """Cordon + repair taint + repair label, remembering whether the
        node was already cordoned (recovery restores, not resets).
        Idempotent: re-asserting an applied quarantine writes nothing."""

        def mutate(node):
            changed = False
            meta = node["metadata"]
            labels = meta.setdefault("labels", {})
            ann = meta.setdefault("annotations", {})
            spec_ = node.setdefault("spec", {})
            if consts.REMEDIATION_INITIAL_STATE_ANNOTATION not in ann:
                ann[consts.REMEDIATION_INITIAL_STATE_ANNOTATION] = (
                    "true" if spec_.get("unschedulable", False) else "false"
                )
                changed = True
            if not spec_.get("unschedulable", False):
                spec_["unschedulable"] = True
                changed = True
            if labels.get(consts.REPAIR_LABEL) != consts.REPAIR_PENDING:
                labels[consts.REPAIR_LABEL] = consts.REPAIR_PENDING
                changed = True
            taints = spec_.setdefault("taints", [])
            if merge_taint(
                taints,
                consts.REPAIR_TAINT_KEY,
                consts.REPAIR_PENDING,
                "NoSchedule",
            ):
                changed = True
            return changed

        mutate_with_retry(self.client, "v1", "Node", name, mutate=mutate)

    def _lift_quarantine(self, name: str) -> None:
        """Untaint, unlabel, and uncordon (unless the node was cordoned
        before remediation touched it)."""

        def mutate(node):
            changed = False
            meta = node["metadata"]
            labels = meta.setdefault("labels", {})
            ann = meta.setdefault("annotations", {})
            spec_ = node.setdefault("spec", {})
            if labels.pop(consts.REPAIR_LABEL, None) is not None:
                changed = True
            taints = spec_.get("taints") or []
            kept = [
                t for t in taints if t.get("key") != consts.REPAIR_TAINT_KEY
            ]
            if len(kept) != len(taints):
                if kept:
                    spec_["taints"] = kept
                else:
                    spec_.pop("taints", None)
                changed = True
            initial = ann.pop(
                consts.REMEDIATION_INITIAL_STATE_ANNOTATION, None
            )
            if initial is not None:
                changed = True
            if initial != "true" and spec_.get("unschedulable", False):
                spec_["unschedulable"] = False
                changed = True
            return changed

        mutate_with_retry(self.client, "v1", "Node", name, mutate=mutate)

    def _drain(self, v: NodeVerdict) -> bool:
        """Evict the node's TPU workload pods through the Eviction
        subresource. A PDB veto (429) DEFERS the step — the FSM stays in
        cordon-drain and the level-triggered requeue retries; the budget
        may free up (a replica turns Ready elsewhere) before we ever
        need to give up. Returns True when the node is clear."""
        from tpu_operator.upgrade.upgrade_state import PodManager

        pods = PodManager(self.client, self.namespace)
        victims = pods.tpu_pods_on_node(v.name)
        if not victims:
            return True
        res = pods.evict_pods(victims, force=False)
        if res.blocked:
            self.drains_vetoed_total += len(res.blocked)
            self._log_once(
                (v.name, "pdb"),
                "node %s: remediation drain vetoed by a disruption budget "
                "(%s); deferring — will retry each pass",
                v.name,
                res.blocked[0],
            )
            return False
        self._logged.discard((v.name, "pdb"))
        if res.skipped:
            # unmanaged (ownerless) pods are never force-deleted by
            # remediation: nothing would recreate the work. The drain
            # holds — SAY SO, once, with the way out (unlike the PDB
            # veto, nothing here ever frees up by itself)
            self._log_once(
                (v.name, "unmanaged"),
                "node %s: remediation drain held by %d unmanaged "
                "(ownerless) TPU pod(s) that will not be force-deleted; "
                "delete them by hand, or set %s=true to leave the node "
                "to a human",
                v.name,
                res.skipped,
                consts.REMEDIATION_SKIP_LABEL,
            )
            return False
        self._logged.discard((v.name, "unmanaged"))
        return not pods.tpu_pods_on_node(v.name)

    # ------------------------------------------------------------------
    # breaker + cleanup
    # ------------------------------------------------------------------
    def _open_breaker(self, summary: RemediationSummary) -> None:
        """Systemic failure: better a degraded-but-diagnosable fleet than
        an operator-inflicted total outage. ZERO node writes happen while
        the breaker is open."""
        if not self._breaker_was_open:
            self._breaker_was_open = True
            self.breaker_opens_total += 1
            log.error(
                "SYSTEMIC node failure: %d of %d TPU nodes unhealthy "
                "(threshold %d) — remediation halted, zero drains issued "
                "(a bad libtpu push must not drain the fleet)",
                summary.unhealthy,
                summary.total,
                summary.breaker_threshold,
            )
        self._record_event(
            "Warning",
            "SystemicNodeFailure",
            f"{summary.unhealthy} of {summary.total} TPU nodes are "
            f"unhealthy (threshold {summary.breaker_threshold}); "
            f"remediation is halted with zero drains until the fleet "
            f"recovers — investigate a fleet-wide cause (bad libtpu "
            f"push, network partition) before clearing",
            dedup_extra="systemic",
        )

    def _cleanup_disabled(self, tpu_nodes: List[Obj]) -> None:
        """Remediation switched off: strip FSM state and lift quarantines
        (the reference's cleanup_state_labels discipline). Touches only
        nodes that actually carry our labels — the steady disabled path
        scans label dicts and writes nothing."""
        for node in tpu_nodes:
            labels = node.get("metadata", {}).get("labels", {}) or {}
            if (
                consts.REMEDIATION_STATE_LABEL not in labels
                and consts.REPAIR_LABEL not in labels
            ):
                continue
            name = node["metadata"]["name"]
            try:
                state = labels.get(consts.REMEDIATION_STATE_LABEL)
                if state in consts.REMEDIATION_DISRUPTED_STATES:
                    self._lift_quarantine(name)
                self._set_state(name, None)

                def mutate(fresh):
                    changed = False
                    meta = fresh["metadata"]
                    fl = meta.setdefault("labels", {})
                    ann = meta.setdefault("annotations", {})
                    if fl.pop(consts.REPAIR_LABEL, None) is not None:
                        changed = True
                    for key in (
                        consts.REMEDIATION_ATTEMPTS_ANNOTATION,
                        consts.REMEDIATION_INITIAL_STATE_ANNOTATION,
                    ):
                        if ann.pop(key, None) is not None:
                            changed = True
                    return changed

                mutate_with_retry(
                    self.client, "v1", "Node", name, mutate=mutate
                )
                log.info(
                    "node %s: remediation disabled; state stripped", name
                )
            except (NotFoundError, ConflictError):
                continue

    # ------------------------------------------------------------------
    def _log_once(self, key: tuple, msg: str, *args) -> None:
        self._logged.log(log, key, msg, *args)

    def _record_event(
        self, etype: str, reason: str, message: str, dedup_extra: str = ""
    ) -> None:
        from tpu_operator.kube.events import cluster_policy_ref, record_event

        record_event(
            self.client,
            self.namespace,
            cluster_policy_ref(),
            etype,
            reason,
            message,
            dedup_extra=dedup_extra,
        )


def _iso_at(epoch: float) -> str:
    from datetime import datetime, timezone

    return datetime.fromtimestamp(epoch, tz=timezone.utc).isoformat()
