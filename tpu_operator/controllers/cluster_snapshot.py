"""Per-reconcile-pass cluster snapshot.

One reconcile pass steps 18 states, and before this existed every
state's readiness check issued its own cluster-wide reads: each
DaemonSet control re-listed all Nodes to count nodeSelector matches
(``object_controls._nodes_wanting``), each OnDelete readiness check
re-listed the namespace pods for its app, and init's runtime/labeling
passes listed Nodes again — O(states × nodes) scans per pass even with
every read served from the informer cache (the requests were free; the
CPU was not; BENCH_r05: 389.7 ms/pass at 1000 nodes).

``ClusterSnapshot`` is the pass-scoped memo the reference gets
implicitly from controller-runtime's cache + per-reconcile locality:
created by ``ClusterPolicyController.begin_pass()``, dropped at pass
end, it memoizes

* the Node list (one informer read per pass, shared frozen views),
* per-nodeSelector match counts (each unique selector costs one scan
  of the memoized node list, then O(1)),
* per-app namespace pod lists (one indexed informer read per app).

Objects inside the snapshot are the informer's SHARED FROZEN views —
the snapshot never copies; consumers follow the same read-only
contract as any cached read. Within one pass the snapshot is
deliberately NOT invalidated by concurrent watch events: a reconcile
computes one consistent verdict from one state of the world and the
level-triggered requeue picks up anything newer (exactly the
controller-runtime cache-read semantics). Writers that change what
they then re-read in the same pass (init's node labeling) refresh the
node list explicitly via ``set_nodes``.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple, Union

from tpu_operator.kube.client import Client, Obj
from tpu_operator.kube.frozen import FrozenList


class ClusterSnapshot:
    """Pass-scoped read memo. Thread-safe: one reconcile pass still owns
    one snapshot (the manager serializes per key), but the write
    pipeline now runs a wave's state controls CONCURRENTLY within that
    pass, and they all share these memos — an RLock guards every
    fill-or-serve (held across the fill: informer reads are
    milliseconds, and double-computing a memo under contention would
    double-count the miss).

    ``namespace`` may be a callable: the snapshot is created at pass
    start, BEFORE ``init()`` resolves the operator namespace on the very
    first pass, so it is read at use time."""

    def __init__(
        self, client: Client, namespace: Union[str, Callable[[], str]]
    ):
        self._lock = threading.RLock()
        self._client = client
        self._namespace_src = namespace
        self._nodes: Optional[List[Obj]] = None
        self._selector_counts: Dict[Tuple[Tuple[str, str], ...], int] = {}
        self._pods_by_app: Dict[str, List[Obj]] = {}
        self._daemonsets: Optional[List[Obj]] = None
        #: Node informer store version captured immediately BEFORE the
        #: memoized node list was taken (None on unversioned clients).
        #: Memos derived from the list (label scan, slice aggregation)
        #: must key on THIS — a version read any later can be newer than
        #: the list and would pin stale derived state under it
        self.nodes_version: Optional[int] = None
        self.hits = 0
        self.misses = 0

    @property
    def _namespace(self) -> str:
        src = self._namespace_src
        return src() if callable(src) else src

    # -- nodes -----------------------------------------------------------
    def _node_list_locked(self) -> List[Obj]:
        """Memoized node list WITHOUT touching the hit/miss counters —
        internal consumers (selector counting) record their own outcome,
        so one consumer read never counts twice."""
        if self._nodes is None:
            fn = getattr(self._client, "store_version", None)
            # read BEFORE listing: an event landing in between makes the
            # list newer than the version, which only ever forces a
            # spurious recompute, never masks the event
            self.nodes_version = fn("v1", "Node") if fn is not None else None
            # shallow FrozenList wrap: the memo is shared pass-wide, so
            # outer-list mutation (sort/append) must fail loudly like
            # any other shared cached view
            self._nodes = FrozenList(self._client.list("v1", "Node"))
        return self._nodes

    def nodes(self) -> List[Obj]:
        """The pass's Node list (shared frozen views; do not mutate)."""
        with self._lock:
            if self._nodes is None:
                self.misses += 1
            else:
                self.hits += 1
            return self._node_list_locked()

    def set_nodes(self, nodes: List[Obj]) -> None:
        """Refresh the memoized node list after a writer changed node
        state it (or a later state) re-reads this pass — init's labeling
        pass calls this with the post-write objects. Selector counts
        derive from the node list, so they reset with it.
        ``nodes_version`` deliberately keeps the ORIGINAL listing's
        version: the writes that motivated the refresh moved the store
        past it, so version-keyed memos correctly refuse to form this
        pass."""
        with self._lock:
            self._nodes = FrozenList(nodes)
            self._selector_counts.clear()

    def count_nodes_matching(self, selector: Dict[str, str]) -> int:
        """How many nodes carry every ``k == v`` of ``selector`` (the
        DaemonSet nodeSelector semantics). Memoized per unique selector;
        18 states re-asking about the same handful of deploy-label
        selectors share one scan each."""
        key = tuple(sorted(selector.items()))
        with self._lock:
            cached = self._selector_counts.get(key)
            if cached is not None:
                self.hits += 1
                return cached
            self.misses += 1
            count = 0
            for node in self._node_list_locked():
                labels = node.get("metadata", {}).get("labels", {}) or {}
                if all(labels.get(k) == v for k, v in selector.items()):
                    count += 1
            self._selector_counts[key] = count
            return count

    # -- pods ------------------------------------------------------------
    def pods_by_app(self, app: str) -> List[Obj]:
        """Operator-namespace pods labeled ``app=<app>`` (shared frozen
        views). One indexed informer read per app per pass."""
        with self._lock:
            cached = self._pods_by_app.get(app)
            if cached is not None:
                self.hits += 1
                return cached
            self.misses += 1
            pods = FrozenList(
                self._client.list(
                    "v1", "Pod", self._namespace, label_selector={"app": app}
                )
            )
            self._pods_by_app[app] = pods
            return pods

    # -- daemonsets ------------------------------------------------------
    def daemonsets(self) -> List[Obj]:
        """The operator namespace's DaemonSets (shared frozen views) —
        one informer read per pass, shared by every disabled state's GC
        sweep and the libtpu generation fan-out's stale-DaemonSet GC
        (``object_controls._delete_daemonsets_like``). Deliberately not
        refreshed after in-pass creates/deletes: the sweeps carry their
        own ``keep`` sets, and ``delete_if_exists`` probes the cache, so
        a pass-start view stays correct."""
        with self._lock:
            if self._daemonsets is None:
                self.misses += 1
                self._daemonsets = FrozenList(
                    self._client.list("apps/v1", "DaemonSet", self._namespace)
                )
            else:
                self.hits += 1
            return self._daemonsets

    # -- observability ---------------------------------------------------
    def stats(self) -> Dict[str, float]:
        with self._lock:
            total = self.hits + self.misses
            return {
                "hits": self.hits,
                "misses": self.misses,
                "hit_rate": round(self.hits / total, 4) if total else 0.0,
                "selectors_memoized": len(self._selector_counts),
                "apps_memoized": len(self._pods_by_app),
                "daemonsets_memoized": 1 if self._daemonsets is not None else 0,
            }
