"""The ordered state machine.

TPU-native analogue of ``controllers/state_manager.go``: a
``ClusterPolicyController`` that ``init()``s cluster facts (k8s version,
container runtime, TPU node labels, generation map), loads the ordered list
of 17 states from asset directories (``controllers/state_manager.go:784-801``),
and ``step()``s through them executing each state's controls and aggregating
readiness (``:933-951``).

Node labeling is the bus (``:473-572``): a node carrying GKE TPU labels (or
the NFD PCI fallback) gets ``tpu.k8s.io/tpu.present=true`` plus per-component
``tpu.k8s.io/tpu.deploy.*`` labels according to its workload configuration
(container vs vm-passthrough, ``:354-414``), and a
``tpu.k8s.io/tpu.generation`` label driving the per-generation libtpu
fan-out (the reference's kernel-version map, ``object_controls.go:555-602``).
"""

from __future__ import annotations

import logging
import os
from typing import Dict, List, Optional, Set, Tuple

from tpu_operator import consts
from tpu_operator.api.v1.clusterpolicy_types import (
    ClusterPolicy,
    State,
    clusterpolicy_from_obj,
)
from tpu_operator.controllers import object_controls
from tpu_operator.controllers.cluster_snapshot import ClusterSnapshot
from tpu_operator.controllers.render_cache import (
    RenderCache,
    render_fingerprint,
)
from tpu_operator.controllers.resource_manager import (
    Resources,
    add_resources_controls,
)
from tpu_operator.kube.client import (
    Client,
    NotFoundError,
    Obj,
    apply_label_delta,
    mutate_with_retry,
)
from tpu_operator.kube.apply import (
    ApplyConflictError,
    ApplySet,
    batch_flush,
)
from tpu_operator.kube.frozen import thaw
from tpu_operator.kube.write_pipeline import BatchLane, WritePipeline
from tpu_operator.obs import LogOnce, flight, trace

log = logging.getLogger("tpu-operator.state")

DEFAULT_ASSETS_DIR = os.environ.get(
    "TPU_OPERATOR_ASSETS", "/opt/tpu-operator"
)

# Ordered list of the reference's 17 states (addState calls,
# controllers/state_manager.go:784-801) plus the TPU-specific
# state-maintenance-handler. Sandbox states run only when
# sandboxWorkloads.enabled.
STATE_ORDER: List[str] = [
    "pre-requisites",
    "state-operator-metrics",
    "state-libtpu",
    "state-runtime",
    "state-operator-validation",
    "state-device-plugin",
    "state-metricsd",
    "state-metrics-exporter",
    "tpu-feature-discovery",
    "state-slice-manager",
    "state-node-status-exporter",
    "state-maintenance-handler",
    "state-vm-manager",
    "state-vm-device-manager",
    "state-sandbox-validation",
    "state-vfio-manager",
    "state-sandbox-device-plugin",
    "state-kata-manager",
]

SANDBOX_STATES: Set[str] = {
    "state-vm-manager",
    "state-vm-device-manager",
    "state-sandbox-validation",
    "state-vfio-manager",
    "state-sandbox-device-plugin",
    "state-kata-manager",
}

# ---------------------------------------------------------------------------
# state ordering DAG
# ---------------------------------------------------------------------------
# Each state's self-contained assets (its own ServiceAccount/RBAC/operand)
# make the container-workload operand states mutually independent: at the
# cluster level everything is level-triggered and hash-idempotent, so the
# ONLY hard edge is that pre-requisites (RuntimeClass, PSP) land first.
# Those states deploy concurrently through the write pipeline. The
# sandbox chain keeps its strict order (vfio unbind / device handoff on a
# real host is genuinely sequenced). A state absent from the independent
# set falls back to the CONSERVATIVE default: it depends on its
# predecessor in STATE_ORDER — i.e. exactly the pre-pipeline behavior.
_PARALLEL_AFTER_PREREQS: Set[str] = {
    "state-operator-metrics",
    "state-libtpu",
    "state-runtime",
    "state-operator-validation",
    "state-device-plugin",
    "state-metricsd",
    "state-metrics-exporter",
    "tpu-feature-discovery",
    "state-slice-manager",
    "state-node-status-exporter",
    "state-maintenance-handler",
}


def _build_state_dag() -> Dict[str, Tuple[str, ...]]:
    dag: Dict[str, Tuple[str, ...]] = {}
    for i, state in enumerate(STATE_ORDER):
        if i == 0:
            dag[state] = ()
        elif state in _PARALLEL_AFTER_PREREQS:
            dag[state] = (STATE_ORDER[0],)
        else:
            dag[state] = (STATE_ORDER[i - 1],)
    return dag


# state -> states that must COMPLETE before it starts (explicit table;
# see _build_state_dag for the conservative-default rule)
STATE_DAG: Dict[str, Tuple[str, ...]] = _build_state_dag()


def state_waves(state_names: List[str]) -> List[List[str]]:
    """Topological levels of ``STATE_DAG`` restricted to
    ``state_names``: states in one wave have no ordering edge between
    them and may deploy concurrently; wave N+1 starts only after wave N
    fully completed (the drain barrier). Order inside a wave follows
    STATE_ORDER, so the serialized fallback (every wave a singleton)
    reproduces the historical sequence exactly."""
    present = set(state_names)
    level: Dict[str, int] = {}

    def lvl(state: str) -> int:
        got = level.get(state)
        if got is not None:
            return got
        deps = [d for d in STATE_DAG.get(state, ()) if d in present]
        got = 1 + max((lvl(d) for d in deps), default=-1)
        level[state] = got
        return got

    waves: Dict[int, List[str]] = {}
    for state in state_names:
        waves.setdefault(lvl(state), []).append(state)
    return [waves[i] for i in sorted(waves)]

# component -> deploy-label key, built once: the per-node label delta
# runs over every node every pass, and re-concatenating ~14 label keys
# per node was a measurable slice of the fleet steady state
_DEPLOY_KEYS: Dict[str, str] = {
    comp: consts.DEPLOY_LABEL_PREFIX + comp
    for comp in (
        *consts.CONTAINER_WORKLOAD_COMPONENTS,
        *consts.VM_WORKLOAD_COMPONENTS,
    )
}


def has_tpu_labels(node: Obj) -> bool:
    """Hardware-fact check (reference ``hasGPULabels``,
    ``controllers/state_manager.go:497-519``): GKE TPU labels or NFD PCI
    vendor 1ae0."""
    labels = node.get("metadata", {}).get("labels", {}) or {}
    if labels.get(consts.GKE_TPU_ACCELERATOR_LABEL):
        return True
    if labels.get(consts.NFD_TPU_PCI_LABEL) == "true":
        return True
    if labels.get(consts.NFD_RULE_TPU_PCI_LABEL) == "true":
        return True
    return False


def node_generation(node: Obj) -> Optional[str]:
    """TPU generation from the GKE accelerator label (per-kernel analogue)."""
    labels = node.get("metadata", {}).get("labels", {}) or {}
    acc = labels.get(consts.GKE_TPU_ACCELERATOR_LABEL, "")
    if acc in consts.GKE_ACCELERATOR_TO_GENERATION:
        return consts.GKE_ACCELERATOR_TO_GENERATION[acc]
    gen = labels.get(consts.TFD_CHIP_TYPE_LABEL)
    if gen in consts.TPU_GENERATIONS:
        return gen
    return None


def node_workload_config(node: Obj) -> str:
    """Per-node workload override (reference ``gpuWorkloadConfiguration``,
    ``controllers/state_manager.go:354-414``)."""
    labels = node.get("metadata", {}).get("labels", {}) or {}
    cfg = labels.get(consts.WORKLOAD_CONFIG_LABEL, consts.WORKLOAD_CONTAINER)
    if cfg not in (consts.WORKLOAD_CONTAINER, consts.WORKLOAD_VM_PASSTHROUGH):
        log.warning(
            "node %s: invalid workload config %r; using %s",
            node["metadata"]["name"],
            cfg,
            consts.WORKLOAD_CONTAINER,
        )
        cfg = consts.WORKLOAD_CONTAINER
    return cfg


def _apply_label_changes(node: Obj, changes: Dict[str, Optional[str]]) -> None:
    """Apply a label delta (value ``None`` = delete) to a MUTABLE node —
    same merge semantics as every ``patch_labels`` implementation."""
    apply_label_delta(node["metadata"].setdefault("labels", {}), changes)


def _label_apply_payload(name: str, changes: Dict[str, Optional[str]]) -> Obj:
    """One node's label delta as a server-side-apply configuration
    (kube/apply.py: a ``None`` leaf is an explicit delete — the same
    delta dialect ``patch_labels`` speaks). Applied through the label
    lane non-forced/non-pruned/update-only: omission never strips other
    keys, conflicts surface instead of reverting foreign writers, and a
    racing node deletion 404s instead of resurrecting the node."""
    return {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": {"name": name, "labels": dict(changes)},
    }


class ClusterPolicyController:
    """reference ``ClusterPolicyController`` (``controllers/state_manager.go:133-156``)."""

    def __init__(self, client: Client, assets_dir: Optional[str] = None):
        self.client = client
        self.assets_dir = assets_dir or (
            DEFAULT_ASSETS_DIR
            if os.path.isdir(DEFAULT_ASSETS_DIR)
            else os.path.join(os.path.dirname(__file__), "..", "..", "assets")
        )
        self.namespace = ""
        self.cp: ClusterPolicy = ClusterPolicy()
        self.cp_obj: Obj = {}
        # user-authored fleet-wide targets (libtpu version / slice
        # layout) BEFORE any rollout rollback override; set by init()
        self.raw_roll_targets: Dict[str, str] = {}
        self.openshift = False
        self.runtime = ""
        self.k8s_version = ""
        self.has_tpu_nodes = False
        self.has_nfd_labels = False
        self.tpu_node_count = 0
        self.tpu_generations: Set[str] = set()
        # the pass's node list: None = never listed (fall back to a
        # fresh list), [] = listed and the cluster really has no nodes —
        # the falsy-list confusion used to send zero-node clusters back
        # to a live LIST per read
        self._nodes_cache: Optional[List[Obj]] = None
        self.state_names: List[str] = []
        self.controls: Dict[str, List[Tuple[str, Obj]]] = {}
        self.resources: Dict[str, Resources] = {}
        self.idx = 0
        self.metrics = None  # wired by the reconciler
        # per-pass read memo (begin_pass/end_pass); None outside a pass
        # so direct init()/step() callers (tests) work without one
        self.snapshot: Optional[ClusterSnapshot] = None
        # process-lifetime memo of rendered manifests, fingerprint-gated
        # by init(); at steady state every control serves its frozen
        # pre-hashed render from here instead of re-rendering
        self.render_cache = RenderCache()
        # DaemonSets whose no-TPU skip was already logged this no-TPU
        # stretch (cleared when TPU nodes appear) — the skip used to
        # logspam every pass on TPU-less clusters. One shared LogOnce
        # implementation (obs/logonce.py) with the set surface intact.
        self.no_tpu_skip_logged = LogOnce()
        # (node store version, sandbox flag) of the last clean labeling
        # pass — while it matches, the O(nodes) label scan is skipped
        self._label_world: Optional[Tuple[int, bool]] = None
        # the Node store version _nodes_cache was listed at — consumers
        # memoizing work derived from that list (slice aggregation) must
        # key on THIS, not on a version read later: a mid-pass node
        # event would otherwise pin stale derived state under the new
        # version
        self._nodes_cache_version: Optional[int] = None
        # version bound to the most recent _list_nodes() result
        self._nodes_listed_at: Optional[int] = None
        # cumulative snapshot counters across passes, for the debug
        # surface + metrics
        self.snapshot_hits_total = 0
        self.snapshot_misses_total = 0
        self.last_snapshot_stats: Dict[str, float] = {}
        # sharded scale-out (tpu_operator/shard.py): the replica's shard
        # ownership view, or None (default single-process operator).
        # When set, the label fan-out writes ONLY nodes this replica
        # covers (owned shards, plus orphaned shards for the shard-0
        # owner) and every node gets its consistent-hash shard stamped
        # as a label (the scoped-re-list selector).
        self.shard_state = None
        # bounded-concurrency write pipeline (kube/write_pipeline.py):
        # the label fan-out and every control's apply ride it; per-key
        # ordering keeps same-object writes serialized while independent
        # objects overlap. WRITE_PIPELINE_DEPTH=1 restores fully serial
        # execution.
        self.writes = WritePipeline(name="reconcile-writes")
        # batched write submission (kube/write_pipeline.BatchLane over
        # kube/apply.py): sibling writes group-commit into multi-object
        # APPLY submissions with per-item fan-back. Three lanes, one per
        # write family (each is one pipeline key, so families overlap
        # while staying internally FIFO):
        # - label lane: per-node label applies (delta-style — force off
        #   so a concurrent human override CONFLICTS instead of being
        #   reverted; prune off so omission never strips labels;
        #   update_only so a racing node deletion 404s, never
        #   resurrects the node)
        # - apply lane: rendered-manifest applies (force on — the
        #   operator owns its operands; prune on — fields it stopped
        #   rendering are removed by omission)
        self.label_lane = BatchLane(
            self.writes,
            lambda payloads: batch_flush(
                self.client, payloads, force=False, prune=False,
                update_only=True,
            ),
            name="node-labels",
            # the fleet-wide label/verdict fan-out is the lane with real
            # volume (2×N items at N nodes): overlap a few batches while
            # per-node FIFO holds (shard choice is item_key-stable)
            shards=4,
        )
        self.apply_lane = BatchLane(
            self.writes,
            lambda payloads: batch_flush(
                self.client, payloads, force=True, prune=True
            ),
            name="manifests",
        )
        # apply-set membership (kube/apply.py): every object a pass
        # intends registers here; a completed pass prunes what an
        # earlier pass applied but this one abandoned. Persisted via
        # the warm-restart journal.
        self.applyset = ApplySet()
        # state runners for DAG waves (lazily built; only spun up when a
        # wave actually holds more than one state)
        self._state_pool = None

    def batch_stats(self) -> Dict[str, object]:
        """Aggregated batch-lane observability (per-lane detail plus
        the headline fill average the fleet bench prints)."""
        lanes = [self.label_lane.stats(), self.apply_lane.stats()]
        items = sum(s["items_total"] for s in lanes)
        batches = sum(s["batches_total"] for s in lanes)
        return {
            "lanes": {s["name"]: s for s in lanes},
            "items_total": items,
            "items_failed_total": sum(
                s["items_failed_total"] for s in lanes
            ),
            "batches_total": batches,
            "fill_avg": round(items / batches, 2) if batches else 0.0,
        }

    def prune_abandoned(self) -> List[Tuple[str, str, str, str]]:
        """Seal the apply-set pass and delete what it abandoned: keys an
        earlier committed pass applied but this one no longer intends.
        Only keys the set has SEEN are ever returned by ``commit``, so
        pruning can never touch an object this operator didn't write.
        Best-effort per key — a failed delete stays a member and is
        retried by the next pass's commit."""
        abandoned = self.applyset.commit()
        for av, kind, ns, name in abandoned:
            try:
                if self.client.delete_if_exists(av, kind, name, ns):
                    log.info(
                        "pruned abandoned %s %s/%s (apply-set: no "
                        "current pass intends it)",
                        kind,
                        ns or "-",
                        name,
                    )
                # already-gone counts too: the abandonment is resolved
                self.applyset.record_pruned()
            except Exception:
                log.exception(
                    "failed to prune abandoned %s %s/%s", kind, ns, name
                )
                # keep retrying on later passes: an unpruned abandoned
                # object is a leak, and only membership makes commit
                # return it again
                self.applyset.retain((av, kind, ns, name))
        return abandoned

    # ------------------------------------------------------------------
    # pass lifecycle (controller-runtime gets this locality implicitly:
    # one cache, one reconcile invocation; here the snapshot carries it)
    # ------------------------------------------------------------------
    def begin_pass(self) -> ClusterSnapshot:
        self.snapshot = ClusterSnapshot(self.client, lambda: self.namespace)
        return self.snapshot

    def end_pass(self) -> Dict[str, float]:
        # drain runs on EVERY pass exit, including exception paths (the
        # reconciler calls end_pass from a finally): a pass that died
        # mid-fan-out (a label patch exhausting its retries) must not
        # leave stragglers writing into the next pass's snapshot. Errors
        # already surfaced through the per-future handlers; this only
        # clears the aggregate so a dead pass's errors don't leak into
        # the next pass's drain.
        try:
            self.writes.drain()
        except Exception:
            log.exception("write pipeline drain failed at pass end")
        snap, self.snapshot = self.snapshot, None
        if snap is None:
            return {}
        self.last_snapshot_stats = snap.stats()
        self.snapshot_hits_total += snap.hits
        self.snapshot_misses_total += snap.misses
        return self.last_snapshot_stats

    def snapshot_stats(self) -> Dict[str, float]:
        """Debug-surface payload: last pass's hit/miss profile plus the
        process-lifetime totals."""
        total = self.snapshot_hits_total + self.snapshot_misses_total
        return {
            "last_pass": self.last_snapshot_stats,
            "hits_total": self.snapshot_hits_total,
            "misses_total": self.snapshot_misses_total,
            "hit_rate_total": (
                round(self.snapshot_hits_total / total, 4) if total else 0.0
            ),
        }

    # ------------------------------------------------------------------
    # init (reference controllers/state_manager.go:743-887)
    # ------------------------------------------------------------------
    def decode_primary(self, cp_obj: Obj) -> None:
        """The CR-decode preamble shared by the full pass (``init``)
        and the sharded scoped pass: the two MUST agree on the
        effective desired state or scoped replicas' label decisions
        diverge from the owner's.

        Applies the rollout rollback override (controllers/rollout.py):
        while the rollout ledger says rolled-back, the EFFECTIVE
        desired version/layout is the recorded previous value — applied
        to this pass's private CR copy BEFORE decoding/fingerprinting
        so rendering, the upgrade FSM's desired hashes and the
        re-partition roller all converge the fleet back. The raw
        user-authored targets are kept for the orchestrator."""
        from tpu_operator.controllers.rollout import apply_override

        self.cp_obj = cp_obj
        self.raw_roll_targets = apply_override(cp_obj)
        self.cp = clusterpolicy_from_obj(cp_obj)
        self.namespace = os.environ.get(consts.OPERATOR_NAMESPACE_ENV, "")
        if not self.namespace:
            # reference exits the process so the pod CrashLoops by design
            # (controllers/state_manager.go:750-758)
            raise RuntimeError(
                f"{consts.OPERATOR_NAMESPACE_ENV} environment variable not set"
            )

    def init(self, cp_obj: Obj) -> None:
        self.decode_primary(cp_obj)
        self.idx = 0

        self.k8s_version = self._get_kubernetes_version()

        if not self.state_names:
            self._add_states()

        if self.cp.spec.psa.is_enabled():
            self.set_pod_security_labels_for_namespace()

        self.label_tpu_nodes()
        self.apply_upgrade_auto_annotation()
        self.runtime = self.get_runtime()
        # every render input is now known: gate the render cache on the
        # desired-state fingerprint — a spec/runtime/uid change clears
        # it, a generation-set change drops only the fan-out entries
        self.render_cache.begin_pass(
            render_fingerprint(
                self.cp_obj, self.namespace, self.runtime, self.openshift
            ),
            self.tpu_generations,
        )
        # apply-set pass bracket: every object a state intends registers
        # during run_states (apply_with_hash), and the reconciler commits
        # a CLEAN pass — abandoned objects (renamed DaemonSets, dropped
        # generation fan-outs) are pruned with no hand-written delete
        # path. An errored or aborted pass calls abort instead, so a
        # half-registered picture can never prune live objects.
        self.applyset.begin_pass()
        log.info(
            "cluster init: k8s=%s runtime=%s tpuNodes=%s generations=%s",
            self.k8s_version,
            self.runtime,
            self.has_tpu_nodes,
            sorted(self.tpu_generations),
        )

    def _list_nodes(self) -> List[Obj]:
        """The pass's node list — shared frozen views via the snapshot
        when a pass is open, a direct (cached) list otherwise. Stamps
        ``_nodes_listed_at`` with the store version BOUND TO THE LIST
        (captured before whichever listing actually produced it), which
        is what every list-derived memo must key on."""
        if self.snapshot is not None:
            nodes = self.snapshot.nodes()
            self._nodes_listed_at = self.snapshot.nodes_version
            return nodes
        self._nodes_listed_at = self._node_store_version()
        return self.client.list("v1", "Node")

    def _get_kubernetes_version(self) -> str:
        # no /version endpoint in the Client interface; derive from nodes
        for node in self._list_nodes():
            v = node.get("status", {}).get("nodeInfo", {}).get("kubeletVersion")
            if v:
                return v
        return ""

    def _add_states(self) -> None:
        """Load every state's assets (reference ``addState`` ×17,
        ``controllers/state_manager.go:784-801``)."""
        for state in STATE_ORDER:
            path = os.path.join(self.assets_dir, state)
            if not os.path.isdir(path):
                raise FileNotFoundError(f"asset dir missing: {path}")
            res, controls = add_resources_controls(path, self.openshift)
            self.state_names.append(state)
            self.resources[state] = res
            self.controls[state] = controls

    # ------------------------------------------------------------------
    # node labeling (reference labelGPUNodes, :473-572)
    # ------------------------------------------------------------------
    def _node_store_version(self) -> Optional[int]:
        fn = getattr(self.client, "store_version", None)
        return fn("v1", "Node") if fn is not None else None

    def label_tpu_nodes(self) -> None:
        # world-unchanged short-circuit: the label delta is a pure
        # function of (node labels, sandbox gating). When the Node store
        # version BOUND TO THIS PASS'S LIST matches the last pass that
        # wrote nothing, every label is still converged and every
        # cluster fact (has_tpu_nodes, generations, counts) still holds
        # — skip the O(nodes) scan entirely. Any node event, any label
        # write (ours or another actor's) moves the store past the
        # listed-at version and forces a full rescan; clients without a
        # versioned store always rescan. The version must come from the
        # LISTING moment, not a fresh read: an event landing between an
        # earlier consumer's list (e.g. _get_kubernetes_version) and
        # this method would otherwise pin the stale list under the newer
        # version and mask the event for every later pass.
        nodes = self._list_nodes()
        version = self._nodes_listed_at
        world = (
            (version, self.cp.spec.sandbox_enabled())
            if version is not None
            else None
        )
        if world is not None and world == self._label_world:
            self._nodes_cache = nodes
            self._nodes_cache_version = version
            return
        self._label_world = None
        self._nodes_cache_version = version
        self.has_tpu_nodes = False
        self.has_nfd_labels = False
        self.tpu_generations = set()
        self.tpu_node_count = 0
        # phase 1 — pure scan over SHARED frozen views: cluster facts +
        # the per-node label delta; nothing is copied or written yet
        results: List[Optional[Obj]] = [None] * len(nodes)
        to_write: List[Tuple[int, Obj, Dict[str, Optional[str]]]] = []
        for i, node in enumerate(nodes):
            labels = node["metadata"].get("labels") or {}
            if any(k.startswith("feature.node.kubernetes.io/") for k in labels):
                self.has_nfd_labels = True
            if has_tpu_labels(node):
                self.has_tpu_nodes = True
                self.tpu_node_count += 1
                gen = node_generation(node)
                if gen:
                    self.tpu_generations.add(gen)
            changes = self._node_label_changes(node)
            if changes:
                to_write.append((i, node, changes))
            else:
                results[i] = node
        if self.shard_state is not None and to_write:
            # sharded write partition: another replica owns (and
            # converges) the skipped nodes' labels; carrying the
            # unmodified view forward keeps THIS pass's aggregation
            # honest about the world it actually read
            kept = []
            for i, node, changes in to_write:
                if self.shard_state.covers_node_obj(node):
                    kept.append((i, node, changes))
                else:
                    results[i] = node
            skipped_foreign = len(to_write) - len(kept)
            to_write = kept
        else:
            skipped_foreign = 0
        # a pass that SKIPPED foreign-owned deltas must not memoize as
        # clean: if that owner dies, its lease expiring moves no store
        # version, and a memoized skip would never hand those nodes to
        # the shard-0 safety net
        wrote = bool(to_write) or skipped_foreign > 0
        # phase 2 — the write fan-out rides the batched label lane: each
        # node's delta is ONE apply payload, and the lane group-commits
        # whatever queued while the previous batch was on the wire into
        # multi-object APPLY submissions (per-item fan-back keeps each
        # node's outcome its own). Non-forced: a foreign writer's label
        # (a human pause override landing mid-scan) CONFLICTS instead of
        # being reverted — the guarantee the old rv-conditioned patch
        # provided, without its false conflicts against unrelated
        # writers. The conflict path recomputes from a live read.
        if to_write:
            # flight timeline: one aggregate event per writing pass (a
            # per-node event at fleet scale would flush the ring), with
            # a small sample of the touched nodes for the post-mortem
            flight.record(
                "labels.write",
                nodes=len(to_write),
                sample=[n["metadata"]["name"] for _, n, _ in to_write[:8]],
            )
            with trace.span("pass.label_writes", nodes=len(to_write)):
                futs = [
                    (
                        i,
                        node,
                        changes,
                        self.label_lane.submit(
                            ("Node", "", node["metadata"]["name"]),
                            _label_apply_payload(
                                node["metadata"]["name"], changes
                            ),
                        ),
                    )
                    for i, node, changes in to_write
                ]
                for i, node, changes, fut in futs:
                    results[i] = self._label_outcome(node, changes, fut)
        self._nodes_cache = final_nodes = [
            n for n in results if n is not None
        ]
        if self.has_tpu_nodes:
            # next no-TPU stretch (nodes drained away) logs the skips
            # again — once per transition, not once per process
            self.no_tpu_skip_logged.clear()
        if self.snapshot is not None:
            # later states re-read nodes through the snapshot; give them
            # the post-label state, not the pass-start listing
            self.snapshot.set_nodes(final_nodes)
        if world is not None and not wrote:
            # a clean pass (nothing needed writing): its outcome stays
            # valid until the node store moves again. A pass that wrote
            # is never memoized — its own write-throughs moved the store
            self._label_world = world

    def _label_outcome(
        self, node: Obj, changes: Dict[str, Optional[str]], fut
    ) -> Optional[Obj]:
        """Resolve one node's batched label apply. Node labels are the
        shared bus: TFD, the slice manager, the maintenance handler, the
        upgrade FSM — and humans pausing components — all write
        concurrently. The lane's apply is non-forced and non-pruned, so
        a foreign writer's concurrent label (a just-written "paused-*"
        override) surfaces as ``ApplyConflictError`` instead of being
        silently reverted, and the recompute path re-decides from a
        LIVE read. ``update_only`` makes a racing node deletion a 404,
        never a ghost resurrection. Returns the node to carry forward,
        or None when it vanished."""
        name = node["metadata"]["name"]
        try:
            return fut.result()
        except ApplyConflictError:
            return self._relabel_fresh(name, node, changes)
        except NotFoundError:
            log.info("node %s vanished during labeling", name)
            return None

    def _relabel_fresh(
        self,
        name: str,
        stale_node: Obj,
        stale_changes: Dict[str, Optional[str]],
    ) -> Optional[Obj]:
        """Conflict path of the non-forced label apply: re-read the
        node LIVE, RECOMPUTE the delta against what the other writer
        actually wrote (the recompute READS their labels — a pause
        override changes the desired state instead of being clobbered),
        and re-apply FORCED: having decided from the fresh world, the
        remaining delta is genuinely ours to win, exactly what the old
        fresh-rv conditional patch expressed. Returns the node to carry
        forward, or None when it vanished."""
        try:
            fresh = getattr(self.client, "get_live", self.client.get)(
                "v1", "Node", name
            )
        except NotFoundError:
            log.info("node %s vanished during labeling", name)
            return None
        changes = self._node_label_changes(fresh)
        if not changes:
            return fresh  # the other writer's state needs nothing
        try:
            return self.client.apply_ssa(
                _label_apply_payload(name, changes),
                force=True,
                prune=False,
                update_only=True,
            )
        except NotFoundError:
            log.info("node %s vanished during labeling", name)
            return None
        except Exception:
            log.warning(
                "node %s label conflict retry failed; the requeue will "
                "converge it",
                name,
                exc_info=True,
            )
            mutable = thaw(stale_node)
            _apply_label_changes(mutable, stale_changes)
            return mutable

    def _node_label_changes(self, node: Obj) -> Dict[str, Optional[str]]:
        """Desired operator-label delta for one node as ``{key: value}``
        (``None`` = delete) — a PURE computation over a (possibly
        frozen) node view; {} in the labeled steady state."""
        labels = node["metadata"].get("labels") or {}
        changes: Dict[str, Optional[str]] = {}
        if has_tpu_labels(node):
            gen = node_generation(node)
            if gen and labels.get(f"{consts.GROUP}/tpu.generation") != gen:
                changes[f"{consts.GROUP}/tpu.generation"] = gen
            if labels.get(consts.TPU_PRESENT_LABEL) != "true":
                changes[consts.TPU_PRESENT_LABEL] = "true"
            if self.shard_state is not None:
                # consistent-hash shard stamp: the server-side selector
                # a journal-stale failover re-lists ONE shard with
                want = str(self.shard_state.shard_of_node_obj(node))
                if labels.get(consts.SHARD_LABEL) != want:
                    changes[consts.SHARD_LABEL] = want
            changes.update(self._state_label_changes(node, labels))
        elif labels.get(consts.TPU_PRESENT_LABEL):
            # TPU removed from node: strip all operator labels
            # (reference removeAllGPUStateLabels)
            for key in labels:
                if key.startswith(f"{consts.GROUP}/"):
                    changes[key] = None
        return changes

    def _state_label_changes(
        self, node: Obj, labels: Dict[str, str]
    ) -> Dict[str, Optional[str]]:
        """Per-workload-config deploy labels (reference
        ``gpuWorkloadConfiguration.updateGPUStateLabels``, ``:354-414``)."""
        cfg = node_workload_config(node)
        if cfg == consts.WORKLOAD_VM_PASSTHROUGH and self.cp.spec.sandbox_enabled():
            enable = consts.VM_WORKLOAD_COMPONENTS
            disable = consts.CONTAINER_WORKLOAD_COMPONENTS
        else:
            enable = consts.CONTAINER_WORKLOAD_COMPONENTS
            disable = consts.VM_WORKLOAD_COMPONENTS
        changes: Dict[str, Optional[str]] = {}
        for comp in enable:
            key = _DEPLOY_KEYS[comp]
            value = labels.get(key)
            if value == "true":
                continue
            # don't fight a human override of "false"/"paused-*"
            # (reference keeps existing explicit disables)
            if value == "false" or (
                isinstance(value, str) and value.startswith("paused-")
            ):
                continue
            changes[key] = "true"
        for comp in disable:
            key = _DEPLOY_KEYS[comp]
            if key in labels:
                changes[key] = None
        return changes

    # ------------------------------------------------------------------
    # PSA labeling (reference setPodSecurityLabelsForNamespace, :590-638)
    # ------------------------------------------------------------------
    def set_pod_security_labels_for_namespace(self) -> None:
        if self.client.get_or_none("v1", "Namespace", self.namespace) is None:
            return
        desired = {
            consts.PSA_LABEL_PREFIX + "enforce": "privileged",
            consts.PSA_LABEL_PREFIX + "audit": "privileged",
            consts.PSA_LABEL_PREFIX + "warn": "privileged",
        }

        def mutate(ns):
            labels = ns["metadata"].setdefault("labels", {})
            if all(labels.get(k) == v for k, v in desired.items()):
                return False
            labels.update(desired)
            return True

        mutate_with_retry(self.client, "v1", "Namespace", self.namespace, mutate=mutate)

    # ------------------------------------------------------------------
    # upgrade annotation (reference applyDriverAutoUpgradeAnnotation, :416-469)
    # ------------------------------------------------------------------
    def apply_upgrade_auto_annotation(self) -> None:
        pol = self.cp.spec.libtpu.upgrade_policy
        enabled = bool(pol and pol.is_auto_upgrade_enabled())
        if (
            self.client.get_or_none(
                consts.API_VERSION, consts.CLUSTER_POLICY_KIND, self.cp.name
            )
            is None
        ):
            return
        want = "true" if enabled else None

        def mutate(obj):
            ann = obj["metadata"].setdefault("annotations", {})
            if want is None and consts.UPGRADE_ENABLED_ANNOTATION in ann:
                del ann[consts.UPGRADE_ENABLED_ANNOTATION]
                return True
            if want and ann.get(consts.UPGRADE_ENABLED_ANNOTATION) != want:
                ann[consts.UPGRADE_ENABLED_ANNOTATION] = want
                return True
            return False

        # the CR is shared with the user's spec edits and the status
        # writer: conflict-retried like every shared-object write
        mutate_with_retry(
            self.client,
            consts.API_VERSION,
            consts.CLUSTER_POLICY_KIND,
            self.cp.name,
            mutate=mutate,
        )

    # ------------------------------------------------------------------
    # runtime discovery (reference getRuntime, :704-741)
    # ------------------------------------------------------------------
    def get_runtime(self) -> str:
        runtime = self.cp.spec.operator.default_runtime or "containerd"
        # `is not None`, NOT truthiness: a listed-but-empty cluster
        # ([] is falsy) must serve the empty pass result, not issue a
        # fresh LIST per call
        nodes = (
            self._nodes_cache
            if self._nodes_cache is not None
            else self._list_nodes()
        )
        for node in nodes:
            if not has_tpu_labels(node):
                continue
            info = (
                node.get("status", {})
                .get("nodeInfo", {})
                .get("containerRuntimeVersion", "")
            )
            for name in ("containerd", "docker", "cri-o", "crio"):
                if info.startswith(name):
                    return "crio" if name in ("cri-o", "crio") else name
        return runtime

    # ------------------------------------------------------------------
    # state gating (reference isStateEnabled, :964-1004)
    # ------------------------------------------------------------------
    def is_state_enabled(self, state_name: str) -> bool:
        spec = self.cp.spec
        mapping = {
            "pre-requisites": True,
            "state-operator-metrics": True,
            "state-libtpu": spec.libtpu.is_enabled(),
            "state-runtime": spec.runtime.is_enabled(),
            # operator validation cannot be disabled (reference :996-997)
            "state-operator-validation": True,
            "state-device-plugin": spec.device_plugin.is_enabled(),
            "state-metricsd": spec.metricsd.is_enabled(),
            "state-metrics-exporter": spec.metrics_exporter.is_enabled(),
            "tpu-feature-discovery": spec.tfd.is_enabled(),
            "state-slice-manager": spec.slice_manager.is_enabled(),
            "state-node-status-exporter": spec.node_status_exporter.is_enabled(),
            # TPU-specific 18th state (no reference analogue): opt-in
            "state-maintenance-handler": spec.maintenance_handler.is_enabled(),
            "state-vm-manager": spec.sandbox_enabled()
            and spec.vm_manager.is_enabled(),
            "state-vm-device-manager": spec.sandbox_enabled()
            and spec.vm_device_manager.is_enabled(),
            "state-sandbox-validation": spec.sandbox_enabled(),
            "state-vfio-manager": spec.sandbox_enabled()
            and spec.vfio_manager.is_enabled(),
            "state-sandbox-device-plugin": spec.sandbox_enabled()
            and spec.sandbox_device_plugin.is_enabled(),
            "state-kata-manager": spec.sandbox_enabled()
            and spec.kata_manager.is_enabled(),
        }
        return bool(mapping.get(state_name, True))

    # ------------------------------------------------------------------
    # stepping (reference step()/last(), :933-964)
    # ------------------------------------------------------------------
    def step(self) -> str:
        """Run all controls of the current state; aggregate readiness
        (reference ``step``, ``controllers/state_manager.go:933-951``)."""
        status = self.run_state(self.state_names[self.idx])
        self.idx += 1
        return status

    def run_state(self, state: str) -> str:
        """Execute one state's controls in asset order (ServiceAccount →
        RBAC → operand) and aggregate readiness. One state's applies are
        few and hash-gated (at steady state each control is a single
        cached read), so they run inline on the state's worker; the
        WIDE concurrency comes from ``run_states`` running independent
        STATES of one DAG wave in parallel, and from the true N-wide
        fan-outs (node labels, slice labels) riding the write pipeline
        per object — a per-control thread handoff here would cost more
        than the steady-state control does."""
        overall = State.READY
        with trace.span("state.step", state=state) as sp:
            for control_name, obj in self.controls[state]:
                fn = object_controls.CONTROLS[control_name]
                status = fn(self, state, obj)
                if status == State.NOT_READY:
                    overall = State.NOT_READY
            sp.set("status", overall)
        return overall

    def run_states(self, concurrent: Optional[bool] = None):
        """Execute ALL states honoring ``STATE_DAG``: states of one
        topological wave run concurrently (their applies overlapping on
        the write pipeline's workers), with a barrier between waves.
        Per-state outcomes — a ``State`` value or the exception the
        state raised — come back in ``STATE_ORDER`` order so
        status/Events/metrics stay deterministic. A raising state never
        aborts its wave (the reconciler's error-isolation contract);
        ``idx`` is parked at the end so ``last()`` holds.

        ``concurrent=False`` runs every wave's states sequentially on
        the calling thread. The reconciler passes this on steady
        (already-Ready) passes: a converged pass issues ZERO writes, so
        fanning its pure cached reads across threads would buy nothing
        and pay scheduler latency per state — the 50 ms steady-pass
        bench gate rides on that. Converging passes (anything not yet
        Ready) keep the wave parallelism, which is where the writes
        are. ``WRITE_PIPELINE_DEPTH=1`` forces sequential always."""
        results: Dict[str, object] = {}
        if concurrent is None:
            concurrent = True

        def run_catching(state: str) -> object:
            try:
                return self.run_state(state)
            except Exception as e:  # noqa: BLE001 - isolated per state
                return e

        for wave_idx, wave in enumerate(state_waves(self.state_names)):
            with trace.span(
                "pass.wave", wave=wave_idx, states=len(wave),
                concurrent=bool(
                    concurrent and len(wave) > 1 and self.writes.depth > 1
                ),
            ):
                if (
                    len(wave) == 1
                    or not concurrent
                    or self.writes.depth == 1
                ):
                    for state in wave:
                        results[state] = run_catching(state)
                    continue
                pool = self._ensure_state_pool()
                futs = [(s, pool.submit(run_catching, s)) for s in wave]
                # the barrier wait gets its OWN layer: the pooled state
                # spans run on other threads (roots there), so without
                # this the wave span's blocked-on-futures time would
                # read as "pass" SELF time while the same milliseconds
                # also count under "state" — the layer breakdown would
                # misattribute exactly the concurrent passes it exists
                # to explain
                with trace.span("wait.states", states=len(wave)):
                    for state, fut in futs:
                        results[state] = fut.result()
        self.idx = len(self.state_names)
        return [(s, results[s]) for s in self.state_names]

    def _ensure_state_pool(self):
        """Lazily-built executor for wave-mate states. Sized to the
        widest possible wave; its threads mostly BLOCK on pipeline
        futures, so the real I/O concurrency cap stays the pipeline
        depth."""
        if self._state_pool is None:
            import weakref
            from concurrent.futures import ThreadPoolExecutor

            self._state_pool = ThreadPoolExecutor(
                max_workers=max(2, len(_PARALLEL_AFTER_PREREQS)),
                thread_name_prefix="state-wave",
            )
            weakref.finalize(
                self,
                lambda ex=self._state_pool: ex.shutdown(wait=False),
            )
        return self._state_pool

    def last(self) -> bool:
        return self.idx == len(self.state_names)

    def current_state(self) -> str:
        return self.state_names[min(self.idx, len(self.state_names) - 1)]
