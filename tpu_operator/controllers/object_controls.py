"""Per-kind object controls and per-operand transforms.

TPU-native analogue of ``controllers/object_controls.go`` (the reference's
4.5k-line heart): each control is ``fn(n, state_name, obj) -> State`` where
``n`` is the ``ClusterPolicyController``. Controls

* fill the operator namespace and owner reference,
* run the per-operand ``transform_*`` keyed by DaemonSet name
  (reference dispatch ``controllers/object_controls.go:654-698``),
* annotate with a content hash and only update on drift
  (``nvidia.com/last-applied-hash`` pattern, ``:3890-3929``),
* and report readiness (``:3082-3177``).

TPU-specific redesigns:

* the per-kernel precompiled-driver fan-out (``:3405-3441``) becomes a
  per-TPU-generation libtpu fan-out (one DaemonSet per v4/v5e/v5p/v6e
  present in the cluster), with the same stale-DaemonSet garbage collection
  (``:3363-3403``);
* OnDelete readiness uses the operand hash stamped into the pod template
  (we control the template) instead of ControllerRevision spelunking
  (``:3107-3177``).
"""

from __future__ import annotations

import copy
import hashlib
import json
import logging
from time import perf_counter
from typing import Any, Callable, Dict, List, Optional

from tpu_operator import consts
from tpu_operator.api.v1.clusterpolicy_types import State
from tpu_operator.kube.client import ConflictError
from tpu_operator.kube.frozen import freeze
from tpu_operator.obs import trace
from tpu_operator.obs.logonce import LogOnce

log = logging.getLogger("tpu-operator.controls")

Obj = Dict[str, Any]

PLACEHOLDER = "FILLED BY THE OPERATOR"


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def compute_hash(obj: Obj) -> str:
    """Deterministic content hash of an object's spec+metadata (reference
    ``getDaemonsetHash``/hashstructure, ``controllers/object_controls.go:3890-3929``).

    Transforms must be deterministic or the hash churns and the operator
    rewrites objects every reconcile (reference bug class: the sorted
    mount-path workaround at ``:2907-2912``).
    """
    meta = obj.get("metadata", {})
    core = {
        "labels": meta.get("labels", {}),
        "annotations": {
            k: v
            for k, v in (meta.get("annotations", {}) or {}).items()
            if k != consts.LAST_APPLIED_HASH_ANNOTATION
        },
        "spec": obj.get("spec", {}),
        "data": obj.get("data", {}),
        "rules": obj.get("rules", []),
        "handler": obj.get("handler", ""),
    }
    blob = json.dumps(core, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def set_owner_reference(n, obj: Obj) -> None:
    """Owner the object to the ClusterPolicy so cluster GC cleans up
    (reference ``SetControllerReference``)."""
    meta = n.cp_obj.get("metadata", {})
    uid = meta.get("uid")
    if not uid:
        return
    obj.setdefault("metadata", {})["ownerReferences"] = [
        {
            "apiVersion": consts.API_VERSION,
            "kind": consts.CLUSTER_POLICY_KIND,
            "name": meta.get("name", ""),
            "uid": uid,
            "controller": True,
            "blockOwnerDeletion": True,
        }
    ]


def _fill_namespace(n, obj: Obj) -> None:
    meta = obj.setdefault("metadata", {})
    if meta.get("namespace") == PLACEHOLDER or (
        "namespace" in meta and not meta["namespace"]
    ):
        meta["namespace"] = n.namespace
    # cluster-scoped kinds keep no namespace
    if obj.get("kind") in (
        "ClusterRole",
        "ClusterRoleBinding",
        "RuntimeClass",
        "PriorityClass",
        "PodSecurityPolicy",
    ):
        meta.pop("namespace", None)
    # RoleBinding/ClusterRoleBinding subjects reference the namespace
    for subject in obj.get("subjects", []) or []:
        if subject.get("namespace") == PLACEHOLDER or not subject.get("namespace"):
            if subject.get("kind") == "ServiceAccount":
                subject["namespace"] = n.namespace


def apply_with_hash(n, obj: Obj, precomputed_hash: Optional[str] = None) -> str:
    """Hash-gated server-side APPLY; returns the hash.

    The steady state costs ZERO requests: the cached object's
    ``last-applied-hash`` annotation matches the rendered hash and
    nothing is sent. Any drift (or absence) costs exactly ONE request —
    a force-owned APPLY (``kube/apply.py``) that creates-or-merges
    server-side under field ownership. The old GET-compare-PUT shape is
    gone entirely, and with it the 409 path that re-GET and re-PUT the
    whole object: an APPLY carries no resourceVersion, so a concurrent
    kubelet status stamp can no longer conflict with a manifest write
    at all, and fields the operator stopped rendering are pruned by
    omission instead of surviving a merge.

    With ``precomputed_hash`` (the render-cache path) ``obj`` is a
    pre-annotated — and possibly FROZEN — rendered manifest: the hash
    is not recomputed and the object is never mutated here (every
    ``apply_ssa`` implementation treats its input as read-only).

    Every intended object also registers in the pass's apply-set
    (``n.applyset``) — including on the no-op branch — so a later pass
    that stops intending it (a renamed DaemonSet, a dropped generation)
    prunes it with no hand-written delete path."""
    if precomputed_hash is None:
        h = compute_hash(obj)
        obj.setdefault("metadata", {}).setdefault("annotations", {})[
            consts.LAST_APPLIED_HASH_ANNOTATION
        ] = h
    else:
        h = precomputed_hash
    av, kind = obj["apiVersion"], obj["kind"]
    meta = obj["metadata"]
    aps = getattr(n, "applyset", None)
    if aps is not None:
        aps.seen(av, kind, meta.get("namespace", ""), meta["name"])
    existing = n.client.get_or_none(av, kind, meta["name"], meta.get("namespace", ""))
    if existing is not None:
        old_hash = (
            existing.get("metadata", {}).get("annotations", {}) or {}
        ).get(consts.LAST_APPLIED_HASH_ANNOTATION)
        if old_hash == h:
            return h  # no-op: idempotent reconcile, zero requests
    with trace.span("apply.object", kind=kind, name=meta["name"]):
        _submit_apply(n, obj)
    return h


def _submit_apply(n, obj: Obj) -> Obj:
    """One manifest APPLY, batched when the controller carries an apply
    lane: concurrent states of a DAG wave submitting sibling manifests
    group-commit into multi-object submissions (per-item status
    fan-back keeps each control's error its own). Controllers without a
    lane (unit tests driving a control directly) apply inline."""
    lane = getattr(n, "apply_lane", None)
    if lane is not None:
        return lane.submit(
            (obj.get("kind", ""), obj["metadata"].get("namespace", ""),
             obj["metadata"].get("name", "")),
            obj,
        ).result()
    return n.client.apply_ssa(obj, force=True, prune=True)


def _render_memo(
    n,
    state_name: str,
    obj: Obj,
    render: Callable[[Obj], Obj],
    generation: Optional[str] = None,
):
    """Memoized render-transform-hash. Returns ``(rendered, hash)``
    where ``rendered`` MAY be a shared frozen view (read-only; see
    ``render_cache.py``).

    On a fingerprint-valid cache hit the deep copy, the transform chain
    and ``compute_hash`` are all skipped. On a miss, ``render`` runs,
    the content hash is computed and annotated once, and the frozen
    result is stored for every later pass. Controllers without a
    ``render_cache`` (unit tests driving a control directly) render
    every time, exactly as before."""
    cache = getattr(n, "render_cache", None)
    key = (
        state_name,
        obj.get("kind", ""),
        obj.get("metadata", {}).get("name", ""),
        generation or "",
    )
    if cache is not None:
        hit = cache.lookup(key)
        if hit is not None:
            # steady-state hot path: the instant marker costs one
            # branch when tracing is off
            trace.instant("render.cache_hit", state=state_name, key=key[1:3])
            return hit
    t0 = perf_counter()
    with trace.span(
        "render.manifest",
        state=state_name,
        kind=key[1],
        name=key[2],
        cache="miss" if cache is not None else "bypass",
    ):
        rendered = render(obj)
        h = compute_hash(rendered)
    render_s = perf_counter() - t0
    rendered.setdefault("metadata", {}).setdefault("annotations", {})[
        consts.LAST_APPLIED_HASH_ANNOTATION
    ] = h
    metrics = getattr(n, "metrics", None)
    if metrics is not None and getattr(
        metrics, "state_render_ms_hist", None
    ):
        metrics.state_render_ms_hist.labels(state=state_name).observe(
            render_s * 1000.0
        )
    if cache is not None:
        rendered = freeze(rendered)
        cache.store(
            key, rendered, h, state_name, render_s,
            generation=generation,
        )
    return rendered, h


def _render_generic(n, obj: Obj) -> Obj:
    obj = copy.deepcopy(obj)
    _fill_namespace(n, obj)
    set_owner_reference(n, obj)
    return obj


def _generic_apply(n, state_name: str, obj: Obj) -> str:
    rendered, h = _render_memo(
        n, state_name, obj, lambda o: _render_generic(n, o)
    )
    apply_with_hash(n, rendered, precomputed_hash=h)
    return State.READY


# ---------------------------------------------------------------------------
# simple kind controls (reference per-kind controlFuncs, object_controls.go:248+)
# ---------------------------------------------------------------------------


def service_account(n, state_name: str, obj: Obj) -> str:
    return _generic_apply(n, state_name, obj)


def role(n, state_name: str, obj: Obj) -> str:
    return _generic_apply(n, state_name, obj)


def role_binding(n, state_name: str, obj: Obj) -> str:
    return _generic_apply(n, state_name, obj)


def cluster_role(n, state_name: str, obj: Obj) -> str:
    return _generic_apply(n, state_name, obj)


def cluster_role_binding(n, state_name: str, obj: Obj) -> str:
    return _generic_apply(n, state_name, obj)


def config_map(n, state_name: str, obj: Obj) -> str:
    return _generic_apply(n, state_name, obj)


def service(n, state_name: str, obj: Obj) -> str:
    return _generic_apply(n, state_name, obj)


def service_monitor(n, state_name: str, obj: Obj) -> str:
    return _generic_apply(n, state_name, obj)


def prometheus_rule(n, state_name: str, obj: Obj) -> str:
    """Alerting rules. The reference ships these OCP-only (monitoring CRDs
    guaranteed there); vanilla clusters may lack the prometheus-operator
    CRDs, so only the missing-CRD failure (404 / no matches for kind) is a
    graceful skip — anything else (RBAC, bad manifest) is NotReady."""
    from tpu_operator.kube.client import NotFoundError

    def _looks_absent(e: Exception) -> bool:
        return isinstance(e, NotFoundError) or (
            "could not find the requested resource" in str(e)
            or "no matches for kind" in str(e)
        )

    try:
        return _generic_apply(n, state_name, obj)
    except Exception as e:
        if _looks_absent(e):
            # a NotFound can also mean the rule object was deleted between
            # read and update: retry once — that recreates it; a genuinely
            # missing CRD fails the same way again and is skipped
            try:
                return _generic_apply(n, state_name, obj)
            except Exception as e2:
                if _looks_absent(e2):
                    log.warning(
                        "PrometheusRule %s skipped (monitoring CRDs absent): %s",
                        obj["metadata"].get("name"),
                        e2,
                    )
                    return State.READY
                e = e2  # a different failure surfaced on retry: report it
        log.error(
            "PrometheusRule %s apply failed: %s",
            obj["metadata"].get("name"),
            e,
        )
        return State.NOT_READY


def runtime_class(n, state_name: str, obj: Obj) -> str:
    """RuntimeClasses; the default one is renamed per
    ``spec.operator.runtime_class`` (reference ``TransformRuntimeClass``)."""

    def render(o: Obj) -> Obj:
        o = copy.deepcopy(o)
        if o["metadata"]["name"] == "tpu":
            o["metadata"]["name"] = n.cp.spec.operator.runtime_class
        _fill_namespace(n, o)
        set_owner_reference(n, o)
        return o

    rendered, h = _render_memo(n, state_name, obj, render)
    apply_with_hash(n, rendered, precomputed_hash=h)
    return State.READY


def priority_class(n, state_name: str, obj: Obj) -> str:
    return _generic_apply(n, state_name, obj)


def pod_security_policy(n, state_name: str, obj: Obj) -> str:
    """PSP only when enabled (reference gates PSP assets on spec.psp)."""
    if not n.cp.spec.psp.is_enabled():
        n.client.delete_if_exists(
            obj["apiVersion"], obj["kind"], obj["metadata"]["name"]
        )
        return State.READY
    return _generic_apply(n, state_name, obj)


def security_context_constraints(n, state_name: str, obj: Obj) -> str:
    # OpenShift-only; skipped off-OCP (we never load *openshift* assets).
    if not n.openshift:
        return State.READY
    return _generic_apply(n, state_name, obj)


def pod(n, state_name: str, obj: Obj) -> str:
    return _generic_apply(n, state_name, obj)


def deployment(n, state_name: str, obj: Obj) -> str:
    rendered, h = _render_memo(
        n, state_name, obj, lambda o: _render_generic(n, o)
    )
    apply_with_hash(n, rendered, precomputed_hash=h)
    live = n.client.get_or_none(
        rendered["apiVersion"],
        "Deployment",
        rendered["metadata"]["name"],
        n.namespace,
    )
    return (
        State.READY if live and is_deployment_ready(live) else State.NOT_READY
    )


# ---------------------------------------------------------------------------
# DaemonSet control — the core
# ---------------------------------------------------------------------------

# DS name -> (spec attr on ClusterPolicySpec, transform fn name)
# (reference dispatch table controllers/object_controls.go:656-672)
TRANSFORMS = {}


def _register(ds_name):
    def deco(fn):
        TRANSFORMS[ds_name] = fn
        return fn

    return deco


def daemonset(n, state_name: str, obj: Obj) -> str:
    """The DaemonSet control path (reference ``DaemonSet()``,
    ``controllers/object_controls.go:3745-3887``)."""
    name = obj["metadata"]["name"]

    # 1. state disabled -> delete any existing operand (reference :3753-3761)
    if not n.is_state_enabled(state_name):
        _delete_daemonsets_like(n, name)
        return State.DISABLED

    # 2. no TPU nodes -> nothing to do (reference :3763-3770)
    if not n.has_tpu_nodes:
        _log_no_tpu_skip(n, name)
        return State.READY

    # 3. libtpu generation fan-out (reference precompiled fan-out :3405-3441)
    if name == "tpu-libtpu-daemonset" and n.cp.spec.libtpu.generation_configs:
        return _libtpu_generation_daemonsets(n, state_name, obj)

    ds, h = _render_memo(n, state_name, obj, lambda o: _render_daemonset(n, o))
    apply_with_hash(n, ds, precomputed_hash=h)
    live = n.client.get_or_none("apps/v1", "DaemonSet", ds["metadata"]["name"], n.namespace)
    if live is None:
        return State.NOT_READY
    return State.READY if is_daemonset_ready(n, live) else State.NOT_READY


def _log_no_tpu_skip(n, name: str) -> None:
    """A TPU-less cluster re-reconciles every 45 s forever; the skip is
    logged at INFO once per DaemonSet per no-TPU transition (the
    registry is cleared when TPU nodes appear), DEBUG thereafter —
    through the shared ``obs/logonce.py`` registry."""
    logged = getattr(n, "no_tpu_skip_logged", None)
    if isinstance(logged, LogOnce):
        logged.log(log, name, "no TPU nodes; skipping DaemonSet %s", name)
        return
    # controllers without the registry (unit tests driving a control
    # directly) log every time, exactly as before
    log.info("no TPU nodes; skipping DaemonSet %s", name)


def _render_daemonset(n, obj: Obj) -> Obj:
    ds = copy.deepcopy(obj)
    _pre_process_daemonset(n, ds)
    set_owner_reference(n, ds)
    return ds


def _render_generation_daemonset(n, obj: Obj, gen: str) -> Obj:
    base_name = obj["metadata"]["name"]
    base_app = obj["metadata"]["labels"].get("app", base_name)
    ds = copy.deepcopy(obj)
    ds["metadata"]["name"] = f"{base_name}-{gen}"
    labels = ds["metadata"].setdefault("labels", {})
    labels[f"{consts.GROUP}/tpu.generation"] = gen
    # each generation DS needs its own selector/app identity — identical
    # selectors across DaemonSets are invalid, and OnDelete readiness
    # must only see this generation's pods
    gen_app = f"{base_app}-{gen}"
    labels["app"] = gen_app
    ds["spec"]["selector"]["matchLabels"]["app"] = gen_app
    tmpl = ds["spec"]["template"]
    tmpl["metadata"].setdefault("labels", {})["app"] = gen_app
    # pods select nodes of this generation
    tmpl["spec"].setdefault("nodeSelector", {})[
        f"{consts.GROUP}/tpu.generation"
    ] = gen
    _pre_process_daemonset(n, ds, generation=gen, transform_key=base_app)
    set_owner_reference(n, ds)
    return ds


def _libtpu_generation_daemonsets(n, state_name: str, obj: Obj) -> str:
    """One libtpu DaemonSet per TPU generation present in the cluster, with
    stale-generation garbage collection (reference
    ``precompiledDriverDaemonsets``/``cleanupUnusedDriverDaemonSets``,
    ``controllers/object_controls.go:3405-3441,3587-3744``). Each
    generation's render is memoized independently: a new generation
    appearing renders exactly one new DaemonSet while the others stay
    cached."""
    base_name = obj["metadata"]["name"]
    wanted = {}
    overall = State.READY
    for gen in sorted(n.tpu_generations):
        ds, h = _render_memo(
            n,
            state_name,
            obj,
            lambda o, g=gen: _render_generation_daemonset(n, o, g),
            generation=gen,
        )
        apply_with_hash(n, ds, precomputed_hash=h)
        wanted[ds["metadata"]["name"]] = True
        live = n.client.get_or_none(
            "apps/v1", "DaemonSet", ds["metadata"]["name"], n.namespace
        )
        if live is None or not is_daemonset_ready(n, live):
            overall = State.NOT_READY
    # GC stale generation DaemonSets and the un-suffixed base one
    _delete_daemonsets_like(n, base_name, keep=set(wanted))
    return overall


def _delete_daemonsets_like(n, base_name: str, keep: Optional[set] = None) -> None:
    """Sweep DaemonSets named ``base_name`` or ``base_name-*``. The
    namespace DaemonSet list is served from the per-pass snapshot when
    one is open — every disabled state and the generation fan-out GC
    used to each issue their own LIST per pass; now they share one
    informer read. ``delete_if_exists`` probes the cache first, so a
    pass-start list that is stale about an already-deleted object
    costs nothing."""
    keep = keep or set()
    snap = getattr(n, "snapshot", None)
    if snap is not None:
        daemonsets = snap.daemonsets()
    else:
        daemonsets = n.client.list("apps/v1", "DaemonSet", n.namespace)
    for ds in daemonsets:
        name = ds["metadata"]["name"]
        if name == base_name or name.startswith(base_name + "-"):
            if name not in keep:
                n.client.delete_if_exists("apps/v1", "DaemonSet", name, n.namespace)


def _pre_process_daemonset(
    n, ds: Obj, generation: Optional[str] = None, transform_key: Optional[str] = None
) -> None:
    """Common config + per-operand transform + pod hash stamping
    (reference ``preProcessDaemonSet``, ``controllers/object_controls.go:3823``)."""
    _fill_namespace(n, ds)
    _apply_common_daemonset_config(n, ds)
    transform = TRANSFORMS.get(transform_key or ds["metadata"]["labels"].get("app"))
    if transform:
        transform(n, ds, generation=generation)
    _transform_validation_init_containers(n, ds)
    # stamp the operand hash into the pod template so OnDelete readiness can
    # compare running pods against the desired revision
    h = compute_hash(ds)
    ds["spec"]["template"]["metadata"].setdefault("annotations", {})[
        consts.LAST_APPLIED_HASH_ANNOTATION
    ] = h


def _apply_common_daemonset_config(n, ds: Obj) -> None:
    """Daemonsets-spec fan-in (reference ``applyCommonDaemonsetConfig``)."""
    dspec = n.cp.spec.daemonsets
    tmpl = ds["spec"]["template"]
    pod_spec = tmpl["spec"]
    if dspec.labels:
        # "app" and "app.kubernetes.io/part-of" stay operator-owned:
        # DaemonSet pod selectors are immutable, so a user override would
        # orphan the pods (reference applyCommonDaemonsetMetadata,
        # controllers/object_controls.go:702-716)
        tmpl["metadata"].setdefault("labels", {}).update(
            {
                k: v
                for k, v in dspec.labels.items()
                if k not in ("app", "app.kubernetes.io/part-of")
            }
        )
    if dspec.annotations:
        tmpl["metadata"].setdefault("annotations", {}).update(dspec.annotations)
    if dspec.tolerations:
        existing = pod_spec.setdefault("tolerations", [])
        for tol in dspec.tolerations:
            if tol not in existing:
                existing.append(tol)
    # every operand tolerates the remediation quarantine taint: the FSM's
    # revalidate/recover steps need the plugin + validator RUNNING on the
    # tainted host to observe the chips coming back — quarantine fences
    # workloads off the node, never the operator's own agents
    repair_tol = {
        "key": consts.REPAIR_TAINT_KEY,
        "operator": "Exists",
        "effect": "NoSchedule",
    }
    tolerations = pod_spec.setdefault("tolerations", [])
    if repair_tol not in tolerations:
        tolerations.append(repair_tol)
    if dspec.priority_class_name:
        pod_spec["priorityClassName"] = dspec.priority_class_name
    # updateStrategy override applies only to RollingUpdate-capable operands
    if (
        dspec.update_strategy == "OnDelete"
        and ds["spec"].get("updateStrategy", {}).get("type") != "OnDelete"
    ):
        ds["spec"]["updateStrategy"] = {"type": "OnDelete"}
    elif dspec.rolling_update and ds["spec"].get("updateStrategy", {}).get(
        "type"
    ) == "RollingUpdate":
        ds["spec"]["updateStrategy"] = {
            "type": "RollingUpdate",
            "rollingUpdate": {
                "maxUnavailable": dspec.rolling_update.max_unavailable
            },
        }


def _env_list(env_vars) -> List[Dict[str, str]]:
    return [{"name": e.name, "value": e.value} for e in env_vars or []]


def _set_container_env(container: Obj, name: str, value: str) -> None:
    """Merge one env var (reference ``setContainerEnv``,
    ``controllers/object_controls.go:2090-2100``)."""
    env = container.setdefault("env", [])
    for e in env:
        if e.get("name") == name:
            e.pop("valueFrom", None)
            e["value"] = value
            return
    env.append({"name": name, "value": value})


def _merge_env(container: Obj, env_vars) -> None:
    for e in env_vars or []:
        _set_container_env(container, e.name, e.value)


def _main_container(ds: Obj, name_hint: str = "") -> Obj:
    containers = ds["spec"]["template"]["spec"]["containers"]
    if name_hint:
        for c in containers:
            if c["name"] == name_hint:
                return c
    return containers[0]


def _all_containers(ds: Obj) -> List[Obj]:
    spec = ds["spec"]["template"]["spec"]
    return list(spec.get("initContainers", [])) + list(spec.get("containers", []))


def _apply_operand_image(n, ds: Obj, spec, main: str = "") -> Obj:
    """Fill the operand image into every placeholder container, returning the
    main container for further transformation."""
    image = spec.image_path()
    for c in _all_containers(ds):
        if c.get("image") == PLACEHOLDER:
            c["image"] = image
            c["imagePullPolicy"] = spec.pull_policy()
    if spec.image_pull_secrets:
        ds["spec"]["template"]["spec"]["imagePullSecrets"] = [
            {"name": s} for s in spec.image_pull_secrets
        ]
    return _main_container(ds, main)


def _apply_resources(container: Obj, spec) -> None:
    res = getattr(spec, "resources", None)
    if res:
        container["resources"] = {
            k: v
            for k, v in (("limits", res.limits), ("requests", res.requests))
            if v
        }


def _mount_config_map(
    ds: Obj, container: Obj, cm_name: str, volume_name: str, mount_path: str
) -> None:
    """Idempotently mount a ConfigMap volume into one container."""
    vols = ds["spec"]["template"]["spec"].setdefault("volumes", [])
    if not any(v.get("name") == volume_name for v in vols):
        vols.append({"name": volume_name, "configMap": {"name": cm_name}})
    mounts = container.setdefault("volumeMounts", [])
    if not any(m.get("name") == volume_name for m in mounts):
        mounts.append(
            {"name": volume_name, "mountPath": mount_path, "readOnly": True}
        )


def _apply_proxy(n, ds: Obj) -> None:
    """Inject cluster-wide proxy env + trusted-CA bundle into every container
    of a network-reaching operand (reference ``applyOCPProxySpec``,
    ``controllers/object_controls.go:907-1050``)."""
    proxy = n.cp.spec.operator.proxy
    if proxy is None:
        return
    env_pairs = [
        ("HTTPS_PROXY", proxy.https_proxy),
        ("HTTP_PROXY", proxy.http_proxy),
        ("NO_PROXY", proxy.no_proxy),
    ]
    for c in _all_containers(ds):
        for name, value in env_pairs:
            if value:
                # both spellings: tooling disagrees on case
                _set_container_env(c, name, value)
                _set_container_env(c, name.lower(), value)
    if proxy.trusted_ca_config_map:
        for c in _all_containers(ds):
            _mount_config_map(
                ds,
                c,
                proxy.trusted_ca_config_map,
                "tpu-operator-trusted-ca",
                consts.TRUSTED_CA_MOUNT_DIR,
            )
            _set_container_env(
                c,
                "TRUSTED_CA_BUNDLE",
                consts.TRUSTED_CA_MOUNT_DIR + "/ca-bundle.crt",
            )


def _transform_validation_init_containers(n, ds: Obj) -> None:
    """Point ``*-validation`` initContainers at the validator image
    (reference ``transformValidatorShared``/initContainer injection,
    ``controllers/object_controls.go:3041-3080``)."""
    vspec = n.cp.spec.validator
    image = vspec.image_path()
    for c in ds["spec"]["template"]["spec"].get("initContainers", []):
        if c["name"].endswith("-validation"):
            if image:
                c["image"] = image
                c["imagePullPolicy"] = vspec.pull_policy()
            _merge_env(c, vspec.env)


# ---------------------------------------------------------------------------
# per-operand transforms (reference Transform*, object_controls.go:656-672)
# ---------------------------------------------------------------------------


@_register("tpu-libtpu-daemonset")
def transform_libtpu(n, ds: Obj, generation: Optional[str] = None) -> None:
    """reference ``TransformDriver``/``transformDriverContainer``
    (``controllers/object_controls.go:2718-2948``), minus everything
    kernel-specific: no DTK, no RHEL entitlements, no peermem."""
    spec = n.cp.spec.libtpu
    if generation and spec.generation_configs.get(generation):
        spec = copy.deepcopy(spec)
        spec.version = spec.generation_configs[generation]
    main = _apply_operand_image(n, ds, spec, "libtpu-ctr")
    _merge_env(main, spec.env)
    if spec.args:
        main["args"] = list(spec.args)
    _apply_resources(main, spec)
    _set_container_env(main, "LIBTPU_INSTALL_DIR", spec.install_dir)
    if generation:
        _set_container_env(main, "TPU_GENERATION", generation)
    if spec.startup_probe:
        main["startupProbe"] = {**main.get("startupProbe", {}), **spec.startup_probe}
    if spec.liveness_probe:
        main["livenessProbe"] = spec.liveness_probe
    if spec.readiness_probe:
        main["readinessProbe"] = spec.readiness_probe
    # custom artifact source + CA certs (reference repoConfig/certConfig,
    # ``controllers/object_controls.go:2770-2830``) and cluster-wide proxy
    if spec.repo_config.get("configMapName"):
        _mount_config_map(
            ds, main, spec.repo_config["configMapName"],
            "libtpu-repo-config", consts.LIBTPU_REPO_CONFIG_DIR,
        )
    if spec.cert_config.get("name"):
        _mount_config_map(
            ds, main, spec.cert_config["name"],
            "libtpu-cert-config", consts.LIBTPU_CERT_CONFIG_DIR,
        )
    _apply_proxy(n, ds)
    # libtpu-manager drain knobs from the upgrade policy
    mgr = next(
        (
            c
            for c in ds["spec"]["template"]["spec"].get("initContainers", [])
            if c["name"] == "libtpu-manager"
        ),
        None,
    )
    if mgr is not None:
        mgr["image"] = spec.image_path()
        pol = spec.upgrade_policy
        drain = pol.drain if pol else None
        if drain:
            # full drain knob set (reference k8s-driver-manager env,
            # assets/state-driver/0500_daemonset.yaml:77-86)
            if drain.enable is not None:
                _set_container_env(
                    mgr, "ENABLE_AUTO_DRAIN", "true" if drain.enable else "false"
                )
            if drain.force:
                _set_container_env(mgr, "DRAIN_USE_FORCE", "true")
            if drain.pod_selector:
                _set_container_env(
                    mgr, "DRAIN_POD_SELECTOR_LABEL", drain.pod_selector
                )
            if drain.timeout_seconds:
                _set_container_env(
                    mgr, "DRAIN_TIMEOUT_SECONDS", str(drain.timeout_seconds)
                )
    # rolling-update override
    if spec.rolling_update and ds["spec"]["updateStrategy"]["type"] == "RollingUpdate":
        ds["spec"]["updateStrategy"]["rollingUpdate"] = {
            "maxUnavailable": spec.rolling_update.max_unavailable
        }


@_register("tpu-runtime-daemonset")
def transform_runtime(n, ds: Obj, generation: Optional[str] = None) -> None:
    """reference ``TransformToolkit`` (``controllers/object_controls.go:1052-1184``):
    instead of runtime-socket/config mounts we wire CDI env."""
    spec = n.cp.spec.runtime
    main = _apply_operand_image(n, ds, spec, "tpu-runtime-ctr")
    _merge_env(main, spec.env)
    _set_container_env(main, "RUNTIME_INSTALL_DIR", spec.install_dir)
    _set_container_env(main, "CONTAINER_RUNTIME", n.runtime or "containerd")
    cdi = n.cp.spec.cdi
    _set_container_env(main, "CDI_ENABLED", str(cdi.is_enabled()).lower())
    _set_container_env(main, "CDI_DEFAULT", str(cdi.is_default()).lower())


@_register("tpu-device-plugin-daemonset")
def transform_device_plugin(n, ds: Obj, generation: Optional[str] = None) -> None:
    """reference ``TransformDevicePlugin`` (``controllers/object_controls.go:1187-1256``)."""
    spec = n.cp.spec.device_plugin
    main = _apply_operand_image(n, ds, spec, "tpu-device-plugin")
    _merge_env(main, spec.env)
    if spec.args:
        main["args"] = list(spec.args)
    _apply_resources(main, spec)
    _set_container_env(
        main, "SLICE_STRATEGY", n.cp.spec.slice.strategy or "single"
    )
    _set_container_env(
        main, "CDI_ENABLED", str(n.cp.spec.cdi.is_enabled()).lower()
    )
    _set_container_env(main, "TPU_RESOURCE", consts.TPU_RESOURCE)
    if n.cp.spec.direct_storage.is_enabled():
        _set_container_env(main, "DIRECT_STORAGE_ENABLED", "true")
    if spec.config and spec.config.name:
        _mount_named_config(
            ds, main, spec.config.name, "/config", spec.config.default
        )


def _mount_named_config(
    ds: Obj, container: Obj, cm_name: str, mount_path: str, default_cfg: str
) -> None:
    """Custom plugin ConfigMap + config-manager sidecar pattern (reference
    ``controllers/object_controls.go:2184-2290``, simplified: the daemon
    watches the mounted file itself, no extra sidecar process)."""
    vols = ds["spec"]["template"]["spec"].setdefault("volumes", [])
    vols.append({"name": "custom-config", "configMap": {"name": cm_name}})
    container.setdefault("volumeMounts", []).append(
        {"name": "custom-config", "mountPath": mount_path}
    )
    _set_container_env(container, "CONFIG_FILE_DIR", mount_path)
    if default_cfg:
        _set_container_env(container, "DEFAULT_CONFIG", default_cfg)


@_register("tpu-operator-validator")
def transform_validator(n, ds: Obj, generation: Optional[str] = None) -> None:
    """reference ``TransformValidator`` + per-component env
    (``validator/main.go:212-315``)."""
    spec = n.cp.spec.validator
    main = _apply_operand_image(n, ds, spec, "tpu-operator-validator")
    _merge_env(main, spec.env)
    _apply_resources(main, spec)
    inits = ds["spec"]["template"]["spec"].setdefault("initContainers", [])
    # optional deep diagnostics appended after jax-validation (the chip is
    # already proven free): membw = dcgmi-diag memory-bandwidth analogue;
    # ringattn/ici/pipeline/moe = parallelism-axis probes. Containers are
    # cloned from jax-validation — without it (custom assets) there is
    # nothing sane to clone, so skip.
    optional_diags = (
        ("membw", spec.membw),
        ("ringattn", spec.ringattn),
        ("ici", spec.ici),
        ("pipeline", spec.pipeline),
        ("moe", spec.moe),
        ("flashattn", spec.flashattn),
    )
    diag_ctr_names = tuple(f"{name}-validation" for name, _ in optional_diags)
    for comp_name, comp_spec in optional_diags:
        ctr_name = f"{comp_name}-validation"
        if (comp_spec or {}).get("enabled") and not any(
            c["name"] == ctr_name for c in inits
        ):
            jax_idx = next(
                (i for i, c in enumerate(inits) if c["name"] == "jax-validation"),
                None,
            )
            if jax_idx is not None:
                ctr = copy.deepcopy(inits[jax_idx])
                ctr["name"] = ctr_name
                ctr["args"] = [f"tpu-validator --component {comp_name}"]
                # chain order: jax → diagnostics in optional_diags order
                # (each insert lands after the previously injected one)
                insert_at = jax_idx + 1
                while (
                    insert_at < len(inits)
                    and inits[insert_at]["name"] in diag_ctr_names
                ):
                    insert_at += 1
                inits.insert(insert_at, ctr)
    for c in inits:
        component_env = {
            "plugin-validation": spec.plugin,
            "jax-validation": spec.jax,
            "libtpu-validation": spec.libtpu,
            "runtime-validation": spec.runtime,
            "membw-validation": spec.membw,
            "ringattn-validation": spec.ringattn,
            "ici-validation": spec.ici,
            "pipeline-validation": spec.pipeline,
            "moe-validation": spec.moe,
            "flashattn-validation": spec.flashattn,
        }.get(c["name"])
        for e in (component_env or {}).get("env", []) or []:
            _set_container_env(c, e["name"], e["value"])
        if c["name"] in ("plugin-validation", "jax-validation"):
            # workload-pod spin-off config: the spawned pod must use the
            # CR-configured validator image + pull credentials, not a
            # baked-in default (reference injects ValidatorImage*/
            # PullSecrets env for the cuda/plugin workload pods,
            # controllers/object_controls.go:1906-1912)
            image = spec.image_path()
            if image:
                _set_container_env(c, "JAX_WORKLOAD_IMAGE", image)
                _set_container_env(
                    c, "JAX_WORKLOAD_PULL_POLICY", spec.pull_policy()
                )
            if spec.image_pull_secrets:
                _set_container_env(
                    c,
                    "JAX_WORKLOAD_PULL_SECRETS",
                    ",".join(spec.image_pull_secrets),
                )


@_register("tpu-metricsd")
def transform_metricsd(n, ds: Obj, generation: Optional[str] = None) -> None:
    """reference ``TransformDCGM`` (``controllers/object_controls.go:1441-1495``)."""
    spec = n.cp.spec.metricsd
    main = _apply_operand_image(n, ds, spec, "tpu-metricsd")
    _merge_env(main, spec.env)
    if spec.host_port and spec.host_port != 5555:
        for port in main.get("ports", []):
            if port.get("name") == "metricsd":
                port["hostPort"] = spec.host_port
                port["containerPort"] = spec.host_port
        _set_container_env(main, "METRICSD_PORT", str(spec.host_port))
    if spec.sample_on_chip:
        # chip-owning JAX sampler sidecar; the native hostengine (main ctr)
        # merges its side-file — single-client chip stays out of the server
        pod_spec = ds["spec"]["template"]["spec"]
        if not any(
            c.get("name") == "tpu-metricsd-sampler"
            for c in pod_spec.get("containers", [])
        ):
            sampler = {
                "name": "tpu-metricsd-sampler",
                "image": main["image"],
                "imagePullPolicy": main.get("imagePullPolicy", "IfNotPresent"),
                "command": ["tpu-metricsd"],
                "args": ["--sampler-only"],
                "securityContext": {"privileged": True},
                "volumeMounts": [
                    {"name": "run-tpu", "mountPath": "/run/tpu"},
                    {"name": "dev", "mountPath": "/dev"},
                ],
            }
            pod_spec["containers"].append(sampler)


@_register("tpu-metrics-exporter")
def transform_metrics_exporter(n, ds: Obj, generation: Optional[str] = None) -> None:
    """reference ``TransformDCGMExporter`` (``controllers/object_controls.go:1302-1439``)."""
    spec = n.cp.spec.metrics_exporter
    main = _apply_operand_image(n, ds, spec, "tpu-metrics-exporter")
    _merge_env(main, spec.env)
    _apply_resources(main, spec)
    if n.cp.spec.metricsd.is_enabled():
        # scrape the standalone daemon instead of opening the chip directly
        # (reference remote-hostengine env, object_controls.go:95-98)
        _set_container_env(
            main,
            "METRICSD_ENDPOINT",
            f"localhost:{n.cp.spec.metricsd.host_port}",
        )
    if spec.metrics_config and spec.metrics_config.name:
        _mount_named_config(ds, main, spec.metrics_config.name, "/etc/tpu-metrics", "")


@_register("tpu-node-status-exporter")
def transform_node_status_exporter(n, ds: Obj, generation: Optional[str] = None) -> None:
    spec = n.cp.spec.node_status_exporter
    main = _apply_operand_image(n, ds, spec, "tpu-node-status-exporter")
    _merge_env(main, spec.env)


@_register("tpu-feature-discovery")
def transform_tfd(n, ds: Obj, generation: Optional[str] = None) -> None:
    """reference ``TransformGPUDiscoveryPlugin``."""
    spec = n.cp.spec.tfd
    main = _apply_operand_image(n, ds, spec, "tpu-feature-discovery")
    _merge_env(main, spec.env)
    _apply_resources(main, spec)
    _set_container_env(
        main, "SLICE_STRATEGY", n.cp.spec.slice.strategy or "single"
    )


@_register("tpu-slice-manager")
def transform_slice_manager(n, ds: Obj, generation: Optional[str] = None) -> None:
    """reference ``TransformMIGManager`` (``controllers/object_controls.go:1497-1579``)."""
    spec = n.cp.spec.slice_manager
    main = _apply_operand_image(n, ds, spec, "tpu-slice-manager")
    _merge_env(main, spec.env)
    _set_container_env(
        main, "WITH_REBOOT", "false"
    )  # TPU repartition never needs a reboot
    if n.cp.spec.cdi.is_enabled():
        _set_container_env(
            main, "CDI_SPEC_PATH", "/var/run/cdi/google.com-tpu.yaml"
        )
    if spec.config and spec.config.name:
        for vol in ds["spec"]["template"]["spec"]["volumes"]:
            if vol["name"] == "slice-config":
                vol["configMap"]["name"] = spec.config.name
        if spec.config.default:
            _set_container_env(main, "DEFAULT_SLICE_CONFIG", spec.config.default)
    if spec.chip_clients_config and spec.chip_clients_config.name:
        for vol in ds["spec"]["template"]["spec"]["volumes"]:
            if vol["name"] == "chip-clients":
                vol["configMap"]["name"] = spec.chip_clients_config.name


@_register("tpu-maintenance-handler")
def transform_maintenance_handler(
    n, ds: Obj, generation: Optional[str] = None
) -> None:
    """TPU-specific host-maintenance watcher (no reference analogue;
    ``tpu_operator/operands/maintenance.py``)."""
    spec = n.cp.spec.maintenance_handler
    main = _apply_operand_image(n, ds, spec, "tpu-maintenance-handler")
    _merge_env(main, spec.env)
    _apply_resources(main, spec)
    if spec.poll_interval_seconds:
        _set_container_env(
            main, "POLL_INTERVAL_S", str(spec.poll_interval_seconds)
        )
    if spec.force_evict is not None:
        _set_container_env(
            main, "FORCE_EVICT", "true" if spec.force_evict else "false"
        )
    if spec.evict_workloads is not None:
        _set_container_env(
            main, "EVICT_WORKLOADS", "true" if spec.evict_workloads else "false"
        )


@_register("tpu-vm-manager-daemonset")
def transform_vm_manager(n, ds: Obj, generation: Optional[str] = None) -> None:
    spec = n.cp.spec.vm_manager
    main = _apply_operand_image(n, ds, spec, "tpu-vm-manager")
    _merge_env(main, spec.env)


@_register("tpu-vm-device-manager")
def transform_vm_device_manager(n, ds: Obj, generation: Optional[str] = None) -> None:
    spec = n.cp.spec.vm_device_manager
    main = _apply_operand_image(n, ds, spec, "tpu-vm-device-manager")
    _merge_env(main, spec.env)
    if spec.config and spec.config.name:
        for vol in ds["spec"]["template"]["spec"]["volumes"]:
            if vol["name"] == "vm-device-config":
                vol["configMap"]["name"] = spec.config.name
        if spec.config.default:
            _set_container_env(main, "DEFAULT_VM_DEVICE_CONFIG", spec.config.default)


@_register("tpu-sandbox-validator")
def transform_sandbox_validator(n, ds: Obj, generation: Optional[str] = None) -> None:
    spec = n.cp.spec.validator
    _apply_operand_image(n, ds, spec, "tpu-sandbox-validator")


@_register("tpu-vfio-manager-daemonset")
def transform_vfio_manager(n, ds: Obj, generation: Optional[str] = None) -> None:
    spec = n.cp.spec.vfio_manager
    main = _apply_operand_image(n, ds, spec, "tpu-vfio-manager")
    _merge_env(main, spec.env)


@_register("tpu-sandbox-device-plugin-daemonset")
def transform_sandbox_device_plugin(n, ds: Obj, generation: Optional[str] = None) -> None:
    spec = n.cp.spec.sandbox_device_plugin
    main = _apply_operand_image(n, ds, spec, "tpu-sandbox-device-plugin")
    _merge_env(main, spec.env)
    if spec.args:
        main["args"] = list(spec.args)


@_register("tpu-kata-manager-daemonset")
def transform_kata_manager(n, ds: Obj, generation: Optional[str] = None) -> None:
    spec = n.cp.spec.kata_manager
    main = _apply_operand_image(n, ds, spec, "tpu-kata-manager")
    _merge_env(main, spec.env)


# ---------------------------------------------------------------------------
# readiness (reference controllers/object_controls.go:3082-3177,3935-3958)
# ---------------------------------------------------------------------------


def _nodes_wanting(n, ds: Obj) -> int:
    """How many nodes match the DaemonSet's nodeSelector. Served from
    the per-pass snapshot when one is open — 18 states asking about the
    same handful of deploy-label selectors share one node scan per
    unique selector instead of each re-listing the fleet."""
    selector = (
        ds.get("spec", {})
        .get("template", {})
        .get("spec", {})
        .get("nodeSelector", {})
        or {}
    )
    snap = getattr(n, "snapshot", None)
    if snap is not None:
        return snap.count_nodes_matching(selector)
    count = 0
    for node in n.client.list("v1", "Node"):
        labels = node.get("metadata", {}).get("labels", {}) or {}
        if all(labels.get(k) == v for k, v in selector.items()):
            count += 1
    return count


def is_daemonset_ready(n, ds: Obj) -> bool:
    status = ds.get("status", {}) or {}
    desired = status.get("desiredNumberScheduled", 0)
    if desired == 0:
        # nothing scheduled yet: ready iff no node actually wants this
        # operand (e.g. sandbox states enabled but every node is
        # container-workload). A node that matches the selector but has no
        # pod yet means the DS controller is still catching up -> NotReady.
        return _nodes_wanting(n, ds) == 0
    if status.get("numberUnavailable", 0) != 0:
        return False
    strategy = ds.get("spec", {}).get("updateStrategy", {}).get("type")
    if strategy == "OnDelete":
        # every pod must run the current operand revision (hash stamped into
        # the pod template by _pre_process_daemonset)
        want = (
            ds["spec"]["template"]["metadata"]
            .get("annotations", {})
            .get(consts.LAST_APPLIED_HASH_ANNOTATION)
        )
        app = ds["spec"]["selector"]["matchLabels"].get("app")
        snap = getattr(n, "snapshot", None)
        if snap is not None:
            # one indexed pod read per app per pass, shared across the
            # OnDelete readiness checks and sweeps of all 18 states
            pods = snap.pods_by_app(app)
        else:
            pods = n.client.list(
                "v1", "Pod", n.namespace, label_selector={"app": app}
            )
        if len(pods) < desired:
            return False
        for p in pods:
            got = (
                p["metadata"].get("annotations", {}) or {}
            ).get(consts.LAST_APPLIED_HASH_ANNOTATION)
            if want and got != want:
                return False
            if p.get("status", {}).get("phase") != "Running":
                return False
        return True
    return status.get("updatedNumberScheduled", desired) >= desired


def is_deployment_ready(dep: Obj) -> bool:
    status = dep.get("status", {}) or {}
    want = dep.get("spec", {}).get("replicas", 1)
    return status.get("availableReplicas", 0) >= want


def is_pod_ready(pod_obj: Obj) -> bool:
    return pod_obj.get("status", {}).get("phase") in ("Running", "Succeeded")


CONTROLS = {
    "service_account": service_account,
    "role": role,
    "role_binding": role_binding,
    "cluster_role": cluster_role,
    "cluster_role_binding": cluster_role_binding,
    "config_map": config_map,
    "service": service,
    "service_monitor": service_monitor,
    "prometheus_rule": prometheus_rule,
    "runtime_class": runtime_class,
    "priority_class": priority_class,
    "pod_security_policy": pod_security_policy,
    "security_context_constraints": security_context_constraints,
    "pod": pod,
    "daemonset": daemonset,
    "deployment": deployment,
}
