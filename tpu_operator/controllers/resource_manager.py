"""Manifest loading: asset directory → typed resources + ordered controls.

TPU-native analogue of ``controllers/resource_manager.go``: each state's
asset directory is walked in sorted-name order
(``controllers/resource_manager.go:70-89``), every YAML document is decoded
and bucketed by ``kind`` into a ``Resources`` struct, and a control-function
name is appended per document in file order (``:91-187``). The state
machine later executes those controls in order.

Unlike the reference (one object of each kind per state), ``Resources``
holds *lists* per kind, which removes the reference's implicit
one-ServiceMonitor-per-state restriction.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Tuple

import yaml

Obj = Dict[str, Any]

# kind -> control name (executed by object_controls)
KIND_TO_CONTROL = {
    "ServiceAccount": "service_account",
    "Role": "role",
    "RoleBinding": "role_binding",
    "ClusterRole": "cluster_role",
    "ClusterRoleBinding": "cluster_role_binding",
    "ConfigMap": "config_map",
    "DaemonSet": "daemonset",
    "Deployment": "deployment",
    "Service": "service",
    "ServiceMonitor": "service_monitor",
    "PrometheusRule": "prometheus_rule",
    "RuntimeClass": "runtime_class",
    "PriorityClass": "priority_class",
    "PodSecurityPolicy": "pod_security_policy",
    "SecurityContextConstraints": "security_context_constraints",
    "Pod": "pod",
}


@dataclass
class Resources:
    """Decoded manifests for one state (reference ``Resources`` struct,
    ``controllers/resource_manager.go:35-53``)."""

    by_kind: Dict[str, List[Obj]] = field(default_factory=dict)

    def add(self, obj: Obj) -> None:
        self.by_kind.setdefault(obj["kind"], []).append(obj)

    def of(self, kind: str) -> List[Obj]:
        return self.by_kind.get(kind, [])

    def first(self, kind: str) -> Obj:
        items = self.of(kind)
        if not items:
            raise KeyError(f"no {kind} in state resources")
        return items[0]


def get_assets_from(path: str, openshift: bool = False) -> List[str]:
    """Sorted asset file list; skips ``*openshift*`` files off-OCP
    (reference ``getAssetsFrom``, ``controllers/resource_manager.go:70-89``)."""
    files = []
    for name in sorted(os.listdir(path)):
        full = os.path.join(path, name)
        if not os.path.isfile(full):
            continue
        if not name.endswith((".yaml", ".yml")):
            continue
        if not openshift and "openshift" in name:
            continue
        files.append(full)
    return files


def add_resources_controls(
    path: str, openshift: bool = False
) -> Tuple[Resources, List[Tuple[str, Obj]]]:
    """Load one state directory.

    Returns the decoded ``Resources`` plus the ordered control list as
    ``(control_name, obj)`` pairs — the Python shape of the reference's
    parallel ``controlFunc`` slice (``controllers/resource_manager.go:91-187``).
    """
    res = Resources()
    controls: List[Tuple[str, Obj]] = []
    for f in get_assets_from(path, openshift):
        with open(f) as fh:
            for doc in yaml.safe_load_all(fh):
                if not doc:
                    continue
                kind = doc.get("kind")
                if not kind:
                    raise ValueError(f"{f}: document without kind")
                control = KIND_TO_CONTROL.get(kind)
                if control is None:
                    raise ValueError(f"{f}: unhandled kind {kind}")
                res.add(doc)
                controls.append((control, doc))
    return res, controls
