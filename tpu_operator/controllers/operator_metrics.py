"""Operator self-metrics.

The reference's 17-series surface (``controllers/operator_metrics.go:13-185``)
re-pointed at TPU concepts, extended to **21 series**: 4 reconciliation
(status/total/failed/last-success), TPU node gauge, feature-label
presence, per-generation libtpu DaemonSet gauge (DTK slot), per-state
operand gauge, and eight upgrade-FSM gauges — six node-state gauges
plus the slice-granular in-progress/pinned pair (the round-5
disruption unit). TPU-first additions beyond the reference's shape:
slice totals/ready pair, maintenance gauge, the PDB-veto pressure
counter (``upgrade_evictions_blocked_total``), and the informer
drift-repair gauge.
"""

from __future__ import annotations

import time

try:
    from prometheus_client import REGISTRY, Counter, Gauge, Histogram

    HAVE_PROM = True
except Exception:  # pragma: no cover - prometheus always present in image
    HAVE_PROM = False


class _NoopMetric:
    """Stand-in for every collector when ``prometheus_client`` is
    absent: the operator runs metric-less instead of raising
    AttributeError on the first gauge access. One shared instance backs
    every series — all operations are no-ops."""

    def labels(self, *a, **kw):
        return self

    def inc(self, *a, **kw):
        pass

    def dec(self, *a, **kw):
        pass

    def set(self, *a, **kw):
        pass

    def observe(self, *a, **kw):
        pass

    def remove(self, *a, **kw):
        pass


_NOOP_METRIC = _NoopMetric()

# Histogram buckets (milliseconds), fixed so dashboards/alerts compare
# across releases (docs/observability.md has the rationale). Each set
# brackets the measured steady/loaded range with ~2-2.5x steps: the
# steady 1000-node pass sits ~12-25 ms (bench gate 50 ms), converging
# passes run 100s of ms; renders are sub-ms to tens of ms; queue waits
# are sub-ms healthy and grow past 10 ms when the pipeline saturates;
# in-process apply RTT is ~0.5-5 ms (real apiserver: tens); allocate
# p99 gates at 850 ms.
PASS_MS_BUCKETS = (1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000, 2500, 5000)
RENDER_MS_BUCKETS = (0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50, 100)
QUEUE_WAIT_MS_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50, 100, 500)
RTT_MS_BUCKETS = (0.25, 0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 1000)
ALLOC_MS_BUCKETS = (1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000, 2500)
# a delta sub-reconcile touches ONE node's label step or ONE slice's
# readiness aggregate: sub-ms to low-ms healthy, tens of ms only when a
# status write conflicts — an order of magnitude under the full pass
DELTA_MS_BUCKETS = (0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50, 100)


class OperatorMetrics:
    """reference ``OperatorMetrics`` (``controllers/operator_metrics.go:13-34``)."""

    _singleton = None

    def __new__(cls, *a, **kw):
        # prometheus_client registers collectors globally; keep one instance
        if cls._singleton is None:
            cls._singleton = super().__new__(cls)
            cls._singleton._init_collectors()
        return cls._singleton

    def _init_collectors(self):
        ns = "tpu_operator"
        if HAVE_PROM:
            g = lambda name, doc, labels=(): Gauge(f"{ns}_{name}", doc, labels)  # noqa: E731
            c = lambda name, doc, labels=(): Counter(f"{ns}_{name}", doc, labels)  # noqa: E731
            h = lambda name, doc, buckets, labels=(): Histogram(  # noqa: E731
                f"{ns}_{name}", doc, labels, buckets=buckets
            )
        else:
            # metric-less mode: every series is the shared no-op stub
            g = lambda *a, **kw: _NOOP_METRIC  # noqa: E731
            c = lambda *a, **kw: _NOOP_METRIC  # noqa: E731
            h = lambda *a, **kw: _NOOP_METRIC  # noqa: E731
        # reconciliation (reference :64-100)
        self.reconciliation_status = g(
            "reconciliation_status",
            "1 success / 0 not-ready / -1 failed / -2 no ClusterPolicy",
        )
        self.reconciliation_total = c(
            "reconciliation_total", "Total reconciliation attempts"
        )
        self.reconciliation_failed = c(
            "reconciliation_failed_total", "Failed reconciliations"
        )
        self.reconciliation_last_success = g(
            "reconciliation_last_success_ts_seconds",
            "Timestamp of last successful reconciliation",
        )
        # fleet (reference :52-57)
        self.tpu_nodes_total = g("tpu_nodes_total", "Number of TPU nodes")
        self.feature_labels_present = g(
            "tpu_feature_labels",
            "1 if TPU hardware labels (GKE/NFD) were found on any node",
        )
        # per-generation libtpu fan-out (DTK-gauge slot, reference :102-140)
        self.libtpu_generations_total = g(
            "libtpu_generations_total",
            "Distinct TPU generations driving libtpu DaemonSet fan-out",
        )
        self.operand_states = g(
            "operand_state",
            "Per-state readiness: 1 ready / 0 not-ready / -1 disabled / "
            "-2 errored (isolated this pass, see status.erroredStates)",
            ("state",),
        )
        # per-state error isolation: how many states raised this pass and
        # were isolated instead of aborting the run (Degraded condition)
        self.states_errored = g(
            "states_errored",
            "States whose step() raised this pass (isolated; the pass "
            "continued to independent states)",
        )
        # slice-scoped readiness (no reference analogue; SURVEY.md §7)
        self.slices_total = g(
            "tpu_slices_total", "TPU slices (multi-host groups + single hosts)"
        )
        self.slices_ready = g(
            "tpu_slices_ready", "TPU slices with every member host validated"
        )
        # host-maintenance visibility (TPU-specific; no reference analogue)
        self.nodes_under_maintenance = g(
            "nodes_under_maintenance",
            "TPU nodes with an active metadata-announced maintenance window "
            "(tpu.k8s.io/maintenance=pending)",
        )
        # upgrade FSM gauges (reference :142-185)
        self.upgrades_in_progress = g(
            "libtpu_upgrades_in_progress", "Nodes currently upgrading libtpu"
        )
        self.upgrades_done = g("libtpu_upgrades_done", "Nodes at upgrade-done")
        self.upgrades_failed = g("libtpu_upgrades_failed", "Nodes at upgrade-failed")
        self.upgrades_available = g(
            "libtpu_upgrades_available",
            "Slices (disruption units; single-host nodes are slices of "
            "one) the upgrade budget would admit now",
        )
        self.upgrades_pending = g(
            "libtpu_upgrades_pending", "Nodes with upgrade-required"
        )
        self.upgrades_unknown = g(
            "libtpu_upgrades_unknown", "Nodes with unknown upgrade state"
        )
        # slice-granular disruption (TPU-first redesign of the reference's
        # per-node budgets): the roll admits/batches whole slices, so the
        # in-flight/pinned truth is per slice, not per node
        self.upgrade_slices_in_progress = g(
            "libtpu_upgrade_slices_in_progress",
            "Slices (disruption units) with at least one member host "
            "mid-upgrade",
        )
        self.upgrade_slices_pinned = g(
            "libtpu_upgrade_slices_pinned",
            "Slices whose upgrade drain is pinned by a disruption-budget "
            "veto on a member host",
        )
        # PDB-veto pressure (reference drain path
        # vendor/.../upgrade/drain_manager.go:76-89): each count is one
        # eviction a PodDisruptionBudget refused — sustained growth means
        # a drain is stuck behind a budget and the upgrade cannot proceed
        self.evictions_blocked = c(
            "upgrade_evictions_blocked_total",
            "Drain evictions vetoed by a PodDisruptionBudget across every "
            "drain path (libtpu upgrades, host maintenance, node "
            "remediation — all share PodManager.evict_pods)",
        )
        # node-health remediation FSM (controllers/remediation.py): the
        # fleet-repair surface — how many hosts are unhealthy, how many
        # the FSM holds quarantined/exhausted, drain vetoes, escalation
        # attempts, and the systemic-failure breaker's disposition
        self.remediation_nodes_unhealthy = g(
            "remediation_nodes_unhealthy",
            "TPU nodes derived unhealthy this pass (0-allocatable chips, "
            "CrashLoopBackOff operands, or validator not Running)",
        )
        self.remediation_nodes_quarantined = g(
            "remediation_nodes_quarantined",
            "TPU nodes the remediation FSM holds cordoned + tainted "
            "(cordon-drain or quarantined)",
        )
        self.remediation_nodes_exhausted = g(
            "remediation_nodes_exhausted",
            "TPU nodes that hit the remediation attempt cap (flapping) "
            "and stay quarantined until a human intervenes",
        )
        self.remediation_drains_vetoed = g(
            "remediation_drains_vetoed",
            "Remediation-drain evictions vetoed by a PodDisruptionBudget "
            "(each veto defers, never fails, the FSM step)",
        )
        self.remediation_breaker_open = g(
            "remediation_breaker_open",
            "1 while the systemic-failure breaker is open (>= "
            "systemicThreshold of the fleet unhealthy: remediation "
            "halted, zero drains)",
        )
        self.remediation_attempts_total = g(
            "remediation_attempts_total",
            "Escalation steps executed by the remediation FSM "
            "(operand restarts + cordon-drains) since process start",
        )
        # allocation traffic (schedsim churn engine, the device-plugin
        # path's foreground workload): admission volume/outcomes, gang
        # holds taken, fleet fragmentation, and the p99 the bench-alloc
        # gate rides. Gauges fed from the engine's own counters (the
        # render_cache_invalidations convention) whenever it runs.
        self.alloc_requests = g(
            "alloc_requests",
            "Allocation requests admitted through the device-plugin path "
            "(successes + failures + cancellations) by the churn engine",
        )
        self.alloc_failures = g(
            "alloc_failures",
            "Allocation requests that failed admission (no host with "
            "enough free chips, gang admission timeout, insufficient "
            "chips at allocate time)",
        )
        self.alloc_gang_holds = g(
            "alloc_gang_holds",
            "Gang-admission hold sets acquired (all member hosts held "
            "atomically) by the hold-and-release coordinator",
        )
        self.alloc_fragmentation_pct = g(
            "alloc_fragmentation_pct",
            "Fleet fragmentation: percent of free chips outside their "
            "host's largest ICI-contiguous free block (last sample)",
        )
        self.alloc_latency_ms_p99 = g(
            "alloc_latency_ms_p99",
            "p99 device-plugin allocation latency (GetPreferredAllocation "
            "-> Allocate -> ledger hold) in milliseconds",
        )
        # sharded scale-out (tpu_operator/shard.py): per-shard lease
        # ownership from THIS replica's view, handoffs it lost, and
        # watch events its router dropped as another replica's work —
        # the balance/health surface the bench gate and the failover
        # post-mortems read
        self.shard_ownership = g(
            "shard_ownership",
            "1 while this replica holds the shard's lease "
            "(tpu-operator-shard-<i>), 0 otherwise",
            ("shard",),
        )
        self.shard_handoff_total = g(
            "shard_handoff_total",
            "Shard leases this replica lost (renewal lost, fenced, or "
            "released at shutdown) — each one is a handoff to a peer",
        )
        self.shard_events_dropped_total = g(
            "shard_events_dropped_total",
            "Watch events dropped before enqueue because their key "
            "belongs to a shard another replica owns",
        )
        # informer health (client-go reflector resync analogue): nonzero
        # means a watch stream silently swallowed an event and the
        # periodic re-list repaired the cache
        self.informer_drift_repairs = g(
            "informer_drift_repairs_total",
            "Cache objects repaired by informer resync (missed watch events)",
        )
        # zero-copy read path (client-go indexed-store analogue): reads
        # served from the informer stores, cumulative list latency, how
        # many lists the indexers answered in O(result), and how many
        # reads paid a deep copy (explicit copy=True writers only)
        self.cache_gets = g(
            "informer_cache_gets_total", "Gets served from informer stores"
        )
        self.cache_lists = g(
            "informer_cache_lists_total", "Lists served from informer stores"
        )
        self.cache_list_seconds = g(
            "informer_cache_list_seconds_total",
            "Cumulative wall time spent inside informer list()",
        )
        self.cache_indexed_lists = g(
            "informer_cache_indexed_lists_total",
            "Informer lists answered from an index bucket (O(result))",
        )
        self.cache_copied_reads = g(
            "informer_cache_copied_reads_total",
            "Cached objects deep-copied for explicit copy=True readers",
        )
        # per-pass reconcile snapshot (node scans + per-app pod lists
        # shared across the 18 states): last pass's hit/miss profile
        self.snapshot_hits = g(
            "reconcile_snapshot_hits",
            "Reads served by the per-pass cluster snapshot memo (last pass)",
        )
        self.snapshot_misses = g(
            "reconcile_snapshot_misses",
            "Reads the per-pass cluster snapshot had to compute (last pass)",
        )
        # memoized manifest render pipeline (desired-state fingerprint
        # short-circuit): a steady-state pass renders nothing — misses
        # staying 0 and the hit gauge at ~the control count is the tell
        self.render_cache_hits = g(
            "render_cache_hits",
            "Manifest renders served from the render cache (last pass)",
        )
        self.render_cache_misses = g(
            "render_cache_misses",
            "Manifests the render cache had to render (last pass)",
        )
        self.render_cache_entries = g(
            "render_cache_entries",
            "Rendered manifests currently memoized under the desired-state "
            "fingerprint",
        )
        # a gauge fed by .set() from the cache's own counter — no _total
        # suffix, which Prometheus conventions reserve for true Counters
        self.render_cache_invalidations = g(
            "render_cache_invalidations",
            "Full render-cache invalidations (desired-state fingerprint "
            "changes: spec edit, runtime flip, CR recreate)",
        )
        self.state_render_ms = g(
            "state_render_ms",
            "Cumulative manifest render wall time per state since the last "
            "fingerprint invalidation (ms)",
            ("state",),
        )
        # concurrent write pipeline (kube/write_pipeline.py): the
        # convergence fan-out's disposition — configured depth, live
        # in-flight writes, how long tasks wait for a worker, and task
        # failures (each also surfaced to its submitter)
        self.write_pipeline_depth = g(
            "write_pipeline_depth",
            "Configured write-pipeline concurrency (WRITE_PIPELINE_DEPTH; "
            "1 = serial escape hatch)",
        )
        self.write_pipeline_inflight = g(
            "write_pipeline_inflight",
            "Write-pipeline tasks currently executing",
        )
        self.write_pipeline_queue_wait_ms = g(
            "write_pipeline_queue_wait_ms",
            "Average queue wait before a pipeline worker picked a write up",
        )
        self.write_pipeline_errors_total = g(
            "write_pipeline_errors",
            "Write-pipeline tasks that raised (after the client's own "
            "retry/breaker policy gave up)",
        )
        # apiserver fault-tolerance surface (kube/retry.py): gauges fed
        # from the client's own counters each pass — retry pressure and
        # the global circuit breaker's disposition
        self.apiserver_retries = g(
            "apiserver_request_retries",
            "API requests retried by the client's fault-tolerance policy "
            "(transient 5xx/429/connection failures)",
        )
        self.apiserver_retry_giveups = g(
            "apiserver_retry_giveups",
            "API calls that exhausted their per-call retry budget",
        )
        self.apiserver_breaker_open = g(
            "apiserver_breaker_open",
            "1 while the global apiserver circuit breaker is open "
            "(requests fail fast instead of hammering a dead server)",
        )
        self.apiserver_breaker_trips = g(
            "apiserver_breaker_trips",
            "Times the apiserver circuit breaker tripped open",
        )
        # optimistic-concurrency pressure: each count is one 409 retry
        # inside mutate_with_retry (shared-object writers re-reading and
        # re-applying); sustained growth means writers are fighting.
        # Installed as the kube layer's hook so client.py never imports
        # upward into controllers.
        self.conflict_retries = c(
            "conflict_retries_total",
            "Optimistic-concurrency (409) retries in mutate_with_retry",
        )
        from tpu_operator.kube import client as _kube_client

        _kube_client.on_conflict_retry = self.conflict_retries.inc

        # latency HISTOGRAMS (ISSUE 10): the key point-in-time gauges
        # promoted to real fixed-bucket distributions — p50/p99 over
        # time instead of "whatever the last pass happened to read".
        # The legacy gauges stay (dashboards/tests read them); the
        # histograms are the alerting-grade series.
        self.reconcile_pass_ms_hist = h(
            "reconcile_pass_duration_ms",
            "Full reconcile pass wall time (ms)",
            PASS_MS_BUCKETS,
        )
        self.state_render_ms_hist = h(
            "state_render_duration_ms",
            "One manifest render+transform+hash on a render-cache miss "
            "(ms), per state",
            RENDER_MS_BUCKETS,
            ("state",),
        )
        self.write_pipeline_queue_wait_hist = h(
            "write_pipeline_queue_wait_duration_ms",
            "Queue wait before a write-pipeline worker picked a task up "
            "(ms)",
            QUEUE_WAIT_MS_BUCKETS,
        )
        self.apply_rtt_ms_hist = h(
            "apiserver_write_rtt_ms",
            "apiserver write round-trip (ms) by verb, retries included "
            "(APPLY is the server-side-apply hot path)",
            RTT_MS_BUCKETS,
            ("verb",),
        )
        self.alloc_latency_ms_hist = h(
            "alloc_latency_duration_ms",
            "Device-plugin allocation latency (GetPreferredAllocation -> "
            "Allocate -> ledger hold) in ms",
            ALLOC_MS_BUCKETS,
        )
        # event-scoped delta reconciliation (ISSUE 13): router trigger
        # disposition + sub-reconcile cost. source = watch kind that
        # fired (node/pod/clusterpolicy/daemonset); key_kind = routed
        # target (node/slice/full/upgrade, or drop for predicate-killed
        # no-op deliveries)
        self.reconcile_triggers = c(
            "reconcile_trigger_total",
            "Watch-event reconcile triggers routed by the delta router, "
            "by event source and target key kind",
            ("source", "key_kind"),
        )
        self.delta_reconcile_ms_hist = h(
            "delta_reconcile_duration_ms",
            "One event-scoped delta sub-reconcile (node label step or "
            "slice readiness aggregate) wall time in ms",
            DELTA_MS_BUCKETS,
        )
        # the kube layer feeds the queue-wait and write-RTT histograms
        # through module hooks (the on_conflict_retry convention: kube/
        # never imports upward into controllers/)
        from tpu_operator.kube import rest as _rest
        from tpu_operator.kube import write_pipeline as _wp

        _wp.on_queue_wait_ms = self.write_pipeline_queue_wait_hist.observe
        _rest.on_write_rtt_ms = self._observe_write_rtt

    def _observe_write_rtt(self, verb: str, ms: float) -> None:
        self.apply_rtt_ms_hist.labels(verb=verb).observe(ms)

    # -- convenience ----------------------------------------------------
    def observe_reconcile(self, status_value: int) -> None:
        self.reconciliation_total.inc()
        self.reconciliation_status.set(status_value)
        if status_value == 1:
            self.reconciliation_last_success.set(time.time())
        elif status_value < 0:
            self.reconciliation_failed.inc()

    def set_state(self, state: str, value: int) -> None:
        self.operand_states.labels(state=state).set(value)
