"""Health-gated progressive rollouts: canary waves with automatic
version rollback.

The reference ships a whole second reconciler just for driver upgrades
(``controllers/upgrade_controller.go`` registered beside the
ClusterPolicy one); our libtpu upgrade FSM (``upgrade/upgrade_state.py``)
and slice re-partition roller (``controllers/repartition.py``) went
further — slice-unit batching, a three-consumer disruption budget — but
both would happily march a *bad* version across the entire fleet: their
admission was gated only on the budget, never on health evidence. A
libtpu build that passes validation but tanks matmul TFLOPS would reach
every slice.

This orchestrator stages any fleet-wide version/layout change through
**canary → wave(s) → fleet** slice cohorts with a live health gate
between stages:

* **cohorts** are a deterministic pure function of ``(target, slice
  ids)`` — sha1-ordered, sized by ``spec.rollout.canary``/``waves``
  (int-or-percent of slices) — so every consumer, pass, and restarted
  operator computes the same assignment with nothing to persist;
* **progress** lives in one durable ledger annotation on the
  ClusterPolicy (``tpu.k8s.io/rollout-state``: kind, target, previous,
  stage, state, failing evidence), and the per-node **rollback facts**
  (previous version + pre-roll validator-perf baseline) are written by
  the upgrade FSM at admission — everything survives operator restarts;
* the **gate** consumes live evidence per cohort: validator TFLOPS /
  membw deltas vs the per-node baseline
  (``tpu.k8s.io/validator-perf[-baseline]`` annotations, published by
  the node-status exporter), NEW remediation quarantines among cohort
  members, upgrade failures (an exhausted ``upgrade-failed`` canary is
  evidence, not a silent stall), operand CrashLoopBackOff, a Degraded
  CR condition, and alloc-latency p99 regression vs the pre-roll
  reading when a latency source is wired;
* **admission** stays under the shared three-consumer disruption budget:
  the orchestrator only narrows which slices the upgrade FSM /
  re-partition roller may admit (``admit_filter``), it never adds
  capacity — rollback re-rolls draw on the same ``maxUnavailable`` pool
  as remediation and re-partitions;
* a regressing canary **pauses** the roll and (``autoRollback``, default
  on) drives **automatic rollback**: the ledger flips to ``rolled-back``
  and ``apply_override`` re-pins the *effective* desired version/layout
  to the recorded previous value before rendering — the FSM then sees
  the cohort's nodes as stale against the OLD version and re-rolls them
  back, while never-admitted waves (whose pods still match the restored
  desired state) are reset to done without a single disruption;
* every pause/rollback decision is **flight-recorded**
  (``obs/flight.py``) with an auto-dump and a warning Event naming the
  failing evidence.
"""

from __future__ import annotations

import hashlib
import json
import logging
import math
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from tpu_operator import consts
from tpu_operator.obs import LogOnce, flight
from tpu_operator.kube.client import Client, Obj, mutate_with_retry

log = logging.getLogger("tpu-operator.rollout")

# ledger kinds: which roller the staged change flows through
KIND_LIBTPU = "libtpu"
KIND_LAYOUT = "layout"

# ledger states
STATE_ROLLING = "rolling"
STATE_PAUSED = "paused"
STATE_ROLLED_BACK = "rolled-back"
STATE_COMPLETE = "complete"

# evidence list cap: a fleet-wide regression names the first few nodes,
# not a thousand of them, in Events and the ledger annotation
EVIDENCE_MAX = 8


# ---------------------------------------------------------------------------
# pure helpers — shared by the orchestrator (CP pass) and the upgrade
# reconciler's admission, so the two sides cannot drift
# ---------------------------------------------------------------------------


def raw_targets(cp_obj: Obj) -> Dict[str, str]:
    """The USER-authored fleet-wide targets straight off the spec dict
    (before any rollback override): the libtpu version and the desired
    slice layout."""
    spec = cp_obj.get("spec", {}) or {}
    return {
        KIND_LIBTPU: (spec.get("libtpu") or {}).get("version") or "",
        KIND_LAYOUT: (
            ((spec.get("sliceManager") or {}).get("config") or {}).get(
                "default"
            )
            or ""
        ),
    }


def load_record(cp_obj: Obj) -> Optional[dict]:
    """The rollout ledger off the CR's annotations (None when absent or
    garbled — a hand-edited annotation reads as 'no rollout')."""
    raw = (
        (cp_obj.get("metadata") or {}).get("annotations") or {}
    ).get(consts.ROLLOUT_STATE_ANNOTATION)
    if not raw:
        return None
    try:
        rec = json.loads(raw)
    except (ValueError, TypeError):
        return None
    if not isinstance(rec, dict) or not rec.get("target"):
        return None
    return rec


def apply_override(cp_obj: Obj) -> Dict[str, str]:
    """Pin the EFFECTIVE desired version/layout back to the recorded
    previous value while a rollback is in force — called by
    ``state_manager.init`` on its private CR copy BEFORE the spec is
    decoded and fingerprinted, so rendering, the upgrade FSM's desired
    hashes, and the re-partition roller all see the rollback target as
    the desired state. Returns the RAW user targets so the orchestrator
    can still tell where the user wants to go.

    The override never touches the stored CR (the user's spec is theirs;
    status writes go through the /status subresource) and lapses the
    moment the user moves the target off the failed version."""
    raw = raw_targets(cp_obj)
    rec = load_record(cp_obj)
    if not rec or rec.get("state") != STATE_ROLLED_BACK:
        return raw
    prev = rec.get("previous") or ""
    if not prev or raw.get(rec.get("kind", "")) != rec.get("target"):
        return raw
    spec = cp_obj.setdefault("spec", {})
    if rec["kind"] == KIND_LIBTPU:
        spec.setdefault("libtpu", {})["version"] = prev
    elif rec["kind"] == KIND_LAYOUT:
        spec.setdefault("sliceManager", {}).setdefault("config", {})[
            "default"
        ] = prev
    return raw


def _scaled_count(value, total: int) -> int:
    """int-or-percent stage size over ``total`` slices, minimum 1 (an
    empty canary would gate nothing)."""
    if total <= 0:
        return 0
    if value is None:
        return 1
    s = str(value).strip()
    try:
        if s.endswith("%"):
            return min(max(1, math.ceil(total * float(s[:-1]) / 100.0)), total)
        return min(max(1, int(s)), total)
    except (TypeError, ValueError):
        return 1


def cohort_stages(all_sids, target: str, spec) -> List[List[str]]:
    """Deterministic canary→wave(s)→fleet cohort assignment for a FRESH
    plan: slice ids ordered by ``sha1(target:sid)`` (stable across
    passes, restarts and processes; a different target draws a
    different canary), sliced into ``[canary] + waves + [remainder]``
    counts. Thin wrapper over ``planned_stages`` with no pinned
    cohorts, so the two can never drift."""
    return planned_stages({"target": target}, all_sids, spec)


def planned_stages(rec: dict, all_sids, spec) -> List[List[str]]:
    """The roll's stage plan with begun stages PINNED: cohorts already
    recorded in the ledger (``rec["cohorts"]`` — appended when a stage
    starts admitting) keep their membership verbatim, and only FUTURE
    stages are computed from the slices not yet claimed. Without the
    pin, a slice joining mid-roll could hash ahead of the live canary
    and silently grow stage 0's blast radius past its configured size;
    with it, late arrivals land in not-yet-begun stages only. Pure over
    ``(rec, all_sids, spec)`` — both reconcilers and a restarted
    operator compute the same plan."""
    live = set(all_sids)
    recorded: List[List[str]] = [
        [s for s in cohort]
        for cohort in (rec.get("cohorts") or [])
        if isinstance(cohort, (list, tuple))
    ]
    claimed = {s for cohort in recorded for s in cohort}
    target = rec.get("target", "")
    ordered = sorted(
        (s for s in live if s not in claimed),
        key=lambda s: hashlib.sha1(
            f"{target}:{s}".encode("utf-8", "replace")
        ).hexdigest(),
    )
    total = max(len(live | claimed), 1)
    counts = [_scaled_count(getattr(spec, "canary", "1"), total)]
    for wave in getattr(spec, "waves", None) or []:
        counts.append(_scaled_count(wave, total))
    stages: List[List[str]] = list(recorded)
    i = 0
    for idx in range(len(recorded), len(counts)):
        if i >= len(ordered):
            break
        stages.append(ordered[i : i + counts[idx]])
        i += counts[idx]
    if i < len(ordered):
        stages.append(ordered[i:])
    return [s for s in stages if s]


def admission_filter(cp_obj: Obj, all_sids) -> Optional[Set[str]]:
    """The slice ids the active rollout allows FRESH admissions for —
    ``None`` means unrestricted (no staged roll). Pure over the in-hand
    CR, so the upgrade reconciler computes the same gate the
    orchestrator does without shared mutable state, and a restarted
    operator is gated from its very first pass.

    Fail-closed discipline: while a version target exists but the
    ledger hasn't been written yet (the CP pass that stages it hasn't
    run), or the user just moved the target and the ledger is stale,
    admissions FREEZE rather than let a race admit the whole fleet
    ungated."""
    spec_d = ((cp_obj.get("spec") or {}).get("rollout")) or {}
    if not spec_d.get("enabled"):
        return None
    from tpu_operator.api.v1.clusterpolicy_types import RolloutSpec

    spec = RolloutSpec.from_dict(spec_d)
    raw = raw_targets(cp_obj)
    rec = load_record(cp_obj)
    if rec is None:
        # no ledger yet: a stageable (version) target freezes admission
        # until the orchestrator stages it; a version-less hash drift is
        # not stageable and rolls ungated
        return set() if raw[KIND_LIBTPU] else None
    kind = rec.get("kind", KIND_LIBTPU)
    if (
        raw.get(kind)
        and raw[kind] != rec.get("target")
        and raw[kind] != (rec.get("previous") or "")
    ):
        # the target moved somewhere NEW: freeze until the CP pass
        # re-stages. A spec reading as the recorded PREVIOUS version is
        # not a move — it is either the rollback override on the CP
        # pass's own (pinned) copy, or the user reverting, which the
        # orchestrator resolves by clearing the ledger
        return set()
    state = rec.get("state")
    if state == STATE_PAUSED:
        return set()
    if state in (STATE_ROLLED_BACK, STATE_COMPLETE):
        # rolled-back: desired is pinned to the previous version, so the
        # only stale slices ARE the rolled cohort — re-roll freely (the
        # shared disruption budget still caps concurrency);
        # complete: nothing left to stage
        return None
    stages = planned_stages(rec, all_sids, spec)
    if not stages:
        return None
    stage = min(max(int(rec.get("stage", 0) or 0), 0), len(stages) - 1)
    allowed: Set[str] = set()
    for cohort in stages[: stage + 1]:
        allowed.update(cohort)
    return allowed


def rollback_admission_filter(cp_obj: Obj, slice_nodes) -> Optional[Set[str]]:
    """The rolled-back refinement of ``admission_filter``: while a
    libtpu ledger says rolled-back, restrict fresh admissions to slices
    that actually NEED re-rolling — a member publishes a version other
    than the restored previous one, or carries the admission-time
    rollback annotation. This closes the one-pass window between the
    rollback decision and the re-render of the previous version, during
    which never-admitted waves still look stale against the ABANDONED
    target and an unrestricted gate would cordon/drain them for
    nothing; late joiners that came up on the bad version remain
    admissible. ``slice_nodes``: sid -> member node objects. Returns
    None when no libtpu rollback is in force."""
    rec = load_record(cp_obj)
    if (
        not rec
        or rec.get("state") != STATE_ROLLED_BACK
        or rec.get("kind") != KIND_LIBTPU
    ):
        return None
    prev = rec.get("previous") or ""
    if not prev:
        return None
    admit: Set[str] = set()
    for sid, nodes in slice_nodes.items():
        for node in nodes:
            labels = node.get("metadata", {}).get("labels", {}) or {}
            ann = node.get("metadata", {}).get("annotations", {}) or {}
            version = labels.get(consts.TFD_LIBTPU_VERSION_LABEL, "")
            if (version and version != prev) or (
                consts.UPGRADE_PREVIOUS_VERSION_ANNOTATION in ann
            ):
                admit.add(sid)
                break
    return admit


def _parse_perf(raw: str) -> Optional[dict]:
    if not raw:
        return None
    try:
        doc = json.loads(raw)
    except (ValueError, TypeError):
        return None
    return doc if isinstance(doc, dict) else None


def _iso_now() -> str:
    from datetime import datetime, timezone

    return datetime.now(timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")


def _parse_iso_s(ts: str) -> float:
    from datetime import datetime, timezone

    try:
        dt = datetime.fromisoformat(str(ts).replace("Z", "+00:00"))
    except (TypeError, ValueError):
        return 0.0
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=timezone.utc)
    return dt.timestamp()


# ---------------------------------------------------------------------------
# summary + controller
# ---------------------------------------------------------------------------


@dataclass
class RolloutSummary:
    """What one orchestrator pass saw/decided — feeds ``status.rollout``,
    /debug/vars, and the reconciler's requeue decision."""

    enabled: bool = False
    kind: str = ""
    target: str = ""
    previous: str = ""
    state: str = ""  # "" = no roll staged
    stage: int = 0
    stages_total: int = 0
    cohort_sids: List[str] = field(default_factory=list)
    evidence: List[str] = field(default_factory=list)
    errored: bool = False
    # rolled-back only: whether every node is back on the previous
    # version/layout (a converged rollback parks without a requeue
    # clock; the ledger stays for the user to acknowledge)
    rollback_converged: bool = False
    # the admission gate this pass computed (None = unrestricted) —
    # consumed by the same-pass repartition roll
    admit_sids: Optional[Set[str]] = None

    @property
    def active(self) -> bool:
        """In-flight staged work wants the level-triggered requeue: the
        observation window and the rollback's re-roll both elapse
        without any cluster event of ours. A paused roll — and a
        rollback that has fully converged back — waits for a human and
        needs no clock; an errored pass retries on it."""
        if self.errored or self.state == STATE_ROLLING:
            return True
        if self.state == STATE_ROLLED_BACK:
            return not self.rollback_converged
        return False

    def status_block(self) -> Optional[Dict[str, object]]:
        if not self.state:
            return None
        out: Dict[str, object] = {
            "kind": self.kind,
            "target": self.target,
            "state": self.state,
            "stage": f"{min(self.stage + 1, self.stages_total)}/{self.stages_total}"
            if self.stages_total
            else "0/0",
        }
        if self.previous:
            out["previous"] = self.previous
        if self.evidence:
            out["evidence"] = list(self.evidence)
        return out


class RolloutController:
    """Level-triggered rollout orchestration, run inside the reconcile
    pass (after remediation — whose fresh verdicts are gate evidence —
    and before the re-partition roll, which consumes the computed
    admission gate). With ``spec.rollout`` absent/disabled the pass is a
    label-dict scan that writes nothing."""

    def __init__(self, client: Client, namespace: str = ""):
        self.client = client
        self.namespace = namespace
        self.promotions_total = 0
        self.rollbacks_total = 0
        self.pauses_total = 0
        self.rollouts_started_total = 0
        self.rollouts_completed_total = 0
        self.last_summary: Dict[str, object] = {}
        self._logged = LogOnce()
        # optional live alloc-latency source (callable -> p99 ms or
        # None), wired by harnesses that run the schedsim engine; the
        # pre-roll reading is recorded in the ledger and regressions
        # past spec.rollout.allocP99DegradedPct count as evidence
        self.alloc_p99_source = None

    # ------------------------------------------------------------------
    def stats(self) -> Dict[str, object]:
        """/debug/vars "rollout" payload."""
        return {
            "last_pass": self.last_summary,
            "promotions_total": self.promotions_total,
            "rollbacks_total": self.rollbacks_total,
            "pauses_total": self.pauses_total,
            "rollouts_started_total": self.rollouts_started_total,
            "rollouts_completed_total": self.rollouts_completed_total,
        }

    def _alloc_p99(self) -> Optional[float]:
        src = self.alloc_p99_source
        if src is None:
            return None
        try:
            v = src()
            return float(v) if v is not None else None
        except Exception:
            return None

    # ------------------------------------------------------------------
    def reconcile(
        self,
        tpu_nodes: List[Obj],
        cp_obj: Obj,
        spec,
        raw: Dict[str, str],
        namespace: str,
        remediation_summary=None,
    ) -> RolloutSummary:
        """One orchestration pass over the labeled TPU node list.
        ``cp_obj`` is the reconciler's private CR copy (override already
        applied by init); ``raw`` is the user-authored targets
        ``apply_override`` returned; ``spec`` is ``cp.spec.rollout``."""
        self.namespace = namespace
        summary = RolloutSummary(enabled=bool(spec and spec.is_enabled()))
        if not summary.enabled:
            # rollout switched off: drop the ledger so a stale override
            # can't keep pinning the desired version
            if load_record(cp_obj) is not None:
                self._save_record(cp_obj, None)
                log.info("rollout disabled; ledger cleared")
            self.last_summary = {"enabled": False}
            return summary

        from tpu_operator.controllers.slice_status import group_slices

        slices = group_slices(tpu_nodes)
        labels_of = {
            n["metadata"]["name"]: (
                n.get("metadata", {}).get("labels", {}) or {}
            )
            for n in tpu_nodes
        }
        rec = load_record(cp_obj)

        # user moved the target away from the recorded roll: the old
        # ledger (and any rollback override) is superseded
        if rec is not None:
            kind = rec.get("kind", KIND_LIBTPU)
            if raw.get(kind, "") != rec.get("target"):
                self._record_event(
                    "Normal",
                    "RolloutSuperseded",
                    f"rollout of {kind} {rec.get('target')!r} superseded by "
                    f"a new target {raw.get(kind)!r}; restaging",
                    dedup_extra=str(raw.get(kind)),
                )
                self._save_record(cp_obj, None)
                rec = None

        if rec is None:
            rec = self._maybe_start(cp_obj, raw, labels_of, slices, spec)
        if rec is None:
            self.last_summary = {"enabled": True, "state": ""}
            return summary

        summary.kind = rec.get("kind", KIND_LIBTPU)
        summary.target = rec.get("target", "")
        summary.previous = rec.get("previous", "")
        summary.state = rec.get("state", STATE_ROLLING)

        stages = planned_stages(rec, slices.keys(), spec)
        summary.stages_total = len(stages)
        summary.stage = (
            min(max(int(rec.get("stage", 0) or 0), 0), len(stages) - 1)
            if stages
            else 0
        )
        cohort_sids: List[str] = []
        for s in stages[: summary.stage + 1]:
            cohort_sids.extend(s)
        summary.cohort_sids = cohort_sids
        summary.evidence = list(rec.get("evidence") or [])

        if summary.state == STATE_ROLLING and stages:
            self._step_rolling(
                cp_obj, rec, spec, summary, stages, slices, labels_of,
                tpu_nodes, remediation_summary,
            )
        elif summary.state == STATE_ROLLED_BACK:
            self._step_rolled_back(summary, labels_of)

        summary.admit_sids = admission_filter(cp_obj, slices.keys())
        if (
            summary.state == STATE_ROLLED_BACK
            and summary.kind == KIND_LAYOUT
            and summary.target
        ):
            # layout analogue of rollback_admission_filter: restrict the
            # same-pass repartition admission to slices actually ON (or
            # mid-roll to) the abandoned layout. Closes the one-pass
            # window between the rollback decision and the next init's
            # override re-pinning the desired layout, during which the
            # roller's desired value is still the BAD target and an
            # unrestricted gate would admit never-rolled waves to it.
            summary.admit_sids = {
                sid
                for sid, info in slices.items()
                if any(
                    labels_of.get(m, {}).get(consts.SLICE_CONFIG_LABEL)
                    == summary.target
                    or labels_of.get(m, {}).get(
                        consts.REPARTITION_STATE_LABEL
                    )
                    == consts.REPARTITION_STATE_ROLLING
                    for m in info.member_nodes
                )
            }
        self.last_summary = {
            "enabled": True,
            "kind": summary.kind,
            "target": summary.target,
            "previous": summary.previous,
            "state": summary.state,
            "stage": summary.stage,
            "stages_total": summary.stages_total,
            "cohort_size": len(summary.cohort_sids),
            "evidence": summary.evidence,
        }
        return summary

    # ------------------------------------------------------------------
    def _maybe_start(
        self, cp_obj, raw, labels_of, slices, spec
    ) -> Optional[dict]:
        """Stage a new roll when a fleet-wide target differs from what
        the fleet runs. The previous (rollback) version is the consensus
        of what the not-yet-rolled nodes report — recorded up front so
        the rollback target exists even if every cohort node is
        re-imaged before the gate trips."""
        from tpu_operator.sliceman.slice_manager import STATE_SUCCESS

        target = raw.get(KIND_LIBTPU, "")
        kind = None
        previous = ""
        if target:
            behind: Dict[str, int] = {}
            fsm_pending = False
            for labels in labels_of.values():
                v = labels.get(consts.TFD_LIBTPU_VERSION_LABEL, "")
                if v and v != target:
                    behind[v] = behind.get(v, 0) + 1
                ustate = labels.get(consts.UPGRADE_STATE_LABEL, "")
                if (
                    ustate == consts.UPGRADE_STATE_UPGRADE_REQUIRED
                    or ustate in consts.UPGRADE_ACTIVE_STATES
                ):
                    fsm_pending = True
            if behind or fsm_pending:
                kind = KIND_LIBTPU
                previous = (
                    max(behind.items(), key=lambda kv: (kv[1], kv[0]))[0]
                    if behind
                    else ""
                )
        if kind is None:
            layout = raw.get(KIND_LAYOUT, "")
            if layout:
                behind = {}
                pending = False
                for labels in labels_of.values():
                    cur = labels.get(consts.SLICE_CONFIG_LABEL, "")
                    done = (
                        cur == layout
                        and labels.get(consts.SLICE_CONFIG_STATE_LABEL)
                        == STATE_SUCCESS
                    )
                    if not done:
                        pending = True
                        if cur and cur != layout:
                            behind[cur] = behind.get(cur, 0) + 1
                if pending:
                    kind = KIND_LAYOUT
                    target = layout
                    previous = (
                        max(behind.items(), key=lambda kv: (kv[1], kv[0]))[0]
                        if behind
                        else ""
                    )
        if kind is None:
            return None
        rec = {
            "kind": kind,
            "target": target,
            "previous": previous,
            "stage": 0,
            "state": STATE_ROLLING,
            "createdAt": _iso_now(),
            "stageStartedAt": _iso_now(),
        }
        # pin the canary cohort in the ledger the moment the roll is
        # staged: slices joining mid-roll must land in future stages,
        # never grow a begun stage's blast radius
        first = planned_stages(rec, slices.keys(), spec)
        if first:
            rec["cohorts"] = [list(first[0])]
        p99 = self._alloc_p99()
        if p99 is not None:
            rec["allocP99Baseline"] = round(p99, 2)
        self._save_record(cp_obj, rec)
        self.rollouts_started_total += 1
        flight.record(
            "rollout.start", kind=kind, target=target, previous=previous
        )
        self._record_event(
            "Normal",
            "RolloutStarted",
            f"staged {kind} rollout to {target!r} started "
            f"(previous {previous!r}; canary first, health-gated)",
            dedup_extra=target,
        )
        log.info(
            "rollout: staging %s %r -> %r (canary first)",
            kind,
            previous,
            target,
        )
        return rec

    # ------------------------------------------------------------------
    def _step_rolling(
        self, cp_obj, rec, spec, summary, stages, slices, labels_of,
        tpu_nodes, remediation_summary=None,
    ) -> None:
        cohort_nodes = []
        for sid in summary.cohort_sids:
            info = slices.get(sid)
            if info is None:
                continue
            cohort_nodes.extend(info.member_nodes)
        evidence = self._collect_evidence(
            cp_obj, rec, spec, summary, cohort_nodes, labels_of, tpu_nodes,
            remediation_summary,
        )
        if evidence:
            summary.evidence = evidence
            rec["evidence"] = evidence
            if spec.rollback_enabled() and rec.get("previous"):
                rec["state"] = STATE_ROLLED_BACK
                rec["rolledBackAt"] = _iso_now()
                summary.state = STATE_ROLLED_BACK
                self.rollbacks_total += 1
                self._save_record(cp_obj, rec)
                for ev in evidence:
                    flight.record("rollout.evidence", detail=ev)
                flight.record(
                    "rollout.rollback",
                    kind=summary.kind,
                    target=summary.target,
                    previous=summary.previous,
                    stage=summary.stage,
                )
                detail = "; ".join(evidence)
                flight.RECORDER.dump(
                    "rollout-rollback",
                    detail=detail,
                    extra={
                        "target": summary.target,
                        "previous": summary.previous,
                        "stage": summary.stage,
                        "evidence": evidence,
                    },
                )
                self._record_event(
                    "Warning",
                    "RolloutRolledBack",
                    f"{summary.kind} rollout to {summary.target!r} failed "
                    f"its health gate at stage "
                    f"{summary.stage + 1}/{summary.stages_total} and is "
                    f"rolling back to {summary.previous!r}: {detail}",
                    dedup_extra=summary.target,
                )
                log.error(
                    "rollout: ROLLING BACK %s %r -> %r (stage %d): %s",
                    summary.kind,
                    summary.target,
                    summary.previous,
                    summary.stage,
                    detail,
                )
            else:
                rec["state"] = STATE_PAUSED
                rec["pausedAt"] = _iso_now()
                summary.state = STATE_PAUSED
                self.pauses_total += 1
                self._save_record(cp_obj, rec)
                for ev in evidence:
                    flight.record("rollout.evidence", detail=ev)
                flight.record(
                    "rollout.pause",
                    kind=summary.kind,
                    target=summary.target,
                    stage=summary.stage,
                )
                detail = "; ".join(evidence)
                flight.RECORDER.dump(
                    "rollout-paused",
                    detail=detail,
                    extra={"target": summary.target, "evidence": evidence},
                )
                self._record_event(
                    "Warning",
                    "RolloutPaused",
                    f"{summary.kind} rollout to {summary.target!r} paused "
                    f"at stage {summary.stage + 1}/{summary.stages_total} "
                    f"on failing health evidence (no rollback target or "
                    f"autoRollback off): {detail}",
                    dedup_extra=summary.target,
                )
                log.error(
                    "rollout: PAUSED %s -> %r (stage %d): %s",
                    summary.kind,
                    summary.target,
                    summary.stage,
                    detail,
                )
            return

        # healthy: promote when the current stage finished rolling and
        # soaked for observeSeconds
        stage_sids = stages[summary.stage]
        live_stage = [sid for sid in stage_sids if sid in slices]
        if not live_stage and any(s in slices for st in stages for s in st):
            # the ENTIRE begun cohort left the fleet (preemption wave):
            # promoting would gate on zero evidence — re-pin this stage
            # from the surviving universe and restart its clock instead
            pins = [list(s) for s in (rec.get("cohorts") or [])][
                : summary.stage
            ]
            rec["cohorts"] = pins
            replanned = planned_stages(rec, slices.keys(), spec)
            if len(replanned) > summary.stage:
                rec["cohorts"] = pins + [list(replanned[summary.stage])]
                rec.pop("stageRolledAt", None)
                rec["stageStartedAt"] = _iso_now()
                self._save_record(cp_obj, rec)
                self._log_once(
                    ("restage", summary.target, summary.stage),
                    "rollout: stage %d cohort vanished from the fleet; "
                    "restaged with %d surviving slice(s)",
                    summary.stage + 1,
                    len(replanned[summary.stage]),
                )
                return
        rolled = all(
            self._slice_rolled(
                slices[sid], rec, labels_of
            )
            for sid in live_stage
        )
        if not rolled:
            if rec.get("stageRolledAt"):
                rec.pop("stageRolledAt", None)
                self._save_record(cp_obj, rec)
            return
        now = time.time()
        rolled_at = _parse_iso_s(rec.get("stageRolledAt", ""))
        if not rolled_at:
            rec["stageRolledAt"] = _iso_now()
            self._save_record(cp_obj, rec)
            return
        observe = float(getattr(spec, "observe_seconds", 60) or 0)
        if now - rolled_at < observe:
            return
        # observation clean: promote
        next_stage = summary.stage + 1
        if next_stage >= len(stages):
            rec["state"] = STATE_COMPLETE
            rec["completedAt"] = _iso_now()
            rec.pop("stageRolledAt", None)
            summary.state = STATE_COMPLETE
            self.rollouts_completed_total += 1
            self._save_record(cp_obj, rec)
            flight.record(
                "rollout.complete", kind=summary.kind, target=summary.target
            )
            self._record_event(
                "Normal",
                "RolloutComplete",
                f"{summary.kind} rollout to {summary.target!r} completed "
                f"through all {len(stages)} stage(s) with a clean health "
                f"gate at every promotion",
                dedup_extra=summary.target,
            )
            log.info(
                "rollout: %s -> %r COMPLETE (%d stages)",
                summary.kind,
                summary.target,
                len(stages),
            )
            return
        rec["stage"] = next_stage
        rec["stageStartedAt"] = _iso_now()
        rec.pop("stageRolledAt", None)
        # pin the stage that is about to start admitting (see
        # planned_stages: begun stages keep their membership verbatim)
        rec["cohorts"] = [list(s) for s in stages[: next_stage + 1]]
        summary.stage = next_stage
        self.promotions_total += 1
        self._save_record(cp_obj, rec)
        flight.record(
            "rollout.promote",
            kind=summary.kind,
            target=summary.target,
            stage=next_stage,
            cohort=len(stages[next_stage]),
        )
        self._record_event(
            "Normal",
            "RolloutStagePromoted",
            f"{summary.kind} rollout to {summary.target!r}: stage "
            f"{summary.stage}/{len(stages) - 1} healthy through its "
            f"observation window; promoting to stage "
            f"{next_stage + 1}/{len(stages)} "
            f"({len(stages[next_stage])} slice(s))",
            dedup_extra=f"{summary.target}:{next_stage}",
        )
        log.info(
            "rollout: %s -> %r promoted to stage %d/%d",
            summary.kind,
            summary.target,
            next_stage + 1,
            len(stages),
        )

    def _slice_rolled(self, info, rec, labels_of) -> bool:
        """Whether every member host of one slice finished this roll.
        For libtpu: version label at target (when published) and the
        upgrade FSM idle/done — a node the FSM hasn't even entered yet
        does NOT read as done unless its version already matches. For a
        layout: config label at target with state success and the
        rolling hold released."""
        from tpu_operator.sliceman.slice_manager import STATE_SUCCESS

        target = rec.get("target", "")
        kind = rec.get("kind", KIND_LIBTPU)
        for member in info.member_nodes:
            labels = labels_of.get(member)
            if labels is None:
                return False
            if kind == KIND_LIBTPU:
                ustate = labels.get(consts.UPGRADE_STATE_LABEL, "")
                if ustate not in ("", consts.UPGRADE_STATE_DONE):
                    return False
                version = labels.get(consts.TFD_LIBTPU_VERSION_LABEL, "")
                if version and version != target:
                    # publishing a non-target version = not rolled. A
                    # version-LESS node with an idle FSM counts as done
                    # (nothing distinguishes it from never-stale); the
                    # observation window re-checks after the FSM's next
                    # pass would have entered it, so a premature read
                    # self-corrects before promotion
                    return False
            else:
                if (
                    labels.get(consts.SLICE_CONFIG_LABEL) != target
                    or labels.get(consts.SLICE_CONFIG_STATE_LABEL)
                    != STATE_SUCCESS
                    or labels.get(consts.REPARTITION_STATE_LABEL)
                    == consts.REPARTITION_STATE_ROLLING
                ):
                    return False
        return True

    def _step_rolled_back(self, summary, labels_of) -> None:
        """While rolled back, track how far the fleet is from the
        restored previous version (the FSM / re-partition roller do the
        actual re-rolling — the override makes the previous value the
        desired state). A fully-converged rollback parks: the ledger
        stays for the user, but the requeue clock stops."""
        from tpu_operator.sliceman.slice_manager import STATE_SUCCESS

        previous = summary.previous
        if not previous:
            summary.rollback_converged = True
            return
        if summary.kind == KIND_LIBTPU:
            behind = sorted(
                name
                for name, labels in labels_of.items()
                if labels.get(consts.TFD_LIBTPU_VERSION_LABEL, "")
                not in ("", previous)
                or labels.get(consts.UPGRADE_STATE_LABEL, "")
                in consts.UPGRADE_ACTIVE_STATES
            )
        else:
            behind = sorted(
                name
                for name, labels in labels_of.items()
                if labels.get(consts.SLICE_CONFIG_LABEL, "") != previous
                or labels.get(consts.SLICE_CONFIG_STATE_LABEL)
                != STATE_SUCCESS
            )
        summary.rollback_converged = not behind
        if behind:
            self._log_once(
                ("rollback", summary.target),
                "rollout: rolling %d node(s) back to %r (%s)",
                len(behind),
                previous,
                ", ".join(behind[:5]),
            )
        else:
            self._logged.discard(("rollback", summary.target))

    # ------------------------------------------------------------------
    def _collect_evidence(
        self, cp_obj, rec, spec, summary, cohort_nodes, labels_of, tpu_nodes,
        remediation_summary=None,
    ) -> List[str]:
        """The health gate: live failure evidence among cohort members.
        Every returned string names the node and the failing signal —
        these go verbatim into the ledger, the Warning Event, and the
        flight-recorder dump."""
        from tpu_operator.upgrade.upgrade_state import (
            FAILED_RETRY_MAX,
            failed_retry_count,
        )

        evidence: List[str] = []
        created_at = _parse_iso_s(rec.get("createdAt", ""))
        target = rec.get("target", "")
        nodes_by_name = {n["metadata"]["name"]: n for n in tpu_nodes}
        crash_by_node, validator_nodes = self._operand_health()

        tflops_pct = float(getattr(spec, "tflops_degraded_pct", 10) or 0)
        membw_pct = float(getattr(spec, "membw_degraded_pct", 10) or 0)

        # SAME-PASS quarantines: labels the remediation pass wrote this
        # very reconcile are on the wire but not in the pass-start node
        # snapshot — a canary quarantined in the pass its observation
        # window elapses must still block the promotion
        fresh_quarantines = set(cohort_nodes) & set(
            getattr(remediation_summary, "newly_disrupted_hosts", None)
            or ()
        )
        for name in sorted(fresh_quarantines)[:EVIDENCE_MAX]:
            evidence.append(
                f"node {name}: remediation quarantine during the roll "
                f"(this pass)"
            )

        for name in sorted(set(cohort_nodes)):
            if len(evidence) >= EVIDENCE_MAX:
                break
            labels = labels_of.get(name)
            node = nodes_by_name.get(name)
            if labels is None or node is None:
                continue
            ann = node["metadata"].get("annotations", {}) or {}

            # new remediation quarantine among cohort members
            rstate = labels.get(consts.REMEDIATION_STATE_LABEL, "")
            if rstate in consts.REMEDIATION_DISRUPTED_STATES:
                since = _parse_iso_s(
                    ann.get(consts.REMEDIATION_STATE_SINCE_ANNOTATION, "")
                )
                if not created_at or not since or since >= created_at:
                    evidence.append(
                        f"node {name}: remediation {rstate} during the roll"
                    )
                    continue

            version = labels.get(consts.TFD_LIBTPU_VERSION_LABEL, "")
            rolled = (
                version == target
                if summary.kind == KIND_LIBTPU
                else labels.get(consts.SLICE_CONFIG_LABEL) == target
            )
            # signals scoped to THIS roll: the node rolled to the
            # target, or its FSM state was (re)stamped after the roll
            # was staged — a node parked upgrade-failed/crashlooping
            # since BEFORE the roll must not veto a healthy new roll
            ustate_since = _parse_iso_s(
                ann.get(consts.UPGRADE_STATE_SINCE_ANNOTATION, "")
            )
            in_this_roll = (
                rolled
                or not created_at
                or (ustate_since and ustate_since >= created_at)
            )

            # upgrade failure — an exhausted canary is evidence, not a
            # silent stall (pre-gate it just parked as failed while the
            # roll neither advanced nor rolled back)
            ustate = labels.get(consts.UPGRADE_STATE_LABEL, "")
            if ustate == consts.UPGRADE_STATE_FAILED and in_this_roll:
                retries = failed_retry_count(node)
                exhausted = (
                    ", retries exhausted"
                    if retries >= FAILED_RETRY_MAX
                    else f", retry {retries}/{FAILED_RETRY_MAX}"
                )
                evidence.append(
                    f"node {name}: upgrade-failed{exhausted}"
                )
                continue

            # operand crashloop (an optionally-crashlooping bad version)
            crash = crash_by_node.get(name)
            if crash and in_this_roll:
                evidence.append(
                    f"node {name}: operand pod(s) in CrashLoopBackOff "
                    f"({', '.join(sorted(crash)[:3])})"
                )
                continue

            # validator down AFTER the node rolled to the target
            if (
                rolled
                and labels.get(
                    consts.DEPLOY_LABEL_PREFIX
                    + consts.COMPONENT_OPERATOR_VALIDATOR
                )
                == "true"
                and validator_nodes is not None
                and name not in validator_nodes
                and ustate in ("", consts.UPGRADE_STATE_DONE)
            ):
                evidence.append(
                    f"node {name}: validator not Running after rolling to "
                    f"{target!r}"
                )
                continue

            # validator perf regression vs the pre-roll baseline (the
            # headline case: a version that passes validation but tanks
            # matmul TFLOPS / HBM bandwidth). For a libtpu roll the
            # reading must be TAGGED with the target version (a stale
            # pre-roll reading equals the baseline and must not mask the
            # window); for a layout roll the version tag is unrelated —
            # readings count once the node reports the layout applied
            perf = _parse_perf(ann.get(consts.VALIDATOR_PERF_ANNOTATION, ""))
            base = _parse_perf(
                ann.get(consts.VALIDATOR_PERF_BASELINE_ANNOTATION, "")
            )
            perf_applicable = (
                perf is not None
                and base is not None
                and (
                    perf.get("version") == target
                    if summary.kind == KIND_LIBTPU
                    else rolled
                )
            )
            if perf_applicable:
                for key, pct, unit in (
                    ("tflops", tflops_pct, "TFLOPS"),
                    ("gbps", membw_pct, "GB/s membw"),
                ):
                    try:
                        now_v = float(perf.get(key))
                        base_v = float(base.get(key))
                    except (TypeError, ValueError):
                        continue
                    if base_v <= 0 or pct <= 0:
                        continue
                    if now_v < base_v * (1.0 - pct / 100.0):
                        evidence.append(
                            f"node {name}: {now_v:g} {unit} at {target!r} "
                            f"vs pre-roll baseline {base_v:g} "
                            f"(> {pct:g}% regression)"
                        )
                        break

        # a Degraded CR condition is fleet-level evidence
        if len(evidence) < EVIDENCE_MAX:
            for cond in (
                (cp_obj.get("status") or {}).get("conditions") or []
            ):
                if (
                    cond.get("type") == "Degraded"
                    and cond.get("status") == "True"
                ):
                    evidence.append(
                        "ClusterPolicy Degraded "
                        f"({cond.get('reason', 'unknown')})"
                    )
                    break

        # alloc-latency p99 regression vs the pre-roll reading
        if len(evidence) < EVIDENCE_MAX:
            base_p99 = rec.get("allocP99Baseline")
            now_p99 = self._alloc_p99()
            pct = float(getattr(spec, "alloc_p99_degraded_pct", 100) or 0)
            if (
                base_p99 is not None
                and now_p99 is not None
                and pct > 0
                and float(base_p99) > 0
                and now_p99 > float(base_p99) * (1.0 + pct / 100.0)
            ):
                evidence.append(
                    f"alloc p99 {now_p99:.0f} ms vs pre-roll "
                    f"{float(base_p99):.0f} ms (> {pct:g}% regression)"
                )
        return evidence[:EVIDENCE_MAX]

    def _operand_health(self):
        """ONE namespace pod listing (informer-served) per ACTIVE pass:
        crashlooping tpu-* operand pods by node + the set of nodes with
        a Running, ready validator pod. Steady state (no staged roll)
        never calls this."""
        from tpu_operator.controllers.remediation import pod_crashlooping
        from tpu_operator.controllers.slice_status import VALIDATOR_APP

        crash_by_node: Dict[str, List[str]] = {}
        validator_nodes: Optional[Set[str]] = set()
        try:
            pods = self.client.list("v1", "Pod", self.namespace)
        except Exception:
            return {}, None  # listing failed: no pod-derived evidence
        for pod in pods:
            node = pod.get("spec", {}).get("nodeName")
            if not node:
                continue
            app = (
                (pod.get("metadata", {}).get("labels") or {}).get("app") or ""
            )
            if app.startswith("tpu-") and pod_crashlooping(pod):
                crash_by_node.setdefault(node, []).append(
                    pod["metadata"]["name"]
                )
            if app == VALIDATOR_APP and pod.get("status", {}).get(
                "phase"
            ) == "Running":
                statuses = pod.get("status", {}).get("containerStatuses")
                if statuses is None or all(
                    cs.get("ready", True) for cs in statuses
                ):
                    validator_nodes.add(node)
        return crash_by_node, validator_nodes

    # ------------------------------------------------------------------
    def _save_record(self, cp_obj: Obj, rec: Optional[dict]) -> None:
        """Persist the ledger annotation (conflict-retried; the CR is
        shared with the status writer and user spec edits) and keep the
        in-hand copy coherent for same-pass readers (the admission
        filter computed right after)."""
        desired = (
            json.dumps(rec, sort_keys=True) if rec is not None else None
        )
        meta = cp_obj.setdefault("metadata", {})
        name = meta.get("name", "")

        def mutate(obj):
            ann = obj["metadata"].setdefault("annotations", {})
            if desired is None:
                if consts.ROLLOUT_STATE_ANNOTATION not in ann:
                    return False
                del ann[consts.ROLLOUT_STATE_ANNOTATION]
                return True
            if ann.get(consts.ROLLOUT_STATE_ANNOTATION) == desired:
                return False
            ann[consts.ROLLOUT_STATE_ANNOTATION] = desired
            return True

        try:
            mutate_with_retry(
                self.client,
                consts.API_VERSION,
                consts.CLUSTER_POLICY_KIND,
                name,
                mutate=mutate,
            )
        except Exception:
            # the in-hand copy still carries the new ledger for this
            # pass's gate; the next pass retries the write
            log.exception("rollout ledger write failed")
        ann = meta.setdefault("annotations", {})
        if desired is None:
            ann.pop(consts.ROLLOUT_STATE_ANNOTATION, None)
        else:
            ann[consts.ROLLOUT_STATE_ANNOTATION] = desired

    # ------------------------------------------------------------------
    def _log_once(self, key: tuple, msg: str, *args) -> None:
        self._logged.log(log, key, msg, *args)

    def _record_event(
        self, etype: str, reason: str, message: str, dedup_extra: str = ""
    ) -> None:
        from tpu_operator.kube.events import cluster_policy_ref, record_event

        try:
            record_event(
                self.client,
                self.namespace,
                cluster_policy_ref(),
                etype,
                reason,
                message,
                dedup_extra=dedup_extra,
            )
        except Exception:
            log.debug("rollout event write failed", exc_info=True)
