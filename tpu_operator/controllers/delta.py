"""Event-scoped delta reconciliation (ISSUE 13).

Every trigger used to run the full 18-state fleet-wide pass through one
worker: a single pod crashloop at 10k nodes paid the whole scan, and
churn storms serialized behind that one thread. The reference model
(PAPER.md) is per-object — ``Reconcile(ctx, req)`` driven by watch
predicates feeding a keyed workqueue. This module is that shape for the
repo's level-triggered architecture, in two halves:

* :class:`EventRouter` — maps each watch event to the *minimal* affected
  unit as a typed queue key, with predicates dropping no-op deliveries
  (status-only CR echoes, irrelevant label churn) before they enqueue:

  ============================  =======================================
  event                         routed key
  ============================  =======================================
  node label/status change      ``("node", name)`` — that node's label
                                FSM step (+ its slice when the change is
                                readiness-relevant)
  pod (validator) transition    ``("slice", sid)`` — its slice's
                                readiness aggregate
  node DELETE                   ``("node", name)`` + ``("slice", sid)``
                                — ledger prune + slice regroup at event
                                speed (plus the upgrade wake)
  CR generation/spec change     full render pass (barrier key)
  TPU-facts change (join,       full pass — cluster facts (generation
  generation flip)              set, counts) feed the render fan-out
  ============================  =======================================

* :class:`DeltaReconciler` — the per-key entry points
  (``reconcile_node``/``reconcile_slice``) that reuse the existing
  label-lane / slice-aggregation / write-pipeline machinery but read and
  write ONLY the keyed unit. Anything needing fleet context (the
  budgeted remediation FSM, slice formation on join) escalates to the
  full pass instead of guessing.

The periodic full pass is demoted to a low-frequency resync safety net
(``RECONCILE_RESYNC_S``, default 300 s — manager.add_reconciler's
``resync_s``) that must still converge anything the delta path missed.
``TPU_DELTA_RECONCILE=0`` disables the router entirely (every event
routes to the full-pass keys, the pre-ISSUE-13 behavior).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, List, Optional

from tpu_operator import consts
from tpu_operator.obs import trace

log = logging.getLogger("tpu-operator.delta")

NODE_KIND = "node"
SLICE_KIND = "slice"

# node labels whose change flips a slice's readiness verdict (or its
# identity/expected-host math) without touching cluster facts: route to
# the slice aggregate, not the full pass. The GKE topology and node-pool
# labels feed _expected_hosts / slice_id_for_node when TFD hasn't
# stamped its own labels yet.
_READINESS_LABELS = (
    consts.MAINTENANCE_STATE_LABEL,
    consts.REMEDIATION_STATE_LABEL,
    consts.REPARTITION_STATE_LABEL,
    consts.SLICE_READY_LABEL,
    consts.TFD_SLICE_HOSTS_LABEL,
    consts.GKE_TPU_TOPOLOGY_LABEL,
    consts.GKE_NODEPOOL_LABEL,
)


def delta_enabled() -> bool:
    """Router default from ``TPU_DELTA_RECONCILE`` (on unless 0/false)."""
    return os.environ.get("TPU_DELTA_RECONCILE", "1").lower() not in (
        "0",
        "false",
        "off",
    )


def default_resync_s() -> float:
    """Full-pass safety-net cadence (``RECONCILE_RESYNC_S``, 300 s)."""
    try:
        return float(os.environ.get("RECONCILE_RESYNC_S", "300"))
    except ValueError:
        return 300.0


def _labels(obj: Optional[dict]) -> dict:
    return ((obj or {}).get("metadata", {}).get("labels") or {}) if obj else {}


class DeltaReconciler:
    """Targeted sub-reconciles riding the keyed workqueue.

    Owned by the :class:`ClusterPolicyReconciler`; the full pass feeds it
    the authoritative slice aggregate (``note_full_pass``) and the delta
    passes keep that mirror — and ``status.slices`` — current at event
    speed between full passes. All shared state sits under ``_lock``
    because independent keys run on different workers concurrently (the
    queue only serializes per key)."""

    def __init__(self, reconciler):
        self.rec = reconciler
        self.client = reconciler.client
        # wired by build_manager: wake the full pass / enqueue a slice
        # key / schedule a coalesced status publish (the delta path
        # itself has no queue handle)
        self.wake_full = None
        self.enqueue_slice = None
        self.enqueue_status = None
        self._lock = threading.Lock()
        # one status.slices writer at a time: concurrent slice workers
        # would otherwise trade 409s on the CR for no information
        self._status_lock = threading.Lock()
        # sid -> SliceInfo: mirror of the last authoritative aggregate,
        # per-slice entries replaced by slice sub-reconciles
        self._slices: Dict[str, object] = {}
        # sid -> ready: verdicts INGESTED from other replicas' label
        # writes (sharded mode, full-pass owner only) — status.slices
        # stays event-fresh for shards this replica doesn't recompute;
        # cleared whenever a full aggregation re-seeds the mirror
        self._foreign: Dict[str, bool] = {}
        self._have_full = False
        # sub-reconciles dispatched for keys this replica no longer
        # owns (a handoff raced the queue): skipped, counted
        self.shard_skips = 0
        # counters (under _lock: sub-reconciles run on N workers)
        self.node_passes = 0
        self.slice_passes = 0
        self.delta_ms_total = 0.0
        self.escalations = 0
        self.status_writes = 0
        self.last: Dict[str, object] = {}

    # ------------------------------------------------------------------
    # full-pass handshake
    # ------------------------------------------------------------------
    def note_full_pass(self, slice_summary) -> None:
        """Seed the slice mirror from a completed full aggregation —
        the delta path refines per-slice entries from here on.

        Sharded takeover race: a SCOPED pass that was already in flight
        when this replica gained shard 0 would otherwise re-mark its
        one-shard mirror as full context right after the takeover's
        ``invalidate_context`` — and the new owner would publish a
        shrunken global ``status.slices`` from it. A scoped summary may
        only seed context while this replica is NOT the full-pass
        owner."""
        if slice_summary is None:
            return
        sm = self._shard_state()
        if (
            getattr(self.rec, "_scoped_pass_active", False)
            and sm is not None
            and sm.owns_full_pass()
        ):
            return
        with self._lock:
            self._slices = dict(slice_summary.slices)
            self._foreign.clear()
            self._have_full = True

    # ------------------------------------------------------------------
    # sharded scale-out helpers
    # ------------------------------------------------------------------
    def _shard_state(self):
        return getattr(self.rec, "shard_state", None)

    def _owns(self, kind: str, key: str) -> bool:
        """Dispatch-time ownership re-check: a key enqueued before a
        handoff may dispatch after it — skipping is always safe (the
        new owner re-derives from its own events/resync), running is
        the overlap the handoff contract forbids."""
        sm = self._shard_state()
        if sm is None:
            return True
        owned = (
            sm.owns_node_name(key)
            if kind == NODE_KIND
            else sm.owns_slice(key)
        )
        if not owned:
            with self._lock:
                self.shard_skips += 1
        return owned

    def invalidate_context(self) -> None:
        """Drop the full-pass context (sharded takeover of shard 0): a
        mirror seeded by a SCOPED pass holds a partial world, and
        publishing global status from it would shrink ``status.slices``
        to one shard's counts — every delta path escalates/holds until
        the first GLOBAL aggregation re-seeds."""
        with self._lock:
            self._have_full = False
            self._slices = {}
            self._foreign.clear()

    def ingest_foreign_verdict(self, sid: str, ready: bool) -> None:
        """A non-owned slice's verdict label, written by its owning
        replica and observed through the watch: fold it into
        ``status.slices`` without recomputing the slice (O(1) — the
        owner already did the O(members) work). Context-gated like
        every other status path: before the first GLOBAL aggregation
        the mirror is empty/partial and publishing from it would
        overwrite a correct block with a shrunken one."""
        if not self._context_ready():
            return
        with self._lock:
            if self._foreign.get(sid) == ready:
                return
            self._foreign[sid] = ready
        enq = self.enqueue_status
        if enq is not None:
            # this runs on the WATCH-DISPATCH hook thread: a blocking
            # CR status write here would stall event ingestion for
            # every kind behind one slow apiserver round-trip — hand
            # the publish to the workqueue (same-key bursts coalesce)
            enq()
        else:
            self._publish_status()

    def publish_status_now(self):
        """Keyed-queue entry point for the coalesced status publish."""
        self._publish_status()
        return None

    def _context_ready(self) -> bool:
        ctrl = self.rec.ctrl
        return bool(
            self.rec.passes_total >= 1
            and ctrl.cp_obj
            and ctrl.namespace
            and self._have_full
        )

    def _escalate(self, why: str, delay: float = 0.0) -> None:
        with self._lock:
            self.escalations += 1
            self.last = {"escalated": why}
        wake = self.wake_full
        if wake is not None:
            wake(delay)

    def expected_verdict(self, sid: str) -> Optional[str]:
        """The verdict the mirror believes this slice carries — the
        router's echo predicate: a node event whose ONLY change is the
        slice-ready label landing at this value is our own write
        bouncing back through the watch, not new information."""
        with self._lock:
            info = self._slices.get(sid)
        if info is None:
            return None
        return "true" if info.ready else "false"

    def remediation_enabled(self) -> bool:
        """Router hint: only when the remediation FSM is actually
        enabled does a health transition need the budgeted full pass."""
        try:
            spec = self.rec.ctrl.cp.spec.remediation
        except Exception:
            return False
        return bool(spec is not None and spec.is_enabled())

    # ------------------------------------------------------------------
    # per-node sub-reconcile
    # ------------------------------------------------------------------
    def reconcile_node(self, name: str):
        """The minimal unit for a node event: this node's operator-label
        delta (the label FSM step) through the batched label lane, or —
        on deletion — event-speed ledger pruning. Fleet context
        (remediation budget math, join-driven cluster facts) escalates
        to the full pass."""
        if not self._owns(NODE_KIND, name):
            return None
        if not self._context_ready():
            self._escalate(f"node/{name}: no full-pass context yet")
            return None
        t0 = time.perf_counter()
        with trace.span("delta.reconcile", kind=NODE_KIND, key=name):
            try:
                self._reconcile_node(name)
            finally:
                self._account(NODE_KIND, name, t0)
        return None

    def _reconcile_node(self, name: str) -> None:
        from tpu_operator.controllers.state_manager import (
            _label_apply_payload,
        )

        node = self.client.get_or_none("v1", "Node", name)
        if node is None:
            self._forget_node(name)
            return
        ctrl = self.rec.ctrl
        changes = ctrl._node_label_changes(node)
        if changes:
            fut = ctrl.label_lane.submit(
                ("Node", "", name), _label_apply_payload(name, changes)
            )
            # None = the node vanished mid-label (the outcome handler
            # absorbs the 404): prune ledgers now, not at the resync
            if ctrl._label_outcome(node, changes, fut) is None:
                self._forget_node(name)
                return
        if self._needs_remediation(node):
            # the remediation FSM steps under a fleet-wide shared
            # disruption budget + systemic breaker — per-node math would
            # guess; run the budgeted pass now instead of at resync
            self._escalate(f"node/{name}: remediation-relevant", 0.05)

    def _needs_remediation(self, node: dict) -> bool:
        if not self.remediation_enabled():
            return False
        from tpu_operator.controllers.slice_status import host_allocatable_ok

        if _labels(node).get(consts.REMEDIATION_STATE_LABEL):
            return True
        return host_allocatable_ok(node) is False

    def _forget_node(self, name: str) -> None:
        """Event-speed ledger prune for a vanished node: drop its
        remediation log-once state and re-aggregate every slice that
        counted it as a member (the delete storm satellite — stale
        verdicts must not wait out the resync)."""
        self.rec.remediation.forget_node(name)
        sm = self._shard_state()
        if sm is not None:
            sm.forget_node(name)
        with self._lock:
            sids = [
                sid
                for sid, info in self._slices.items()
                if name in info.member_nodes
            ]
        enqueue = self.enqueue_slice
        for sid in sids:
            if enqueue is not None:
                enqueue(sid)
            else:
                self.reconcile_slice(sid)

    # ------------------------------------------------------------------
    # per-slice sub-reconcile
    # ------------------------------------------------------------------
    def reconcile_slice(self, sid: str):
        """The minimal unit for a readiness-relevant event: recompute ONE
        slice's aggregate from live member reads, publish its verdict
        labels through the batched label lane, and fold the result into
        ``status.slices`` — O(slice members), never O(fleet)."""
        if not self._owns(SLICE_KIND, sid):
            return None
        if not self._context_ready():
            self._escalate(f"slice/{sid}: no full-pass context yet")
            return None
        t0 = time.perf_counter()
        with trace.span("delta.reconcile", kind=SLICE_KIND, key=sid):
            try:
                self._reconcile_slice(sid)
            finally:
                self._account(SLICE_KIND, sid, t0)
        return None

    def _reconcile_slice(self, sid: str) -> None:
        from tpu_operator.controllers import slice_status
        from tpu_operator.controllers.state_manager import has_tpu_labels

        ctrl = self.rec.ctrl
        members = self._slice_members_live(sid)
        tpu_members = [n for n in members if has_tpu_labels(n)]
        if not tpu_members:
            with self._lock:
                removed = self._slices.pop(sid, None) is not None
            if removed:
                self._publish_status()
            return
        validated = slice_status.validated_on_nodes(
            self.client,
            ctrl.namespace,
            [n["metadata"]["name"] for n in tpu_members],
        )
        summary = slice_status.aggregate(
            self.client,
            ctrl.namespace,
            tpu_members,
            validated=validated,
            lane=ctrl.label_lane,
        )
        # members were filtered to slice_id_for_node(n) == sid, and
        # group_slices re-derives keys with the same function over the
        # same views — the summary holds exactly this sid. (A member
        # whose identity CHANGED is the router's old_sid != sid path.)
        info = summary.slices.get(sid)
        with self._lock:
            if info is not None:
                self._slices[sid] = info
            else:
                self._slices.pop(sid, None)
        self._publish_status()

    def _slice_members_live(self, sid: str) -> List[dict]:
        """Fresh member node views for one slice, resolved through the
        informer indexes in O(members): the explicit TFD slice-id label,
        the GKE node-pool fallback (all hosts of one multi-host slice
        share a pool), and the node's own name for single-host slices.
        The sid computation is authoritative — candidates that compute a
        different sid are dropped."""
        from tpu_operator.controllers.slice_status import slice_id_for_node

        members: Dict[str, dict] = {}
        for selector in (
            {consts.TFD_SLICE_ID_LABEL: sid},
            {consts.GKE_NODEPOOL_LABEL: sid},
        ):
            try:
                candidates = self.client.list(
                    "v1", "Node", label_selector=selector
                )
            except Exception:
                candidates = []
            for n in candidates:
                members.setdefault(n["metadata"]["name"], n)
        if sid not in members:
            single = self.client.get_or_none("v1", "Node", sid)
            if single is not None:
                members[sid] = single
        return [
            n for n in members.values() if slice_id_for_node(n) == sid
        ]

    # ------------------------------------------------------------------
    # status.slices delta writer
    # ------------------------------------------------------------------
    def _publish_status(self) -> None:
        """Fold the slice mirror into ``status.slices`` (and the slice
        gauges) — only this block: the CR ``state``/conditions/errored
        picture belongs to the full pass and is left untouched."""
        from tpu_operator.controllers.clusterpolicy_controller import (
            select_primary,
        )
        from tpu_operator.kube.client import ConflictError

        sm = self._shard_state()
        if sm is not None and not sm.owns_full_pass():
            # CR status belongs to the shard-0 owner (one writer for the
            # global aggregate); this replica's verdict labels are its
            # contribution — the owner ingests them from the watch
            return
        with self._lock:
            infos = list(self._slices.values())
            foreign = dict(self._foreign)
            block = {
                "total": len(infos),
                "ready": sum(
                    1 for s in infos if foreign.get(s.slice_id, s.ready)
                ),
            }
            degraded = sorted(
                s.slice_id
                for s in infos
                if not foreign.get(s.slice_id, s.ready)
            )
            if degraded:
                block["degraded"] = degraded
        metrics = self.rec.metrics
        if metrics and getattr(metrics, "slices_total", None):
            metrics.slices_total.set(block["total"])
            metrics.slices_ready.set(block["ready"])
        # one writer at a time: N slice workers racing the CR's status
        # revision would only trade 409s for no information
        with self._status_lock:
            try:
                policies = self.client.list(
                    consts.API_VERSION,
                    consts.CLUSTER_POLICY_KIND,
                    copy=True,
                )
                if not policies:
                    return
                primary, _ = select_primary(policies)
                wrote = False
                for attempt in range(3):
                    status = primary.setdefault("status", {})
                    if status.get("slices") == block:
                        wrote = attempt > 0
                        break
                    status["slices"] = block
                    try:
                        self.client.update_status(primary)
                        wrote = True
                        break
                    except ConflictError:
                        # the full pass's status writer (or a spec
                        # edit) moved the CR: re-read LIVE and re-apply
                        # only our block to the fresh revision
                        primary = getattr(
                            self.client, "get_live", self.client.get
                        )(
                            primary["apiVersion"],
                            primary["kind"],
                            primary["metadata"]["name"],
                            primary["metadata"].get("namespace", ""),
                        )
                else:
                    log.warning(
                        "delta status update lost its conflict race; "
                        "the resync pass converges it"
                    )
                if wrote:
                    with self._lock:
                        self.status_writes += 1
            except Exception:
                log.exception(
                    "delta status update failed; the resync pass "
                    "converges it"
                )

    # ------------------------------------------------------------------
    # bookkeeping
    # ------------------------------------------------------------------
    def _account(self, kind: str, key: str, t0: float) -> None:
        ms = (time.perf_counter() - t0) * 1000.0
        with self._lock:
            if kind == NODE_KIND:
                self.node_passes += 1
            else:
                self.slice_passes += 1
            self.delta_ms_total += ms
            self.last = {"kind": kind, "key": key, "ms": round(ms, 3)}
        metrics = self.rec.metrics
        hist = getattr(metrics, "delta_reconcile_ms_hist", None)
        if hist is not None:
            hist.observe(ms)

    def stats(self) -> Dict[str, object]:
        """/debug/vars "delta_reconcile" payload: delta-vs-full pass
        counts and cumulative self-time, plus the router's trigger and
        drop disposition when wired."""
        with self._lock:
            out: Dict[str, object] = {
                "enabled": delta_enabled(),
                "have_full_context": self._have_full,
                "node_passes": self.node_passes,
                "slice_passes": self.slice_passes,
                "delta_passes": self.node_passes + self.slice_passes,
                "delta_ms_total": round(self.delta_ms_total, 3),
                "escalations": self.escalations,
                "status_writes": self.status_writes,
                "shard_skips": self.shard_skips,
                "slices_tracked": len(self._slices),
                "last": dict(self.last),
            }
        out["full_passes"] = self.rec.passes_total
        out["full_ms_total"] = round(
            getattr(self.rec, "full_ms_total", 0.0), 3
        )
        router = getattr(self, "router", None)
        if router is not None:
            out["router"] = router.stats()
        return out


class EventRouter:
    """Watch-event → minimal-queue-key routing with no-op predicates.

    Replaces the ``wire_event_sources`` closure: the legacy behavior
    (every relevant event wakes a full pass) is the ``enabled=False``
    branch and stays byte-compatible — the chaos soak's router-off
    variant and ``TPU_DELTA_RECONCILE=0`` both ride it."""

    def __init__(self, mgr, delta: Optional[DeltaReconciler], cp_key, upgrade_key):
        self.mgr = mgr
        self.delta = delta
        self.cp_key = cp_key
        self.upgrade_key = upgrade_key
        self.enabled = delta_enabled() and delta is not None
        # sharded scale-out (tpu_operator/shard.py): when the manager
        # carries a shard-ownership view, events for keys outside the
        # replica's owned shards are dropped BEFORE they enqueue — the
        # other replica that owns them sees the same watch stream
        self.shard = getattr(mgr, "shard_state", None)
        if delta is not None:
            delta.router = self
        self._lock = threading.Lock()
        # object caches for old/new diffs (the hook only carries new)
        self._node_cache: Dict[str, dict] = {}
        self._cp_cache: Dict[str, dict] = {}
        # pods currently in CrashLoopBackOff (namespace/name)
        self._crashlooping = set()
        # validator pods currently counting as Running+ready
        self._validator_ready = set()
        # nodes with an in-flight upgrade FSM label
        self._upgrading = set()
        self._upgrade_wake_states = (
            consts.UPGRADE_STATE_UPGRADE_REQUIRED,
        ) + tuple(consts.UPGRADE_ACTIVE_STATES)
        # (source, key_kind) -> count; mirrored into
        # reconcile_trigger_total{source,key_kind}
        self._triggers: Dict[tuple, int] = {}
        self.dropped_total = 0

    # ------------------------------------------------------------------
    def _count(self, source: str, key_kind: str) -> None:
        with self._lock:
            self._triggers[(source, key_kind)] = (
                self._triggers.get((source, key_kind), 0) + 1
            )
            if key_kind == "drop":
                self.dropped_total += 1
        metrics = (
            self.delta.rec.metrics if self.delta is not None else None
        )
        counter = getattr(metrics, "reconcile_triggers", None)
        if counter is not None:
            counter.labels(source=source, key_kind=key_kind).inc()

    def _fire(self, source: str, key, delay: float = 0.0) -> None:
        if key == self.cp_key:
            kind = "full"
        elif key == self.upgrade_key:
            kind = "upgrade"
        else:
            kind = key[0]
        if not self._shard_allows(key):
            # outside this replica's owned shards: the owning replica's
            # router enqueues it from the same watch stream
            self._count(source, "shard_drop")
            self.shard.note_event_dropped()
            return
        self._count(source, kind)
        self.mgr.enqueue(key, delay)

    def _shard_allows(self, key) -> bool:
        """Shard routing discipline (single choke point):

        * full-pass key — every replica (the non-owner dispatch runs
          the SCOPED shard pass: its own shards' label/verdict work);
        * upgrade key — shard-0 owner only (the FSM admits against the
          global disruption budget);
        * ``(node, name)`` / ``(slice, sid)`` — the owning replica only.
        """
        sm = self.shard
        if sm is None:
            return True
        if key == self.cp_key:
            return True
        if key == self.upgrade_key:
            return sm.owns_full_pass()
        if isinstance(key, tuple) and len(key) == 2:
            kind, name = key
            if kind == NODE_KIND:
                allowed = sm.owns_node_name(name)
            elif kind == SLICE_KIND:
                allowed = sm.owns_slice(name)
            else:
                return True
            if allowed:
                sm.note_event_routed(
                    sm.shard_of_node_name(name)
                    if kind == NODE_KIND
                    else sm.shard_of_slice(name)
                )
            return allowed
        return True

    def stats(self) -> Dict[str, object]:
        with self._lock:
            triggers = {
                f"{source}:{kind}": n
                for (source, kind), n in sorted(self._triggers.items())
            }
            return {
                "enabled": self.enabled,
                "triggers": triggers,
                "dropped_total": self.dropped_total,
            }

    # ------------------------------------------------------------------
    # the hook
    # ------------------------------------------------------------------
    def on_event(self, event: str, obj: dict) -> None:
        kind = obj.get("kind")
        if kind == "ClusterPolicy":
            self._on_clusterpolicy(event, obj)
        elif kind == "Node":
            self._on_node(event, obj)
        elif kind == "Pod":
            self._on_pod(event, obj)
        elif kind == "DaemonSet":
            # owned-operand drift (reference watch on owned DaemonSets):
            # DS status feeds per-state readiness, which only the full
            # pass aggregates; the 0.1 s delay coalesces update storms
            self._fire("daemonset", self.cp_key, 0.1)

    # -- ClusterPolicy --------------------------------------------------
    def _on_clusterpolicy(self, event: str, obj: dict) -> None:
        name = obj.get("metadata", {}).get("name", "")
        with self._lock:
            old = self._cp_cache.get(name)
            if event == "DELETED":
                self._cp_cache.pop(name, None)
            else:
                self._cp_cache[name] = obj
        if self.enabled and not self._cp_significant(event, old, obj):
            # status-only echo — our own status writer (full or delta
            # pass) bouncing back through the watch; nothing to converge
            self._count("clusterpolicy", "drop")
            return
        self._fire("clusterpolicy", self.cp_key)
        self._fire("clusterpolicy", self.upgrade_key)

    @staticmethod
    def _cp_significant(event: str, old: Optional[dict], new: dict) -> bool:
        """True when the CR change can alter desired state: spec,
        labels, annotations (the rollout ledger lives there), deletion.
        A status-only write — rv moved, everything else equal — is our
        own echo."""
        if event != "MODIFIED" or old is None:
            return True
        if old.get("spec") != new.get("spec"):
            return True
        om, nm = old.get("metadata", {}), new.get("metadata", {})
        return (
            (om.get("labels") or {}) != (nm.get("labels") or {})
            or (om.get("annotations") or {}) != (nm.get("annotations") or {})
            or om.get("generation") != nm.get("generation")
        )

    # -- Node -----------------------------------------------------------
    def _on_node(self, event: str, obj: dict) -> None:
        from tpu_operator.controllers.clusterpolicy_controller import (
            node_event_needs_reconcile,
        )

        name = obj["metadata"]["name"]
        with self._lock:
            old = self._node_cache.get(name)
            if event == "DELETED":
                # drop the entry entirely: a tombstone-per-name under
                # join/preemption storms of unique node names grew this
                # cache without bound
                self._node_cache.pop(name, None)
                self._upgrading.discard(name)
            else:
                self._node_cache[name] = obj
        if event == "DELETED":
            # a node vanishing mid-upgrade must wake the upgrade
            # reconciler: its slice's budget hold releases on the next
            # build_state, and waiting out the 120 s requeue starves
            # pending sibling slices meanwhile
            self._fire("node", self.upgrade_key)
            if self.enabled:
                # delete storm satellite: ledgers prune and the slice
                # regroups at event speed, not at the resync
                self._fire("node", (NODE_KIND, name))
                sid = self._sid_of(old or obj)
                if sid:
                    self._fire("node", (SLICE_KIND, sid))
            elif node_event_needs_reconcile(event, old, obj):
                self._fire("node", self.cp_key)
            if self.shard is not None and (
                not self.enabled or not self.shard.owns_node_name(name)
            ):
                # prune the name→shard mapping wherever no delta
                # (node, name) prune will ever dispatch for it: on
                # non-owners the router just dropped the key, and with
                # the delta router disabled (TPU_DELTA_RECONCILE=0) the
                # keyed path is off EVERYWHERE — without this,
                # unique-name churn leaks one map entry per deleted
                # node. The delta-enabled owner keeps its entry until
                # its delta prune runs, so the dispatch-time ownership
                # re-check stays exact.
                self.shard.forget_node(name)
            return
        self._track_upgrade_state(name, old, obj)
        if self.shard is not None:
            # keep the name→shard map current (the slice identity needs
            # the node's labels, which only this hook sees)
            self.shard.shard_of_node_obj(obj)
        if not node_event_needs_reconcile(event, old, obj):
            self._count("node", "drop")
            return
        if (
            self.shard is not None
            and self.shard.owns_full_pass()
            and old is not None
        ):
            # another replica's verdict write on a shard we don't own:
            # fold it into status.slices at O(1) instead of letting the
            # shard filter silently stale the global aggregate
            self._maybe_ingest_foreign_verdict(old, obj)
        if not self.enabled:
            self._fire("node", self.cp_key)
            return
        if old is None or self._changes_cluster_facts(old, obj):
            # a joining TPU node / generation flip changes the facts the
            # render fan-out and slice formation derive from — full pass
            self._fire("node", self.cp_key)
            return
        if self._is_own_verdict_echo(old, obj):
            # our slice-ready write bouncing back through the watch: the
            # mirror already holds this verdict, nothing to recompute
            self._count("node", "drop")
            return
        with self._lock:
            rolling = bool(self._upgrading)
        if rolling:
            # a staged roll in flight: version-label flips, FSM
            # transitions and health edges are the rollout
            # orchestrator's gate EVIDENCE, and promotion/rollback
            # decisions live in the full pass — it must observe at
            # event speed (the PR 11 canary contract), not at the 5 s
            # requeue. The empty-set common case keeps steady churn off
            # the full pass entirely.
            self._fire("node", self.cp_key, 0.1)
        if _labels(old) != _labels(obj):
            # only a label change can move the node's own label-FSM
            # step; a status-only event (chip health) skips straight to
            # the slice aggregate below
            self._fire("node", (NODE_KIND, name))
        if self._readiness_relevant(old, obj):
            sid = self._sid_of(obj)
            if sid:
                self._fire("node", (SLICE_KIND, sid))
            old_sid = self._sid_of(old)
            if old_sid and old_sid != sid:
                self._fire("node", (SLICE_KIND, old_sid))
        if self.delta is not None and self.delta.remediation_enabled():
            if self._health_transition(old, obj):
                # budgeted FSM territory: run the full pass now instead
                # of waiting out the resync
                self._fire("node", self.cp_key, 0.05)

    def _track_upgrade_state(
        self, name: str, old: Optional[dict], new: dict
    ) -> None:
        ustate = _labels(new).get(consts.UPGRADE_STATE_LABEL) or ""
        old_ustate = _labels(old).get(consts.UPGRADE_STATE_LABEL) or ""
        with self._lock:
            (
                self._upgrading.add
                if ustate in self._upgrade_wake_states
                else self._upgrading.discard
            )(name)
        if ustate != old_ustate:
            # an FSM transition landed (ours or another replica's): the
            # next step is level-triggered off the labels — run it now,
            # not at the 120 s resync
            self._fire("node", self.upgrade_key, 0.1)

    @staticmethod
    def _changes_cluster_facts(old: dict, new: dict) -> bool:
        from tpu_operator.controllers.state_manager import (
            has_tpu_labels,
            node_generation,
        )

        if has_tpu_labels(old) != has_tpu_labels(new):
            return True
        if node_generation(old) != node_generation(new):
            return True
        return _labels(old).get(consts.WORKLOAD_CONFIG_LABEL) != _labels(
            new
        ).get(consts.WORKLOAD_CONFIG_LABEL)

    @staticmethod
    def _readiness_relevant(old: dict, new: dict) -> bool:
        from tpu_operator.controllers.clusterpolicy_controller import (
            _tpu_resource_view,
        )

        if _tpu_resource_view(old) != _tpu_resource_view(new):
            return True
        ol, nl = _labels(old), _labels(new)
        if any(ol.get(k) != nl.get(k) for k in _READINESS_LABELS):
            return True
        return ol.get(consts.TFD_SLICE_ID_LABEL) != nl.get(
            consts.TFD_SLICE_ID_LABEL
        )

    def _is_own_verdict_echo(self, old: dict, new: dict) -> bool:
        """True when the ONLY change is the slice-ready label landing at
        exactly the verdict the delta mirror computed — the watch echo
        of our own publish. A foreign writer flipping the verdict to
        anything ELSE fails the predicate and reaches the slice key,
        which reclaims the label."""
        if self.delta is None:
            return False
        from tpu_operator.controllers.clusterpolicy_controller import (
            _tpu_resource_view,
        )

        if _tpu_resource_view(old) != _tpu_resource_view(new):
            return False
        ol, nl = dict(_labels(old)), dict(_labels(new))
        verdict = nl.get(consts.SLICE_READY_LABEL)
        ol.pop(consts.SLICE_READY_LABEL, None)
        nl.pop(consts.SLICE_READY_LABEL, None)
        if ol != nl or verdict is None:
            return False
        sid = self._sid_of(new)
        return sid is not None and (
            self.delta.expected_verdict(sid) == verdict
        )

    def _maybe_ingest_foreign_verdict(self, old: dict, new: dict) -> None:
        if self.delta is None:
            return
        sid = self._sid_of(new)
        if sid is None or self.shard.owns_slice(sid):
            return
        verdict = _labels(new).get(consts.SLICE_READY_LABEL)
        if verdict is None:
            return
        if _labels(old).get(consts.SLICE_READY_LABEL) == verdict:
            return
        self.delta.ingest_foreign_verdict(sid, verdict == "true")

    def _health_transition(self, old: dict, new: dict) -> bool:
        from tpu_operator.controllers.slice_status import host_allocatable_ok

        if _labels(old).get(consts.REMEDIATION_STATE_LABEL) != _labels(
            new
        ).get(consts.REMEDIATION_STATE_LABEL):
            return True
        return host_allocatable_ok(new) is False and (
            host_allocatable_ok(old) is not False
        )

    def _sid_of(self, node: Optional[dict]) -> Optional[str]:
        if not node:
            return None
        from tpu_operator.controllers.slice_status import slice_id_for_node

        try:
            return slice_id_for_node(node)
        except Exception:
            return None

    # -- Pod ------------------------------------------------------------
    def _on_pod(self, event: str, obj: dict) -> None:
        from tpu_operator.controllers.remediation import pod_crashlooping
        from tpu_operator.controllers.slice_status import VALIDATOR_APP

        meta = obj.get("metadata", {})
        # same tpu-* operand filter the remediator's health verdict
        # applies: a user pod's crashloop is not a node-health signal
        # and must not burn reconcile passes
        app = (meta.get("labels") or {}).get("app") or ""
        if not app.startswith("tpu-"):
            return
        with self._lock:
            upgrading = bool(self._upgrading)
        if upgrading:
            # operand/validator pod movement advances FSM steps
            # (pod-restart completion, validation) — coalesced by the
            # workqueue, and only while an upgrade is in flight
            self._fire("pod", self.upgrade_key, 0.25)
        key = f"{meta.get('namespace', '')}/{meta.get('name', '')}"
        now = event != "DELETED" and pod_crashlooping(obj)
        with self._lock:
            # read-and-update under ONE lock hold: hooks dispatch from
            # both the watch thread and the resync repair thread, and a
            # stale 'was' read would silently drop a flip's wake
            was = key in self._crashlooping
            (self._crashlooping.add if now else self._crashlooping.discard)(
                key
            )
        crash_flip = was != now
        if not self.enabled:
            if crash_flip:
                self._fire("pod", self.cp_key, 0.1)
            return
        remediation_on = (
            self.delta is not None and self.delta.remediation_enabled()
        )
        if crash_flip and (remediation_on or upgrading):
            # crashloop health is remediation-FSM input (fleet budget)
            # AND rollout gate evidence while a staged roll is in
            # flight: full pass, as before the router existed
            self._fire("pod", self.cp_key, 0.1)
        slice_hit = False
        if app == VALIDATOR_APP:
            from tpu_operator.controllers.slice_status import (
                validator_pod_ready,
            )

            ready = event != "DELETED" and validator_pod_ready(obj)
            with self._lock:
                was_ready = key in self._validator_ready
                (
                    self._validator_ready.add
                    if ready
                    else self._validator_ready.discard
                )(key)
            if ready != was_ready:
                # pod event → its slice's readiness aggregate: the
                # validator verdict is the slice gate
                slice_hit = self._fire_slice_for_pod(obj)
        elif crash_flip and not remediation_on:
            slice_hit = self._fire_slice_for_pod(obj)
        if not (upgrading or crash_flip or slice_hit):
            self._count("pod", "drop")

    def _fire_slice_for_pod(self, pod: dict) -> bool:
        node_name = pod.get("spec", {}).get("nodeName")
        if not node_name:
            return False
        with self._lock:
            node = self._node_cache.get(node_name)
        if node is None:
            node = self._node_obj_fallback(node_name)
        sid = self._sid_of(node)
        if sid:
            self._fire("pod", (SLICE_KIND, sid), 0.05)
            return True
        # node unknown to the router (cache not warm yet): the full
        # pass regroups safely
        self._fire("pod", self.cp_key, 0.1)
        return True

    def _node_obj_fallback(self, name: str) -> Optional[dict]:
        try:
            client = (
                self.delta.client if self.delta is not None else None
            )
            if client is None:
                return None
            return client.get_or_none("v1", "Node", name)
        except Exception:
            return None
