"""Slice-scoped readiness aggregation.

The reference's readiness is strictly per-node (DaemonSet unavailable==0,
``controllers/object_controls.go:3107-3177``). A multi-host TPU pod-slice is
only usable when **every** host in the slice is validated — a v5p-64 with 15
of 16 hosts ready is 0% useful, not 94%. This is the "readiness semantics on
multi-host slices" hard part called out in SURVEY.md §7: an aggregate the
reference does not have.

Mechanics, staying on the node-label bus:

* nodes are grouped into slices by the ``tpu.k8s.io/tpu.slice-id`` label
  (published by TPU feature discovery; falls back to the GKE node-pool label
  for multi-host node pools, else every node is its own single-host slice);
* the expected host count comes from ``tpu.k8s.io/tpu.slice-hosts`` (TFD
  computes it from the ICI topology string) — a slice with members missing
  from the cluster is *not* ready even if every present member is;
* a member host counts as validated when the operator-validator DaemonSet
  pod on it is Running (the validator's main container only runs after the
  libtpu → runtime → plugin → jax initContainer chain passed, exactly the
  reference's "validator Running == node good" semantics,
  ``assets/state-operator-validation/0500_daemonset.yaml:28-157``);
* the verdict is published back onto each member node as
  ``tpu.k8s.io/tpu.slice.ready=true|false`` so schedulers / workload
  controllers can gate multi-host jobs on it, and summarized into the
  ClusterPolicy status and operator metrics.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from functools import lru_cache
from typing import Dict, Iterable, List, Optional, Set

from tpu_operator import consts
from tpu_operator.kube.apply import ApplyConflictError
from tpu_operator.kube.client import Client, NotFoundError, Obj

log = logging.getLogger("tpu-operator.slices")

VALIDATOR_APP = "tpu-operator-validator"


@dataclass
class SliceInfo:
    slice_id: str
    member_nodes: List[str] = field(default_factory=list)
    expected_hosts: int = 0  # 0 = unknown; fall back to member count
    ready_nodes: int = 0
    # members advertising the TPU resource with ZERO allocatable chips —
    # the per-host reason a slice is down, named in the degradation Event
    unhealthy_hosts: List[str] = field(default_factory=list)
    # members inside an announced host-maintenance window: the host is
    # ABOUT to lose its chips, so the slice verdict flips ahead of the
    # outage (multi-host jobs drain once, proactively — not when the
    # kubelet finally reports dead chips)
    maintenance_hosts: List[str] = field(default_factory=list)
    # members the node-health remediation FSM holds cordoned + tainted
    # (cordon-drain / quarantined / exhausted): named as the per-slice
    # degradation reason — an exhausted flapping host can look healthy
    # moment-to-moment yet must keep its slice out of service
    quarantined_hosts: List[str] = field(default_factory=list)
    # members mid live slice re-partition (controllers/repartition.py):
    # the roll pauses the host's chip clients on purpose, so the slice
    # verdict flips ahead of the outage — same proactive rule as
    # maintenance windows (a gang job must not land on a slice whose
    # layout is changing under it)
    repartitioning_hosts: List[str] = field(default_factory=list)

    @property
    def ready(self) -> bool:
        want = self.expected_hosts or len(self.member_nodes)
        return want > 0 and self.ready_nodes >= want and (
            len(self.member_nodes) >= want
        )


@dataclass
class SliceSummary:
    slices: Dict[str, SliceInfo] = field(default_factory=dict)

    @property
    def total(self) -> int:
        return len(self.slices)

    @property
    def ready(self) -> int:
        return sum(1 for s in self.slices.values() if s.ready)

    @property
    def degraded(self) -> List[str]:
        return sorted(k for k, s in self.slices.items() if not s.ready)


def slice_id_for_node(node: Obj) -> str:
    """Slice identity for a TPU node.

    Priority: explicit TFD slice-id label; GKE node-pool label when the node
    is part of a multi-host slice (all hosts of one GKE multi-host TPU slice
    live in one node pool); else the node is a single-host slice of its own.
    """
    labels = node.get("metadata", {}).get("labels", {}) or {}
    explicit = labels.get(consts.TFD_SLICE_ID_LABEL)
    if explicit:
        return explicit
    hosts = _expected_hosts(node)
    if hosts > 1:
        pool = labels.get(consts.GKE_NODEPOOL_LABEL)
        if pool:
            return pool
    return node["metadata"]["name"]


def _expected_hosts(node: Obj) -> int:
    labels = node.get("metadata", {}).get("labels", {}) or {}
    return _hosts_from_labels(
        labels.get(consts.TFD_SLICE_HOSTS_LABEL, ""),
        labels.get(consts.GKE_TPU_TOPOLOGY_LABEL, ""),
        labels.get(consts.GKE_TPU_ACCELERATOR_LABEL, ""),
        labels.get(consts.TFD_CHIP_TYPE_LABEL, ""),
    )


@lru_cache(maxsize=256)
def _hosts_from_labels(raw: str, topology: str, acc: str, gen: str) -> int:
    """Expected host count from the slice labels. Memoized: a 1000-node
    fleet carries a handful of distinct (hosts, topology, accelerator)
    label shapes, and this runs twice per TPU node per reconcile pass
    (slice identity + slice sizing) — the topology parse was a
    measurable slice of the steady-state pass."""
    try:
        return int(raw)
    except (TypeError, ValueError):
        pass
    # derive from the GKE topology label when TFD hasn't run yet
    if topology:
        try:
            from tpu_operator.workloads import topology as topo

            gen = gen or consts.GKE_ACCELERATOR_TO_GENERATION.get(acc, "")
            if gen:
                return topo.host_count(topology, gen)
        except Exception:
            return 0
    return 0


def validator_pod_ready(pod: Obj) -> bool:
    """THE validator-pod readiness predicate — phase Running with every
    container ready (initContainer chain passed — reference semantics:
    validator Running == node validated). One implementation shared by
    the fleet scan, the per-node delta scan and the event router's
    transition detection, so the three sites cannot drift on what
    counts as validated."""
    status = pod.get("status", {}) or {}
    if status.get("phase") != "Running":
        return False
    statuses = status.get("containerStatuses")
    return statuses is None or all(
        cs.get("ready", True) for cs in statuses
    )


def validator_ready_nodes(
    client: Client, namespace: str, app: str = VALIDATOR_APP
) -> Set[str]:
    """Nodes whose operator-validator pod passes ``validator_pod_ready``."""
    ready: Set[str] = set()
    # selector pushed into the list: the informer's app-label index
    # answers this in O(validator pods) instead of scanning (and then
    # discarding most of) every namespace pod
    for pod in client.list("v1", "Pod", namespace, label_selector={"app": app}):
        if not validator_pod_ready(pod):
            continue
        node = pod.get("spec", {}).get("nodeName")
        if node:
            ready.add(node)
    return ready


def validated_on_nodes(
    client: Client,
    namespace: str,
    node_names: Iterable[str],
    app: str = VALIDATOR_APP,
) -> Set[str]:
    """Per-node variant of ``validator_ready_nodes`` for the delta path
    (controllers/delta.py): one indexed ``(app, spec.nodeName)`` pod
    list per member, so a single slice's readiness costs O(members ×
    pods-per-member) — never O(fleet validator pods)."""
    ready: Set[str] = set()
    for name in node_names:
        for pod in client.list(
            "v1",
            "Pod",
            namespace,
            label_selector={"app": app},
            field_selector={"spec.nodeName": name},
        ):
            if validator_pod_ready(pod):
                ready.add(name)
                break
    return ready


def host_allocatable_ok(node: Obj) -> Optional[bool]:
    """Kubelet-derived chip health for a member host — the reference's
    capacity check (``validator/main.go:1083-1161``) at slice
    granularity. ``None`` = no TPU resource advertised yet (bring-up:
    the validator verdict stands alone); ``True`` = ANY TPU-prefixed
    resource (plain chips or subslices) has nonzero allocatable;
    ``False`` = everything advertised reads zero — a host that cannot
    serve work even though its validator pod passed at startup.

    Subslice resources count deliberately: a mixed-strategy partition
    stops the plain-resource plugin (the kubelet then zeroes its
    allocatable while retaining capacity), and the chips live on under
    ``google.com/tpu-<shape>`` — that host is healthy, not degraded."""
    status = node.get("status", {}) or {}
    cap = status.get("capacity", {}) or {}
    alloc = status.get("allocatable", {}) or {}
    tpu_resources = [
        k
        for k in cap
        if k == consts.TPU_RESOURCE
        or k.startswith(consts.TPU_SUBSLICE_RESOURCE_PREFIX)
    ]
    if not tpu_resources:
        return None
    for k in tpu_resources:
        try:
            if int(alloc.get(k, "0")) > 0:
                return True
        except (TypeError, ValueError):
            continue
    return False


def slice_members(client: Client, node: Obj):
    """``(slice_id, member node objects)`` for the slice this node
    belongs to — the ONE membership computation shared by every consumer
    (maintenance flip, gang validator), so they cannot disagree about
    who the members are."""
    sid = slice_id_for_node(node)
    members = [
        n for n in client.list("v1", "Node") if slice_id_for_node(n) == sid
    ]
    return sid, members


def group_slices(tpu_nodes: List[Obj]) -> Dict[str, SliceInfo]:
    slices: Dict[str, SliceInfo] = {}
    for node in tpu_nodes:
        sid = slice_id_for_node(node)
        info = slices.setdefault(sid, SliceInfo(slice_id=sid))
        info.member_nodes.append(node["metadata"]["name"])
        info.expected_hosts = max(info.expected_hosts, _expected_hosts(node))
    return slices


def aggregate(
    client: Client,
    namespace: str,
    tpu_nodes: List[Obj],
    validated: Optional[Set[str]] = None,
    pipeline=None,
    lane=None,
    owns=None,
) -> SliceSummary:
    """Compute per-slice readiness and publish it to member node labels.

    ``validated`` overrides the validator-pod scan (used by tests and by
    callers that already listed pods this pass).

    ``owns`` (sharded scale-out, ``tpu_operator/shard.py``): an optional
    ``owns(slice_id) -> bool`` write gate — slices another replica owns
    are still COMPUTED (the full-pass owner's status aggregate needs
    them) but their verdict labels and degradation events are that
    replica's to publish. ``None`` (the default single-process
    operator) publishes everything.

    ``lane`` (a ``kube.write_pipeline.BatchLane`` over the label-apply
    flush — the reconciler's label lane) group-commits the per-node
    verdict writes into multi-object APPLY submissions: a 1000-node
    fleet flip becomes ~N/batch wire requests instead of N. Without a
    lane, ``pipeline`` (a ``kube.write_pipeline.WritePipeline``) fans
    individual merge patches out concurrently, keyed per node — and
    with neither, writes go inline (unit tests driving this directly).
    """
    if validated is None:
        validated = validator_ready_nodes(client, namespace)
    label_futs = []
    slices = group_slices(tpu_nodes)
    cached = {n["metadata"]["name"]: n for n in tpu_nodes}
    for info in slices.values():
        info.unhealthy_hosts = sorted(
            n
            for n in info.member_nodes
            if host_allocatable_ok(cached[n]) is False
        )
        info.maintenance_hosts = sorted(
            n
            for n in info.member_nodes
            if (
                cached[n].get("metadata", {}).get("labels", {}) or {}
            ).get(consts.MAINTENANCE_STATE_LABEL)
        )
        info.quarantined_hosts = sorted(
            n
            for n in info.member_nodes
            if (
                cached[n].get("metadata", {}).get("labels", {}) or {}
            ).get(consts.REMEDIATION_STATE_LABEL)
            in consts.REMEDIATION_DISRUPTED_STATES
        )
        info.repartitioning_hosts = sorted(
            n
            for n in info.member_nodes
            if (
                cached[n].get("metadata", {}).get("labels", {}) or {}
            ).get(consts.REPARTITION_STATE_LABEL)
            == consts.REPARTITION_STATE_ROLLING
        )
        # a member counts only when validated AND not advertising zero
        # allocatable chips (kubelet-derived health can sour a host long
        # after its validator initContainer chain passed) AND not inside
        # a maintenance window (the chips are about to vanish) AND not
        # held by the remediation FSM (quarantined/exhausted) AND not
        # mid layout roll (its chip clients are paused on purpose)
        info.ready_nodes = sum(
            1
            for n in info.member_nodes
            if n in validated
            and n not in info.unhealthy_hosts
            and n not in info.maintenance_hosts
            and n not in info.quarantined_hosts
            and n not in info.repartitioning_hosts
        )
        verdict = "true" if info.ready else "false"
        if owns is not None and not owns(info.slice_id):
            # another replica's shard: computed for the aggregate,
            # published by its owner
            continue
        was_ready = any(
            (cached[n].get("metadata", {}).get("labels", {}) or {}).get(
                consts.SLICE_READY_LABEL
            )
            == "true"
            for n in info.member_nodes
        )
        if verdict == "false" and was_ready:
            _record_degradation(client, namespace, info)
        for node_name in info.member_nodes:
            # steady-state cheap path: when the cached node already carries
            # the right verdict, skip the API round-trip entirely
            cached_labels = (
                cached[node_name].get("metadata", {}).get("labels", {}) or {}
            )
            current = cached_labels.get(consts.SLICE_READY_LABEL)
            if current == verdict:
                continue
            if current is None and verdict == "false":
                # never-labeled node: absence already MEANS not-ready to
                # every consumer, and writing "false" onto a whole
                # converging fleet doubled the label write volume for
                # zero information — only a real true→false flip (or
                # readiness) is worth a write
                continue
            if lane is not None:
                label_futs.append(
                    (
                        node_name,
                        verdict,
                        lane.submit(
                            ("Node", "", node_name),
                            _verdict_payload(node_name, verdict),
                        ),
                    )
                )
            elif pipeline is not None:
                label_futs.append(
                    (
                        node_name,
                        verdict,
                        pipeline.submit(
                            ("Node", "", node_name),
                            _publish_verdict,
                            client,
                            node_name,
                            verdict,
                        ),
                    )
                )
            else:
                try:
                    _publish_verdict(client, node_name, verdict)
                except Exception:
                    log.exception(
                        "failed to label node %s slice.ready=%s",
                        node_name,
                        verdict,
                    )
    # drain barrier: the summary must not be returned while verdict
    # writes are still in flight (the status writer and the next pass's
    # memo both read the world these writes produce)
    for node_name, verdict, fut in label_futs:
        try:
            fut.result()
        except NotFoundError:
            # node deleted mid-pass (the lane applies update_only, so a
            # racing deletion 404s instead of resurrecting the node):
            # normal churn, next reconcile regroups without it
            pass
        except ApplyConflictError:
            # this aggregation is the verdict label's ONLY writer, so a
            # field conflict means a foreign actor touched the key —
            # take it back with one forced re-apply (ownership
            # transfers; the next pass is conflict-free again)
            _reclaim_verdict(client, node_name, verdict)
        except Exception:
            log.exception(
                "failed to label node %s slice.ready=%s", node_name, verdict
            )
    return SliceSummary(slices=slices)


def _verdict_payload(node_name: str, verdict: str) -> Obj:
    """One node's slice-ready verdict as an apply configuration for the
    batched label lane (delta dialect: only the verdict key is named,
    and the lane applies non-pruned so omission strips nothing)."""
    return {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": {
            "name": node_name,
            "labels": {consts.SLICE_READY_LABEL: verdict},
        },
    }


def _reclaim_verdict(client: Client, node_name: str, verdict: str) -> None:
    fn = getattr(client, "apply_ssa", None)
    if not callable(fn):
        return
    try:
        fn(
            _verdict_payload(node_name, verdict),
            force=True,
            prune=False,
            update_only=True,
        )
    except NotFoundError:
        pass
    except Exception:
        log.exception(
            "failed to reclaim node %s slice.ready=%s", node_name, verdict
        )


def _publish_verdict(client: Client, node_name: str, verdict: str) -> None:
    """Write one node's slice-ready verdict as a labels-only merge
    patch: the delta payload (one operator-OWNED key — this aggregation
    is its only writer, so an unconditional merge cannot revert anyone)
    replaces what used to be a full-node read-modify-write: a fleet
    Node carries kubelet status and an image list, and PUTting 1000 of
    them back was the single largest write volume on the convergence
    path.

    Only a vanished node is swallowed here; any other failure
    propagates so the pipeline's error aggregation (and the
    write_pipeline_errors gauge) actually sees it — the drain loop in
    ``aggregate`` logs and continues, preserving the best-effort
    contract."""
    try:
        client.patch_labels(
            "v1",
            "Node",
            node_name,
            labels={consts.SLICE_READY_LABEL: verdict},
        )
    except NotFoundError:
        # node deleted mid-pass: normal churn, next reconcile regroups
        # the slices without it
        pass


def _record_degradation(client: Client, namespace: str, info: SliceInfo) -> None:
    """Warning Event on the true→false flip naming WHICH hosts took the
    slice down — a v5p-64 losing one host is invisible in per-node
    readiness; this is where the operator says so out loud."""
    from tpu_operator import consts as c
    from tpu_operator.kube.events import (
        TYPE_WARNING,
        cluster_policy_ref,
        record_event,
    )

    if info.quarantined_hosts:
        detail = (
            f"host(s) {', '.join(info.quarantined_hosts)} are "
            f"quarantined for repair "
            f"({c.REPAIR_TAINT_KEY}={c.REPAIR_PENDING} taint)"
        )
    elif info.repartitioning_hosts:
        detail = (
            f"host(s) {', '.join(info.repartitioning_hosts)} are mid "
            f"slice re-partition (chip clients paused for a layout roll)"
        )
    elif info.maintenance_hosts:
        detail = (
            f"host(s) {', '.join(info.maintenance_hosts)} are inside a "
            f"scheduled host-maintenance window"
        )
    elif info.unhealthy_hosts:
        detail = (
            f"host(s) {', '.join(info.unhealthy_hosts)} advertise 0 "
            f"allocatable {c.TPU_RESOURCE}"
        )
    else:
        detail = (
            f"{info.ready_nodes} of "
            f"{info.expected_hosts or len(info.member_nodes)} member hosts "
            f"validated"
        )
    record_event(
        client,
        namespace,
        cluster_policy_ref(),
        TYPE_WARNING,
        "SliceDegraded",
        f"slice {info.slice_id} is no longer ready: {detail}",
        # one Event per slice: two slices flipping must not collapse
        # into one record that only names the later one's hosts
        dedup_extra=info.slice_id,
    )
