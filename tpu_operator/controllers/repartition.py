"""Live slice re-partition roll — the fleet half of the MIG-reconfigure
story, and the THIRD consumer of the shared disruption budget.

The per-node slice manager (``sliceman/slice_manager.py``) is a daemon:
it sees ``tpu.k8s.io/tpu.slice.config`` change on ITS node, pauses the
chip clients, applies the named layout, reports through
``…slice.config.state``. What nothing did before this controller is
change that label across a BUSY fleet safely: flipping a thousand nodes
at once would pause every device plugin in the cluster simultaneously —
a self-inflicted full outage the reference's mig-manager avoids only by
being operated by hand.

This controller rolls a changed fleet-wide layout (``spec.sliceManager
.config.default``) node-by-node at SLICE granularity through the same
``maxUnavailable`` pool rolling libtpu upgrades and node-health
remediation already share (``kube/disruption.py`` joint accounting):

* a slice is admitted into the roll as ONE unit — every member host gets
  ``tpu.k8s.io/repartition-state=rolling`` plus the new desired config
  label (state reset to ``pending``) in one write each;
* while any member rolls, the slice counts against the joint disrupted
  set, so upgrades and remediation admissions both see it (and vice
  versa: a slice mid-upgrade or quarantined is never admitted here);
* the hold releases when the node's slice manager reports the new
  layout applied (``state=success`` under the desired config) — the
  ``rolling`` label is cleared and the budget unit returns to the pool;
* all state lives on node labels, so the roll survives operator
  restarts, and a node deleted mid-roll (spot preemption) releases its
  hold the moment it leaves the node listing — nothing to retire.

Like remediation, the controller runs inside the reconcile pass over the
pass's in-hand node list; with no desired layout configured it costs a
label-dict scan and writes nothing (the 50 ms steady-pass gate holds).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from tpu_operator import consts
from tpu_operator.obs import LogOnce, flight
from tpu_operator.kube.client import (
    Client,
    ConflictError,
    NotFoundError,
    Obj,
    mutate_with_retry,
)

log = logging.getLogger("tpu-operator.repartition")


@dataclass
class RepartitionSummary:
    """What one roll pass saw and did — feeds /debug/vars and the
    reconciler's requeue decision."""

    total: int = 0  # TPU nodes considered
    desired: str = ""  # the fleet-wide layout profile (empty = no roll)
    pending_slices: int = 0  # slices still needing the new layout
    rolling_slices: int = 0  # slices currently holding a budget unit
    completed_nodes: int = 0  # holds released this pass
    admitted_slices: int = 0  # slices admitted this pass
    deferred_slices: int = 0  # admissions the budget refused this pass
    failed_nodes: List[str] = field(default_factory=list)
    budget_cap: int = 0
    disrupted_slices: int = 0  # joint set (upgrades+remediation+this)

    @property
    def active(self) -> bool:
        """In-flight or pending work wants the level-triggered requeue:
        budget headroom opens without any cluster event when another
        consumer's disruption completes."""
        return self.rolling_slices > 0 or self.pending_slices > 0


class SliceRepartitionController:
    """Level-triggered fleet roll, at most one admission wave per pass."""

    def __init__(self, client: Client, namespace: str = ""):
        self.client = client
        self.namespace = namespace
        self.rolls_started_total = 0
        self.rolls_completed_total = 0
        self.budget_deferred_total = 0
        self.last_summary: Dict[str, object] = {}
        self._logged = LogOnce()

    # ------------------------------------------------------------------
    def stats(self) -> Dict[str, object]:
        """/debug/vars "repartition" payload."""
        return {
            "last_pass": self.last_summary,
            "rolls_started_total": self.rolls_started_total,
            "rolls_completed_total": self.rolls_completed_total,
            "budget_deferred_total": self.budget_deferred_total,
        }

    # ------------------------------------------------------------------
    def reconcile(
        self,
        tpu_nodes: List[Obj],
        spec,
        namespace: str,
        extra_disrupted: Optional[Set[str]] = None,
        admit_filter: Optional[Set[str]] = None,
    ) -> RepartitionSummary:
        """One roll pass over the labeled TPU node list. ``spec`` is
        ``cp.spec.slice_manager``; with no ``config.default`` the pass
        only clears leftover ``rolling`` labels (an aborted roll must not
        hold budget forever). ``extra_disrupted`` is the same-pass
        remediation disrupted slice set: its label writes are on the wire
        but not yet in ``tpu_nodes``, and counting them here is what
        keeps the two same-pass consumers under the ONE shared cap.
        ``admit_filter`` (optional set of slice ids) restricts FRESH
        admissions to the named slices — the health-gated rollout
        orchestrator's cohort gate (``controllers/rollout.py``); slices
        already rolling always finish."""
        self.namespace = namespace
        desired = ""
        if spec is not None and spec.config is not None:
            desired = spec.config.default or ""
        summary = RepartitionSummary(total=len(tpu_nodes), desired=desired)
        if not desired:
            self._cleanup_abandoned(tpu_nodes)
            self.last_summary = {"desired": ""}
            return summary

        from tpu_operator.controllers.slice_status import group_slices
        from tpu_operator.kube.disruption import (
            OWNER_REPARTITION,
            joint_disrupted_slices,
        )
        from tpu_operator.sliceman.slice_manager import (
            STATE_FAILED,
            STATE_SUCCESS,
        )
        from tpu_operator.upgrade.upgrade_state import parse_max_unavailable

        slices = group_slices(tpu_nodes)
        slice_of = {
            member: sid
            for sid, info in slices.items()
            for member in info.member_nodes
        }
        joint = joint_disrupted_slices(tpu_nodes, slice_of)
        disrupted: Set[str] = set(joint["all"])
        if extra_disrupted:
            disrupted |= set(extra_disrupted)
        rolling_sids: Set[str] = set(joint[OWNER_REPARTITION])
        summary.budget_cap = parse_max_unavailable(
            getattr(spec, "max_unavailable", None), len(slices)
        )

        nodes_by_name = {n["metadata"]["name"]: n for n in tpu_nodes}
        pending_sids: Set[str] = set()
        for name, node in nodes_by_name.items():
            labels = node.get("metadata", {}).get("labels", {}) or {}
            rolling = (
                labels.get(consts.REPARTITION_STATE_LABEL)
                == consts.REPARTITION_STATE_ROLLING
            )
            done = (
                labels.get(consts.SLICE_CONFIG_LABEL) == desired
                and labels.get(consts.SLICE_CONFIG_STATE_LABEL)
                == STATE_SUCCESS
            )
            if rolling and done:
                # layout applied: release the hold
                try:
                    self._clear_rolling(name)
                    summary.completed_nodes += 1
                    self.rolls_completed_total += 1
                except (NotFoundError, ConflictError):
                    pass  # vanished/contended: next pass retries
                continue
            if rolling and labels.get(
                consts.SLICE_CONFIG_STATE_LABEL
            ) == STATE_FAILED:
                summary.failed_nodes.append(name)
                self._log_once(
                    (name, "failed"),
                    "node %s: slice re-partition to %r reported failed; "
                    "holding the slice disrupted while the node's slice "
                    "manager retries",
                    name,
                    desired,
                )
                continue
            if not rolling and not done:
                pending_sids.add(slice_of.get(name, name))

        # a slice PARTIALLY admitted (operator crashed mid-wave, or a
        # member joined mid-roll) finishes its batch without new budget:
        # the slice is already disrupted
        for sid in sorted(pending_sids & rolling_sids):
            self._admit_slice(
                sid, slices[sid].member_nodes, nodes_by_name, desired
            )
        pending_sids -= rolling_sids

        # fresh admissions within the JOINT headroom, whole slices only
        admitted = 0
        for sid in sorted(pending_sids):
            if admit_filter is not None and sid not in admit_filter:
                # outside the rollout's current cohort: the slice waits
                # for its wave (the orchestrator widens the gate when it
                # promotes a stage)
                continue
            if sid in disrupted:
                # another actor (upgrade roll, quarantine) owns this
                # slice's disruption: never double-disrupt — it becomes
                # eligible when that actor releases it
                self._log_once(
                    (sid, "interlock"),
                    "slice %s: re-partition deferred — another actor "
                    "holds it disrupted",
                    sid,
                )
                continue
            self._logged.discard((sid, "interlock"))
            if self._under_maintenance(sid, slices, nodes_by_name):
                continue
            if len(disrupted) >= summary.budget_cap:
                summary.deferred_slices += 1
                self.budget_deferred_total += 1
                self._log_once(
                    (sid, "budget"),
                    "slice %s: re-partition deferred — %d slice(s) "
                    "already disrupted (upgrades + repairs + rolls) at "
                    "the maxUnavailable cap of %d",
                    sid,
                    len(disrupted),
                    summary.budget_cap,
                )
                continue
            self._logged.discard((sid, "budget"))
            started = self._admit_slice(
                sid, slices[sid].member_nodes, nodes_by_name, desired
            )
            if started:
                disrupted.add(sid)
                rolling_sids.add(sid)
                admitted += 1
                self.rolls_started_total += started
                self._record_event(
                    "Normal",
                    "SliceRepartitionStarted",
                    f"slice {sid}: rolling {started} member host(s) to "
                    f"slice layout {desired!r} (one shared-budget "
                    f"disruption unit)",
                    dedup_extra=sid,
                )

        summary.admitted_slices = admitted
        summary.rolling_slices = len(rolling_sids)
        summary.pending_slices = len(pending_sids - rolling_sids)
        summary.disrupted_slices = len(disrupted)
        # retire log-once state for vanished nodes/slices
        live = set(nodes_by_name) | set(slices)
        self._logged.prune(live)
        self.last_summary = {
            "desired": desired,
            "total": summary.total,
            "pending_slices": summary.pending_slices,
            "rolling_slices": summary.rolling_slices,
            "admitted_slices": summary.admitted_slices,
            "deferred_slices": summary.deferred_slices,
            "completed_nodes": summary.completed_nodes,
            "failed_nodes": summary.failed_nodes,
            "budget_cap": summary.budget_cap,
            "disrupted_slices": summary.disrupted_slices,
        }
        return summary

    # ------------------------------------------------------------------
    def _admit_slice(
        self,
        sid: str,
        member_nodes: List[str],
        nodes_by_name: Dict[str, Obj],
        desired: str,
    ) -> int:
        """Mark every not-yet-done member of one slice rolling + desired
        (state reset to pending so a stale ``success`` from the PREVIOUS
        layout can't read as done). Returns members actually started."""
        from tpu_operator.sliceman.slice_manager import (
            STATE_PENDING,
            STATE_SUCCESS,
        )

        started = 0
        for name in sorted(member_nodes):
            node = nodes_by_name.get(name)
            if node is None:
                continue
            labels = node.get("metadata", {}).get("labels", {}) or {}
            if (
                labels.get(consts.SLICE_CONFIG_LABEL) == desired
                and labels.get(consts.SLICE_CONFIG_STATE_LABEL)
                == STATE_SUCCESS
            ):
                continue  # this member already runs the layout
            if (
                labels.get(consts.REPARTITION_STATE_LABEL)
                == consts.REPARTITION_STATE_ROLLING
                and labels.get(consts.SLICE_CONFIG_LABEL) == desired
            ):
                continue  # already admitted (crash-resume)

            def mutate(fresh):
                fl = fresh["metadata"].setdefault("labels", {})
                changed = False
                for key, value in (
                    (consts.SLICE_CONFIG_LABEL, desired),
                    (consts.SLICE_CONFIG_STATE_LABEL, STATE_PENDING),
                    (
                        consts.REPARTITION_STATE_LABEL,
                        consts.REPARTITION_STATE_ROLLING,
                    ),
                ):
                    if fl.get(key) != value:
                        fl[key] = value
                        changed = True
                # rollback fact for the health-gated rollout: the
                # pre-roll validator perf reading becomes the baseline
                # its TFLOPS/membw deltas are measured against (the
                # upgrade FSM records the same at ITS admission)
                ann = fresh["metadata"].setdefault("annotations", {})
                perf = ann.get(consts.VALIDATOR_PERF_ANNOTATION)
                if perf and (
                    ann.get(consts.VALIDATOR_PERF_BASELINE_ANNOTATION)
                    != perf
                ):
                    ann[consts.VALIDATOR_PERF_BASELINE_ANNOTATION] = perf
                    changed = True
                return changed

            try:
                mutate_with_retry(
                    self.client, "v1", "Node", name, mutate=mutate
                )
                started += 1
                # flight timeline: each admitted member is one budget-
                # consuming write — the event a budget post-mortem names
                flight.record(
                    "budget.admit",
                    owner="repartition",
                    sid=sid,
                    node=name,
                    layout=desired,
                )
                log.info(
                    "node %s: rolling slice layout -> %r (slice %s)",
                    name,
                    desired,
                    sid,
                )
            except (NotFoundError, ConflictError):
                # vanished/contended member: the slice stays rolling via
                # whoever was marked; the partial-admission sweep above
                # finishes the batch next pass
                log.warning(
                    "node %s: re-partition admit write failed; retrying "
                    "next pass",
                    name,
                )
        return started

    def _clear_rolling(self, name: str) -> None:
        def mutate(fresh):
            labels = fresh["metadata"].setdefault("labels", {})
            if consts.REPARTITION_STATE_LABEL not in labels:
                return False
            del labels[consts.REPARTITION_STATE_LABEL]
            return True

        mutate_with_retry(self.client, "v1", "Node", name, mutate=mutate)
        flight.record("budget.release", owner="repartition", node=name)
        log.info("node %s: slice re-partition complete; hold released", name)

    def _under_maintenance(
        self, sid: str, slices, nodes_by_name: Dict[str, Obj]
    ) -> bool:
        for name in slices[sid].member_nodes:
            node = nodes_by_name.get(name)
            if node is None:
                continue
            if (node.get("metadata", {}).get("labels", {}) or {}).get(
                consts.MAINTENANCE_STATE_LABEL
            ):
                self._log_once(
                    (sid, "maintenance"),
                    "slice %s: re-partition deferred during host "
                    "maintenance on %s",
                    sid,
                    name,
                )
                return True
        self._logged.discard((sid, "maintenance"))
        return False

    def _cleanup_abandoned(self, tpu_nodes: List[Obj]) -> None:
        """No desired layout configured: any leftover ``rolling`` label
        is an abandoned roll still holding budget — release it. Steady
        path writes nothing (label-dict scan only)."""
        for node in tpu_nodes:
            labels = node.get("metadata", {}).get("labels", {}) or {}
            if consts.REPARTITION_STATE_LABEL not in labels:
                continue
            try:
                self._clear_rolling(node["metadata"]["name"])
            except (NotFoundError, ConflictError):
                continue

    # ------------------------------------------------------------------
    def _log_once(self, key: tuple, msg: str, *args) -> None:
        self._logged.log(log, key, msg, *args)

    def _record_event(
        self, etype: str, reason: str, message: str, dedup_extra: str = ""
    ) -> None:
        from tpu_operator.kube.events import cluster_policy_ref, record_event

        try:
            record_event(
                self.client,
                self.namespace,
                cluster_policy_ref(),
                etype,
                reason,
                message,
                dedup_extra=dedup_extra,
            )
        except Exception:
            log.debug("repartition event write failed", exc_info=True)
