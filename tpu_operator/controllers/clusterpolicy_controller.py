"""ClusterPolicy reconciler.

TPU-native analogue of ``controllers/clusterpolicy_controller.go``:

* singleton enforcement — extra CRs get status ``Ignored`` (``:104-109``);
* every reconcile runs the full state machine (``:134-158``), relying on
  hash idempotency to no-op;
* 5 s requeue while NotReady (``:140,167``), 45 s poll when no TPU/NFD
  labels are present yet (``:170-182``);
* CR status + operator metrics updates (``:184-196``).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Optional

from tpu_operator import consts
from tpu_operator.api.v1.clusterpolicy_types import State
from tpu_operator.kube.events import TYPE_NORMAL, TYPE_WARNING, record_event
from tpu_operator.controllers.operator_metrics import OperatorMetrics
from tpu_operator.controllers.state_manager import (
    ClusterPolicyController,
    has_tpu_labels,
)
from tpu_operator.kube.client import Client, ConflictError
from tpu_operator.obs import flight, trace

log = logging.getLogger("tpu-operator.reconcile")

# requeue cadences (reference :140,167,173)
REQUEUE_NOT_READY_S = 5.0
REQUEUE_NO_LABELS_S = 45.0


@dataclass
class Result:
    requeue_after: Optional[float] = None
    ready: bool = False


def select_primary(policies):
    """Deterministic singleton selection shared by both reconcilers: oldest
    creationTimestamp wins, name as tiebreak. resourceVersion is opaque and
    bumped by our own status writes, so it must not participate."""
    policies = sorted(
        policies,
        key=lambda o: (
            o["metadata"].get("creationTimestamp", ""),
            o["metadata"].get("name", ""),
        ),
    )
    return policies[0], policies[1:]


class ClusterPolicyReconciler:
    def __init__(self, client: Client, assets_dir: Optional[str] = None):
        self.client = client
        self.ctrl = ClusterPolicyController(client, assets_dir=assets_dir)
        self.metrics = OperatorMetrics()
        self.ctrl.metrics = self.metrics
        # node-health remediation FSM (runs inside the reconcile pass,
        # after label_tpu_nodes has produced the pass's node list)
        from tpu_operator.controllers.remediation import (
            NodeRemediationController,
        )

        self.remediation = NodeRemediationController(client)
        # live slice re-partition roll (third consumer of the shared
        # disruption budget; no-op without spec.sliceManager.config.default)
        from tpu_operator.controllers.repartition import (
            SliceRepartitionController,
        )

        self.repartition = SliceRepartitionController(client)
        # health-gated progressive rollout orchestrator (canary waves +
        # automatic rollback; no-op without spec.rollout.enabled). Runs
        # after remediation (fresh quarantines are gate evidence) and
        # before repartition (which consumes the computed cohort gate).
        from tpu_operator.controllers.rollout import RolloutController

        self.rollout = RolloutController(client)
        # (Node, Pod) store versions of the last clean slice aggregation
        # — while both hold, the per-node slice grouping and readiness
        # math is a pure recomputation over an unchanged world, so the
        # memoized summary is served instead (see _aggregate_slices)
        self._slice_world = None
        self._slice_summary = None
        # state_render_ms label values currently exported (so series for
        # states gone from the render cost map can be removed)
        self._render_ms_states = set()
        # completed reconcile passes (plain int, no prometheus needed):
        # external health/invariant checkers use this to reason in
        # operator-pass units instead of wall time — "stale for N
        # passes" is meaningful on any box, "stale for N seconds" only
        # on an idle one
        self.passes_total = 0
        # cumulative full-pass wall time (ms): the churn-storm bench's
        # delta-vs-full A/B reads this next to delta.delta_ms_total
        self.full_ms_total = 0.0
        # event-scoped delta sub-reconciles (controllers/delta.py):
        # targeted node/slice entry points the keyed workqueue drives
        # between full passes; each full pass re-seeds its slice mirror
        from tpu_operator.controllers.delta import DeltaReconciler

        self.delta = DeltaReconciler(self)
        # sharded scale-out (tpu_operator/shard.py): the replica's shard
        # ownership view, or None (single-process default). Non-owners
        # of shard 0 run the SCOPED pass (label + verdict work for
        # owned shards only); the shard-0 owner runs the global pass
        # with every budgeted section behind a live lease re-check.
        self.shard_state = None
        # True while a scoped pass body runs (the CP barrier key keeps
        # passes serial): note_full_pass reads it to tell a scoped
        # aggregate from a global one across a mid-pass takeover
        self._scoped_pass_active = False
        # Degraded-transition tracker: the flight recorder dumps once
        # per NEW errored-state picture, not once per 5 s requeue
        self._last_errored_states: frozenset = frozenset()
        # the last pass's self-time-by-layer trace summary (populated
        # while tracing is enabled; /debug/vars "trace" mirrors it)
        self.last_trace_summary = {}
        # flight dumps post a warning Event against the primary CR.
        # Weakly bound: the process-global recorder must not pin a
        # retired reconciler (test fixtures build many per process)
        import weakref

        self_ref = weakref.ref(self)

        def _sink(reason: str, detail: str, path: str) -> None:
            live = self_ref()
            if live is not None:
                live._flight_dump_event(reason, detail, path)

        flight.RECORDER.event_sink = _sink

    def _flight_dump_event(self, reason: str, detail: str, path: str) -> None:
        """Flight-recorder dump notifier: a warning Event on the CR so
        the dump is discoverable from ``kubectl describe``."""
        cp = self.ctrl.cp_obj
        ns = self.ctrl.namespace
        if not cp or not ns:
            return
        record_event(
            self.client,
            ns,
            cp,
            TYPE_WARNING,
            "FlightRecorderDump",
            f"flight recorder dumped ({reason}"
            + (f": {detail}" if detail else "")
            + f") -> {path}",
        )

    def reconcile(self, name: str = "") -> Result:
        import time as _time

        # copy=True: the CR objects are mutated below (_set_status writes
        # status in place; init stores the primary as cp_obj) — they must
        # be private copies, not the informer's shared frozen views
        policies = self.client.list(
            consts.API_VERSION, consts.CLUSTER_POLICY_KIND, copy=True
        )
        if not policies:
            self.metrics.observe_reconcile(-2)
            return Result()
        # one cluster snapshot per pass: the 18 states' readiness checks
        # share one node scan + one indexed pod read per app instead of
        # each issuing their own (end_pass also feeds the hit-rate debug
        # surface and metrics)
        t0 = _time.perf_counter()
        self.ctrl.begin_pass()
        try:
            with trace.span("pass.reconcile", n=self.passes_total):
                return self._reconcile_pass(policies)
        finally:
            self.ctrl.end_pass()
            self.passes_total += 1
            pass_ms = (_time.perf_counter() - t0) * 1000.0
            self.full_ms_total += pass_ms
            hist = getattr(self.metrics, "reconcile_pass_ms_hist", None)
            if hist is not None:
                hist.observe(pass_ms)
            if trace.TRACER.enabled:
                self.last_trace_summary = trace.TRACER.mark_pass()
            self._update_snapshot_metrics()

    def _reconcile_pass(self, policies) -> Result:
        ss = self.shard_state
        if ss is not None:
            if not ss.owns_full_pass():
                return self._shard_scoped_pass(policies)
            if not ss.confirm_full_pass_owner():
                # split-brain guard: this replica BELIEVED it held
                # shard 0 but a live lease read says otherwise (taken
                # over mid-window). Running the budget arbiter now
                # would double-drain against the new owner — degrade
                # to scoped-worker work instead (confirm already
                # demoted our ownership view).
                log.warning(
                    "shard-0 lease lost mid-window; fencing the "
                    "budgeted full pass and degrading to scoped work"
                )
                flight.record("shard.fenced", identity=ss.identity)
                return self._shard_scoped_pass(policies)
        primary, extras = select_primary(policies)
        for extra in extras:
            self._set_status(extra, State.IGNORED)

        try:
            self.ctrl.init(primary)
        except Exception:
            log.exception("init failed")
            # init may have opened an apply-set pass before raising; an
            # empty commit would read as "prune everything"
            self.ctrl.applyset.abort()
            self._set_status(primary, State.NOT_READY)
            self.metrics.observe_reconcile(-1)
            raise

        # no TPU nodes and no hardware labels yet: keep polling NFD/GKE
        # (reference :170-182); has_tpu_nodes was computed by init's
        # label_tpu_nodes pass over the node list
        if not self.ctrl.has_tpu_nodes:
            # no states ran, nothing registered: sealing this pass would
            # prune every previously-applied object
            self.ctrl.applyset.abort()
            self._set_status(primary, State.NOT_READY)
            self.metrics.observe_reconcile(0)
            self._update_fleet_metrics()
            return Result(requeue_after=REQUEUE_NO_LABELS_S)

        overall = State.READY
        not_ready_states = []
        errored_states = []  # (state, "ExcType: message") — this pass
        # DAG-pipelined deployment: states with no ordering edge deploy
        # concurrently; outcomes come back in STATE_ORDER order.
        # Per-state error isolation is preserved: one state's exception
        # (a busted asset, a write that exhausted its retries) never
        # aborts the INDEPENDENT states — the reference reports
        # reconciliation_status per run rather than losing the whole
        # pass. A pass starting from Ready is a zero-write steady pass:
        # it runs the waves sequentially (see run_states).
        steady = (primary.get("status", {}) or {}).get("state") == State.READY
        for state_name, outcome in self.ctrl.run_states(
            concurrent=not steady
        ):
            if isinstance(outcome, BaseException):
                log.error(
                    "state %s failed; isolating and continuing",
                    state_name,
                    exc_info=outcome,
                )
                overall = State.NOT_READY
                errored_states.append(
                    (state_name, f"{type(outcome).__name__}: {outcome}")
                )
                self.metrics.set_state(state_name, -2)
                continue
            status = outcome
            self.metrics.set_state(
                state_name,
                {State.READY: 1, State.NOT_READY: 0}.get(status, -1),
            )
            if status == State.NOT_READY:
                overall = State.NOT_READY
                not_ready_states.append(state_name)
                log.info("state %s not ready; will requeue", state_name)
        if self.metrics and getattr(self.metrics, "states_errored", None):
            self.metrics.states_errored.set(len(errored_states))
        # flush barrier: nothing of this pass's write fan-out may
        # outlive the pass (remediation/slice aggregation below read the
        # world the states just wrote). Errors already surfaced through
        # the per-state futures; drain only collects stragglers.
        self.ctrl.writes.drain()

        # apply-set pruning: a pass that ran EVERY state to completion
        # holds the complete intended-object picture — seal it and
        # delete what an earlier pass applied but this one abandoned
        # (the renamed-DaemonSet leak). An errored state's registrations
        # are incomplete, so that pass aborts instead: membership stays
        # at the last complete picture and nothing is pruned on partial
        # information.
        if errored_states:
            self.ctrl.applyset.abort()
        else:
            self.ctrl.prune_abandoned()

        # node-health remediation (its quarantine label writes move the
        # Node store version, so the slice aggregate below never memoizes
        # a pre-quarantine world; the labels themselves land in the next
        # pass's node list — level-triggered, like every other writer)
        with trace.span("fsm.remediation"):
            remediation_summary = self._run_remediation()

        # health-gated rollout orchestration (canary→wave→fleet staging
        # of any fleet-wide version/layout change, with automatic
        # rollback on failing canary evidence): consumes the fresh
        # remediation verdicts as gate evidence and computes the cohort
        # admission gate the re-partition roll (below) and the upgrade
        # reconciler both honor
        with trace.span("fsm.rollout"):
            rollout_summary = self._run_rollout(primary, remediation_summary)

        # live slice re-partition roll (after remediation, and handed
        # remediation's in-pass disrupted set: the quarantine labels it
        # just wrote are on the wire but NOT in this pass's node
        # snapshot, and the label-derived joint set alone would let the
        # two consumers jointly over-admit past the one cap)
        with trace.span("fsm.repartition"):
            repartition_summary = self._run_repartition(
                remediation_summary, rollout_summary
            )

        with trace.span("pass.slices"):
            slice_summary = self._aggregate_slices()

        was_ready = (primary.get("status", {}) or {}).get("state") == State.READY
        if overall == State.READY and not was_ready:
            record_event(
                self.client,
                self.ctrl.namespace,
                primary,
                TYPE_NORMAL,
                "Ready",
                "all TPU operand states are ready",
            )
        elif not_ready_states:
            record_event(
                self.client,
                self.ctrl.namespace,
                primary,
                TYPE_WARNING,
                "OperandsNotReady",
                f"states not ready: {', '.join(not_ready_states)}",
            )
        if errored_states:
            record_event(
                self.client,
                self.ctrl.namespace,
                primary,
                TYPE_WARNING,
                "StatesDegraded",
                "states errored: "
                + "; ".join(f"{n} ({e})" for n, e in errored_states),
            )
        # flight recorder: a NEW Degraded picture dumps the recent
        # causal timeline once (the 5 s requeue re-reporting the same
        # errored set must not dump every pass)
        errored_now = frozenset(n for n, _ in errored_states)
        if errored_states and errored_now != self._last_errored_states:
            for state_name, err in errored_states:
                flight.record("state.degraded", state=state_name, error=err)
            flight.RECORDER.dump(
                "state-degraded",
                detail=", ".join(sorted(errored_now)),
            )
        self._last_errored_states = errored_now

        self._set_status(
            primary, overall, slice_summary, errored_states,
            remediation_summary, rollout_summary,
        )
        self._update_fleet_metrics()
        if errored_states:
            # the run is degraded even though it completed: report it
            # like the reference's reconciliation_status=-1, and keep the
            # level-triggered 5s requeue converging the healthy states
            self.metrics.observe_reconcile(-1)
            return Result(requeue_after=REQUEUE_NOT_READY_S)
        if overall == State.NOT_READY:
            self.metrics.observe_reconcile(0)
            return Result(requeue_after=REQUEUE_NOT_READY_S)
        self.metrics.observe_reconcile(1)
        if remediation_summary is not None and remediation_summary.active:
            # unhealthy nodes mid-FSM: their escalation backoffs elapse
            # without any cluster event to wake the reconciler, so the
            # level-triggered requeue is the remediation clock
            return Result(ready=True, requeue_after=REQUEUE_NOT_READY_S)
        if repartition_summary is not None and repartition_summary.active:
            # an in-flight/pending layout roll: budget headroom opens
            # when ANOTHER consumer releases a slice — no cluster event
            # of ours fires for that, so the requeue is the roll's clock
            return Result(ready=True, requeue_after=REQUEUE_NOT_READY_S)
        if rollout_summary is not None and rollout_summary.active:
            # a staged roll in flight: the observation window and the
            # rollback's re-roll elapse without any cluster event — the
            # requeue is the rollout's clock
            return Result(ready=True, requeue_after=REQUEUE_NOT_READY_S)
        return Result(ready=True)

    # ------------------------------------------------------------------
    def _shard_scoped_pass(self, policies) -> Result:
        """The non-shard-0 replica's pass (sharded scale-out): label and
        slice-verdict convergence for the shards THIS replica owns —
        O(owned nodes), riding the scoped informer stores — while CR
        render, operand deployment, the three budgeted FSMs and status
        stay pinned to the shard-0 owner. Also seeds the delta
        reconciler's context so keyed sub-reconciles run here at event
        speed between passes."""
        primary, _ = select_primary(policies)
        ctrl = self.ctrl
        # the SAME decode preamble as the owner's init (rollback
        # override included): label decisions must agree across
        # replicas, so the preamble is shared, not mirrored
        ctrl.decode_primary(primary)
        # marks this pass's aggregate as SCOPED for note_full_pass: a
        # shard-0 takeover landing mid-pass must not let the partial
        # mirror masquerade as global context
        self._scoped_pass_active = True
        try:
            with trace.span("pass.shard_scope"):
                ctrl.label_tpu_nodes()
                ctrl.writes.drain()
                self._aggregate_slices()
        finally:
            self._scoped_pass_active = False
        self.metrics.observe_reconcile(1)
        return Result(ready=True)

    def _slice_owns_gate(self):
        """The verdict-publish gate for ``slice_status.aggregate``:
        ``covers_slice`` of the shard view, or None (publish all) for
        the single-process operator."""
        ss = self.shard_state
        if ss is None:
            return None
        return ss.covers_slice

    def _run_remediation(self):
        """Node-health remediation pass (tentpole of the robustness
        story): derives per-node health from the pass's in-hand node
        list + one namespace pod listing, steps each unhealthy node's
        FSM, and reports counts for status/metrics. Failure-isolated
        like any state: a remediation exception must not abort the
        reconcile."""
        from tpu_operator.controllers.state_manager import has_tpu_labels

        try:
            tpu_nodes = [
                n for n in (self.ctrl._nodes_cache or ()) if has_tpu_labels(n)
            ]
            summary = self.remediation.reconcile(
                tpu_nodes, self.ctrl.cp.spec.remediation, self.ctrl.namespace
            )
        except Exception:
            log.exception("node remediation pass failed")
            # zero the gauges AND hand back an errored (all-zero) summary:
            # freezing metrics or status at the LAST pass's picture (an
            # open breaker, a quarantine count) while remediation is not
            # actually running would keep alerts — and the CR — on stale
            # data; errored=True keeps the 5s requeue retrying the pass
            self._update_remediation_metrics(None)
            from tpu_operator.controllers.remediation import (
                RemediationSummary,
            )

            return RemediationSummary(errored=True)
        self._update_remediation_metrics(summary)
        return summary

    def _run_rollout(self, primary, remediation_summary=None):
        """Health-gated rollout orchestration pass. Failure-isolated
        like remediation: an orchestrator exception must not abort the
        reconcile — the 5s requeue retries it, and an errored pass
        reports active so the clock keeps ticking."""
        from tpu_operator.controllers.rollout import RolloutSummary
        from tpu_operator.controllers.state_manager import has_tpu_labels

        try:
            tpu_nodes = [
                n for n in (self.ctrl._nodes_cache or ()) if has_tpu_labels(n)
            ]
            return self.rollout.reconcile(
                tpu_nodes,
                primary,
                self.ctrl.cp.spec.rollout,
                getattr(self.ctrl, "raw_roll_targets", None) or {},
                self.ctrl.namespace,
                remediation_summary=remediation_summary,
            )
        except Exception:
            log.exception("rollout orchestration pass failed")
            # FAIL CLOSED: an errored orchestrator must freeze fresh
            # staged admissions (admit_sids=set()), not leave the
            # same-pass repartition roll unrestricted — the 5s errored
            # retry re-opens the gate as soon as a pass succeeds
            return RolloutSummary(errored=True, admit_sids=set())

    def _run_repartition(self, remediation_summary=None, rollout_summary=None):
        """Live slice re-partition pass (third shared-budget consumer).
        Failure-isolated like remediation: a roll exception must not
        abort the reconcile; the 5s requeue retries it."""
        from tpu_operator.controllers.state_manager import has_tpu_labels

        try:
            tpu_nodes = [
                n for n in (self.ctrl._nodes_cache or ()) if has_tpu_labels(n)
            ]
            return self.repartition.reconcile(
                tpu_nodes,
                self.ctrl.cp.spec.slice_manager,
                self.ctrl.namespace,
                extra_disrupted=getattr(
                    remediation_summary, "disrupted_sids", None
                ),
                admit_filter=getattr(rollout_summary, "admit_sids", None),
            )
        except Exception:
            log.exception("slice re-partition pass failed")
            from tpu_operator.controllers.repartition import (
                RepartitionSummary,
            )

            # rolling_slices=1 keeps .active truthy so the 5s requeue
            # retries the errored pass (any held slices stay honest)
            return RepartitionSummary(rolling_slices=1)

    def _update_remediation_metrics(self, summary) -> None:
        m = self.metrics
        if not m or not getattr(m, "remediation_nodes_unhealthy", None):
            return
        rc = self.remediation
        if summary is None:
            m.remediation_nodes_unhealthy.set(0)
            m.remediation_nodes_quarantined.set(0)
            m.remediation_nodes_exhausted.set(0)
            m.remediation_breaker_open.set(0)
        else:
            m.remediation_nodes_unhealthy.set(summary.unhealthy)
            m.remediation_nodes_quarantined.set(summary.quarantined)
            m.remediation_nodes_exhausted.set(summary.exhausted)
            m.remediation_breaker_open.set(1 if summary.breaker_open else 0)
        m.remediation_drains_vetoed.set(rc.drains_vetoed_total)
        m.remediation_attempts_total.set(rc.attempts_total)

    def _aggregate_slices(self):
        """Slice-scoped readiness (SURVEY.md §7 hard part): a multi-host
        pod-slice is only Ready when every member host validated. Publishes
        ``tpu.k8s.io/tpu.slice.ready`` node labels + metrics; summarized in
        the CR status by ``_set_status``."""
        from tpu_operator.controllers import slice_status
        from tpu_operator.controllers.state_manager import has_tpu_labels

        versions = self._store_versions()
        if (
            versions is not None
            and versions == self._slice_world
            and self._slice_summary is not None
        ):
            # unchanged (Node, Pod) world: slice identity, membership,
            # health and the published labels are all still exactly what
            # the memoized aggregation computed
            summary = self._slice_summary
        else:
            self._slice_world = None
            try:
                tpu_nodes = [
                    n
                    for n in (self.ctrl._nodes_cache or ())
                    if has_tpu_labels(n)
                ]
                summary = slice_status.aggregate(
                    self.client,
                    self.ctrl.namespace,
                    tpu_nodes,
                    pipeline=self.ctrl.writes,
                    lane=self.ctrl.label_lane,
                    owns=self._slice_owns_gate(),
                )
            except Exception:
                log.exception("slice readiness aggregation failed")
                return None
            if versions is not None and versions == self._store_versions():
                # nothing moved during the aggregation (it published no
                # labels and no event raced it): memoize until the world
                # does
                self._slice_world = versions
                self._slice_summary = summary
        if self.metrics and getattr(self.metrics, "slices_total", None):
            self.metrics.slices_total.set(summary.total)
            self.metrics.slices_ready.set(summary.ready)
        # re-seed the delta path's slice mirror IMMEDIATELY (not at pass
        # end): the aggregation just published its verdict labels, and
        # every publish echoes back through the watch as a node event —
        # the router's echo predicate can only drop those once the
        # mirror agrees, so a late seed turns a 1000-node flip into a
        # 1000-key no-op backlog on the delta workers
        self.delta.note_full_pass(summary)
        return summary

    def _store_versions(self):
        """(Node, Pod) world key for the slice memo, or None whenever a
        memo would be unsafe.

        The node component is the version ``_nodes_cache`` — the list
        the aggregation actually consumes — was taken at (stamped by
        ``label_tpu_nodes``), and it only counts while the LIVE store
        still sits at that version: a node event landing mid-pass (after
        the label scan, before/while aggregating) makes the consumed
        list stale, and memoizing its summary under the newer version
        would mask the event until some unrelated change. The pod
        component is read live — the validator-pod list is read inside
        the aggregation itself."""
        fn = getattr(self.client, "store_version", None)
        if fn is None:
            return None
        node_v = self.ctrl._nodes_cache_version
        pod_v = fn("v1", "Pod")
        if node_v is None or pod_v is None or fn("v1", "Node") != node_v:
            return None
        return (node_v, pod_v)

    def _update_fleet_metrics(self) -> None:
        if (
            self.metrics
            and getattr(self.metrics, "informer_drift_repairs", None)
            and hasattr(self.client, "drift_repairs_total")
        ):
            self.metrics.informer_drift_repairs.set(
                self.client.drift_repairs_total()
            )
        if self.metrics and getattr(self.metrics, "tpu_nodes_total", None):
            self.metrics.tpu_nodes_total.set(self.ctrl.tpu_node_count)
            self.metrics.feature_labels_present.set(
                1 if self.ctrl.has_tpu_nodes else 0
            )
            self.metrics.libtpu_generations_total.set(
                len(self.ctrl.tpu_generations)
            )
            under_maintenance = sum(
                1
                for n in (self.ctrl._nodes_cache or ())
                if (n.get("metadata", {}).get("labels") or {}).get(
                    consts.MAINTENANCE_STATE_LABEL
                )
            )
            self.metrics.nodes_under_maintenance.set(under_maintenance)

    def _update_snapshot_metrics(self) -> None:
        """Cache-read observability: informer read counters + list
        latency, the per-pass snapshot hit profile, and the render
        cache's hit/miss + per-state render cost — so both halves of the
        hot loop (reads AND renders) show up on the metrics surface
        instead of only in bench output."""
        m = self.metrics
        if not m or not getattr(m, "snapshot_hits", None):
            return
        stats = self.ctrl.last_snapshot_stats or {}
        m.snapshot_hits.set(stats.get("hits", 0))
        m.snapshot_misses.set(stats.get("misses", 0))
        if hasattr(self.client, "read_stats"):
            reads = self.client.read_stats()
            m.cache_gets.set(reads["gets"])
            m.cache_lists.set(reads["lists"])
            m.cache_list_seconds.set(reads["list_seconds"])
            m.cache_indexed_lists.set(reads["indexed_lists"])
            m.cache_copied_reads.set(reads["copied_reads"])
        if getattr(m, "render_cache_hits", None):
            render = self.ctrl.render_cache.stats()
            m.render_cache_hits.set(render["last_pass"]["hits"])
            m.render_cache_misses.set(render["last_pass"]["misses"])
            m.render_cache_entries.set(render["entries"])
            m.render_cache_invalidations.set(render["invalidations"])
            # a fingerprint invalidation resets the per-state render
            # cost; label series for states not re-rendered since must
            # not keep serving pre-invalidation readings
            current = set(render["render_ms_by_state"])
            for state in self._render_ms_states - current:
                try:
                    m.state_render_ms.remove(state)
                except KeyError:
                    pass
            self._render_ms_states = current
            for state, ms in render["render_ms_by_state"].items():
                m.state_render_ms.labels(state=state).set(ms)
        if getattr(m, "write_pipeline_depth", None):
            ws = self.ctrl.writes.stats()
            m.write_pipeline_depth.set(ws["depth"])
            m.write_pipeline_inflight.set(ws["inflight"])
            m.write_pipeline_queue_wait_ms.set(ws["queue_wait_ms_avg"])
            m.write_pipeline_errors_total.set(ws["errors_total"])
        if getattr(m, "apiserver_retries", None) and hasattr(
            self.client, "fault_stats"
        ):
            fault = self.client.fault_stats()
            retry = fault.get("retry")
            if retry:
                m.apiserver_retries.set(retry["retries_total"])
                m.apiserver_retry_giveups.set(retry["giveups_total"])
            breaker = fault.get("breaker")
            if breaker:
                m.apiserver_breaker_open.set(
                    1 if breaker["state"] == "open" else 0
                )
                m.apiserver_breaker_trips.set(breaker["trips_total"])

    def _set_status(
        self,
        cp_obj,
        state: str,
        slice_summary=None,
        errored=None,
        remediation_summary=None,
        rollout_summary=None,
    ) -> None:
        """reference ``updateCRState`` (``:198``) + Ready and Degraded
        conditions, the per-state error block, the slice-readiness
        aggregate, and the node-remediation counts (no reference
        analogues)."""
        status = cp_obj.setdefault("status", {})
        slices = None
        if slice_summary is not None:
            slices = {
                "total": slice_summary.total,
                "ready": slice_summary.ready,
            }
            if slice_summary.degraded:
                slices["degraded"] = slice_summary.degraded
        errored_block = [
            {"state": n, "error": e} for n, e in (errored or ())
        ]
        breaker_open = bool(
            remediation_summary is not None
            and remediation_summary.breaker_open
        )
        # the effective block: present only while there is something to
        # report (an all-healthy fleet keeps status clean, and the
        # no-change comparison below must agree with what gets stored)
        remediation_block = None
        if remediation_summary is not None:
            block = remediation_summary.status_block()
            if any(block.values()):
                remediation_block = block
        rollout_block = (
            rollout_summary.status_block()
            if rollout_summary is not None
            else None
        )
        if (
            status.get("state") == state
            and status.get("namespace")
            == (self.ctrl.namespace or status.get("namespace"))
            and (slices is None or status.get("slices") == slices)
            and (status.get("erroredStates") or []) == errored_block
            and (
                remediation_summary is None
                or status.get("remediation") == remediation_block
            )
            and (
                rollout_summary is None
                or status.get("rollout") == rollout_block
            )
        ):
            return
        from datetime import datetime, timezone

        prev_conditions = {
            c.get("type"): c for c in (status.get("conditions") or [])
        }
        status["state"] = state
        status["namespace"] = self.ctrl.namespace
        if slices is not None:
            status["slices"] = slices
        if errored_block:
            status["erroredStates"] = errored_block
        else:
            status.pop("erroredStates", None)
        if remediation_summary is not None:
            if remediation_block is not None:
                status["remediation"] = remediation_block
            else:
                status.pop("remediation", None)
        if rollout_summary is not None:
            if rollout_block is not None:
                status["rollout"] = rollout_block
            else:
                status.pop("rollout", None)

        now = datetime.now(timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")

        def condition(ctype, value, reason, message=None):
            # k8s condition semantics: lastTransitionTime only moves when
            # the condition's status actually flips, not on every status
            # write (e.g. a slices-aggregate fluctuation while Ready
            # stays True)
            prev = prev_conditions.get(ctype)
            cond = {
                "type": ctype,
                "status": value,
                "reason": reason,
                "lastTransitionTime": (
                    prev.get("lastTransitionTime")
                    if prev is not None and prev.get("status") == value
                    else now
                ),
            }
            if message:
                cond["message"] = message
            return cond

        status["conditions"] = [
            condition(
                "Ready",
                "True" if state == State.READY else "False",
                {
                    State.READY: "OperandsReady",
                    State.NOT_READY: "OperandsNotReady",
                    State.IGNORED: "IgnoredDuplicate",
                }.get(state, "Unknown"),
            ),
            condition(
                "Degraded",
                "True" if (errored_block or breaker_open) else "False",
                # the systemic breaker outranks per-state errors: a
                # fleet-wide node failure is the headline, not a busted
                # asset dir
                (
                    "SystemicNodeFailure"
                    if breaker_open
                    else "StatesErrored"
                    if errored_block
                    else "AllStatesHealthy"
                ),
                message=(
                    "; ".join(
                        (
                            [
                                f"{remediation_summary.unhealthy} of "
                                f"{remediation_summary.total} TPU nodes "
                                f"unhealthy; remediation halted with zero "
                                f"drains"
                            ]
                            if breaker_open
                            else []
                        )
                        + [
                            f"{b['state']}: {b['error']}"
                            for b in errored_block
                        ]
                    )
                    or None
                ),
            ),
        ]
        try:
            self.client.update_status(cp_obj)
        except ConflictError:
            # the CR moved while we reconciled (self-inflicted spec writes
            # or another writer): re-read and re-apply the status to the
            # fresh revision — standard status-writer retry, no logspam
            try:
                meta = cp_obj.get("metadata", {})
                # live read: behind an informer cache, re-reading the
                # cached revision would carry the same stale rv forever
                fresh = getattr(self.client, "get_live", self.client.get)(
                    cp_obj["apiVersion"], cp_obj["kind"], meta["name"],
                    meta.get("namespace", ""),
                )
                fresh["status"] = status
                self.client.update_status(fresh)
            except Exception:
                log.exception(
                    "failed to update ClusterPolicy status after conflict "
                    "retry; next reconcile will converge it"
                )
        except Exception:
            log.exception("failed to update ClusterPolicy status")


# ---------------------------------------------------------------------------
# watch predicates (reference addWatchNewGPUNode, :220-314)
# ---------------------------------------------------------------------------


def _tpu_resource_view(node: dict) -> tuple:
    """The node-status slice the operator's readiness logic consumes:
    TPU-prefixed capacity/allocatable entries (kubelet-derived chip
    health feeding slice-scoped readiness)."""
    status = node.get("status", {}) or {}
    out = []
    for bucket in ("capacity", "allocatable"):
        for k, v in sorted((status.get(bucket) or {}).items()):
            if k == consts.TPU_RESOURCE or k.startswith(
                consts.TPU_SUBSLICE_RESOURCE_PREFIX
            ):
                out.append((bucket, k, v))
    return tuple(out)


def node_event_needs_reconcile(event: str, old: Optional[dict], new: dict) -> bool:
    """Predicate deciding whether a Node event triggers a reconcile
    (reference ``:247-306``): new TPU node arrives, TPU labels change,
    operator labels were externally modified — or the kubelet changed
    the node's TPU capacity/allocatable (the reference's predicates are
    label-only, but slice-scoped readiness consumes kubelet-derived chip
    health, so a chip souring AFTER validation must wake the reconciler
    too)."""
    if event == "ADDED":
        return has_tpu_labels(new)
    if event == "DELETED":
        return True
    if old is None:
        return True
    if _tpu_resource_view(old) != _tpu_resource_view(new):
        return True
    old_labels = old.get("metadata", {}).get("labels", {}) or {}
    new_labels = new.get("metadata", {}).get("labels", {}) or {}
    if old_labels == new_labels:
        return False
    watched_prefixes = (
        "cloud.google.com/gke-tpu",
        "feature.node.kubernetes.io/",
        f"{consts.GROUP}/",
    )
    keys = set(old_labels) | set(new_labels)
    return any(
        old_labels.get(k) != new_labels.get(k)
        for k in keys
        if k.startswith(watched_prefixes)
    )
