"""Memoized manifest render pipeline keyed by a desired-state fingerprint.

The reconcile hot loop is "render manifests → transform → hash-gated
apply → readiness". PR 1 made the *read* half zero-copy; this module
removes the *render* half from the steady state. At steady state the
desired output of every control is a pure function of a small input
fingerprint — the ClusterPolicy spec (+ generation/uid), the operator
namespace, the discovered container runtime, the openshift flag and the
set of TPU generations present. While that fingerprint holds, each
control's ``copy.deepcopy`` + transform chain + ``compute_hash`` is
skipped entirely: the cached, pre-hashed, FROZEN rendered manifest
(``kube/frozen.py``) goes straight to the hash-annotation compare and
the readiness check.

Invalidation granularity:

* the **base fingerprint** covers every input a transform may read
  (spec, generation, uid, namespace, runtime, openshift). Any change —
  a spec edit, a runtime flip, a CR recreate — clears the whole cache:
  transforms read arbitrary spec fields, so nothing finer is safe.
* the **TPU generation set** affects only the per-generation libtpu
  fan-out. A new generation appearing renders exactly one new DaemonSet
  (its key simply misses); a generation vanishing drops exactly its
  entry. Nothing else re-renders.

Entries are frozen shared views: a consumer mutating a cached manifest
raises ``FrozenObjectError`` — the same always-on guard the informer
read path runs behind. ``apply_with_hash`` deep-copies (which thaws)
only on actual drift.

The cache is process-lifetime state on the ``ClusterPolicyController``
(one per reconciler); ``begin_pass`` is called from ``init()`` once the
pass's inputs are known.
"""

from __future__ import annotations

import hashlib
import json
import threading
from typing import Any, Dict, Iterable, Optional, Tuple

Obj = Dict[str, Any]

# cache key: (state_name, kind, asset name, generation-or-"")
Key = Tuple[str, str, str, str]
# entry: (frozen rendered manifest, content hash, generation-or-None)
Entry = Tuple[Obj, str, Optional[str]]


def _digest(payload: Any) -> str:
    blob = json.dumps(payload, sort_keys=True, separators=(",", ":"), default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def render_fingerprint(
    cp_obj: Obj, namespace: str, runtime: str, openshift: bool
) -> str:
    """The base desired-state fingerprint: a stable hash over every
    render input except the TPU generation set (which only scopes the
    libtpu fan-out and is handled at entry granularity).

    ``metadata.generation`` rides along even though ``spec`` is hashed
    directly (belt and braces against a lossy spec read), and ``uid``
    because ``set_owner_reference`` bakes it into every manifest — a
    deleted-and-recreated CR with an identical spec must not serve
    manifests owned by the dead UID. The daemonsets overrides named in
    the contract are part of ``spec``."""
    meta = cp_obj.get("metadata", {}) or {}
    return _digest(
        {
            "spec": cp_obj.get("spec", {}),
            "generation": meta.get("generation"),
            "uid": meta.get("uid"),
            "namespace": namespace,
            "runtime": runtime,
            "openshift": bool(openshift),
        }
    )


class RenderCache:
    """Fingerprint-gated memo of rendered-and-hashed manifests.

    Thread-safe: the manager still serializes passes
    (MaxConcurrentReconciles=1), but within a pass the write pipeline
    runs a wave's state controls concurrently and they all look up /
    store here — a lock guards the entry dict and the counters
    (``begin_pass`` stays single-threaded by construction, but takes
    the lock anyway so a racing /debug/vars scrape reads a consistent
    picture)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._base_fp: Optional[str] = None
        self._generations: Tuple[str, ...] = ()
        #: full fingerprint (base + sorted generations) — the /debug/vars
        #: identity of the world the cached manifests were rendered for
        self.fingerprint: Optional[str] = None
        self._entries: Dict[Key, Entry] = {}
        # cumulative render wall time per state since the last
        # invalidation (the cost the cache is amortizing)
        self._render_s_by_state: Dict[str, float] = {}
        self.hits_total = 0
        self.misses_total = 0
        self.pass_hits = 0
        self.pass_misses = 0
        self.renders_total = 0
        self.invalidations = 0

    # ------------------------------------------------------------------
    def begin_pass(self, base_fp: str, generations: Iterable[str]) -> None:
        """Reset per-pass counters and reconcile the cache against this
        pass's fingerprint: a base change clears everything, a
        generation-set change drops exactly the vanished generations'
        fan-out entries."""
        gens = tuple(sorted(generations))
        with self._lock:
            self._begin_pass_locked(base_fp, gens)

    def _begin_pass_locked(self, base_fp: str, gens: Tuple[str, ...]) -> None:
        if self._base_fp is not None and base_fp != self._base_fp:
            self._entries.clear()
            self._render_s_by_state.clear()
            self.invalidations += 1
        elif gens != self._generations:
            stale = [
                key
                for key, (_, _, gen) in self._entries.items()
                if gen is not None and gen not in gens
            ]
            for key in stale:
                del self._entries[key]
        self._base_fp = base_fp
        self._generations = gens
        self.fingerprint = _digest({"base": base_fp, "generations": list(gens)})
        self.pass_hits = 0
        self.pass_misses = 0

    # ------------------------------------------------------------------
    def lookup(self, key: Key) -> Optional[Tuple[Obj, str]]:
        """The memoized (frozen manifest, content hash) for ``key``, or
        None on a miss (the caller renders and ``store``s)."""
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                self.pass_misses += 1
                self.misses_total += 1
                return None
            self.pass_hits += 1
            self.hits_total += 1
            return ent[0], ent[1]

    def store(
        self,
        key: Key,
        frozen_obj: Obj,
        content_hash: str,
        state_name: str,
        render_s: float,
        generation: Optional[str] = None,
    ) -> None:
        with self._lock:
            self._entries[key] = (frozen_obj, content_hash, generation)
            self._render_s_by_state[state_name] = (
                self._render_s_by_state.get(state_name, 0.0) + render_s
            )
            self.renders_total += 1

    # ------------------------------------------------------------------
    # warm restart (kube/warm.py journal)
    # ------------------------------------------------------------------
    def export(self) -> Dict[str, Any]:
        """JSON-serializable snapshot of the cache (entries thawed to
        plain dicts) plus the base fingerprint it was rendered for —
        the warm journal's render half."""
        from tpu_operator.kube.frozen import thaw

        with self._lock:
            return {
                "base_fp": self._base_fp,
                "generations": list(self._generations),
                "entries": [
                    {
                        "key": list(key),
                        "obj": thaw(obj),
                        "hash": h,
                        "generation": gen,
                    }
                    for key, (obj, h, gen) in self._entries.items()
                ],
            }

    def seed(self, payload: Dict[str, Any]) -> int:
        """Load a journal snapshot BEFORE the first pass. The seeded
        base fingerprint is compared by the next ``begin_pass`` exactly
        like a live one: a restart whose inputs changed invalidates the
        seeded entries through the normal path, so a stale journal can
        never serve wrong manifests. Returns entries seeded."""
        from tpu_operator.kube.frozen import freeze

        base_fp = payload.get("base_fp")
        entries = payload.get("entries") or []
        if not base_fp or not entries:
            return 0
        with self._lock:
            if self._base_fp is not None:
                return 0  # a live pass already ran: its picture wins
            self._base_fp = base_fp
            self._generations = tuple(sorted(payload.get("generations") or ()))
            self.fingerprint = _digest(
                {"base": base_fp, "generations": list(self._generations)}
            )
            for ent in entries:
                key = ent.get("key")
                obj = ent.get("obj")
                h = ent.get("hash")
                if not key or len(key) != 4 or obj is None or not h:
                    continue
                self._entries[tuple(key)] = (
                    freeze(obj),
                    h,
                    ent.get("generation"),
                )
            return len(self._entries)

    # ------------------------------------------------------------------
    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> Dict[str, Any]:
        """Debug-surface / metrics payload: current fingerprint, entry
        count, last pass's hit profile, lifetime totals, and per-state
        render cost. Called from the /debug/vars HTTP thread while the
        reconcile thread mutates the cache — snapshot the dicts before
        iterating (a racing scrape may read a mid-pass value, but must
        never trip 'dict changed size during iteration')."""
        with self._lock:
            render_s_by_state = dict(self._render_s_by_state)
            total = self.hits_total + self.misses_total
            pass_total = self.pass_hits + self.pass_misses
            entries = len(self._entries)
            return {
                "fingerprint": self.fingerprint,
                "entries": entries,
                "last_pass": {
                    "hits": self.pass_hits,
                    "misses": self.pass_misses,
                    "hit_rate": (
                        round(self.pass_hits / pass_total, 4)
                        if pass_total
                        else 0.0
                    ),
                },
                "hits_total": self.hits_total,
                "misses_total": self.misses_total,
                "hit_rate_total": (
                    round(self.hits_total / total, 4) if total else 0.0
                ),
                "renders_total": self.renders_total,
                "invalidations": self.invalidations,
                "render_ms_by_state": {
                    state: round(sec * 1000.0, 3)
                    for state, sec in sorted(render_s_by_state.items())
                },
            }
