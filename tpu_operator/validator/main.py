"""``tpu-validator`` CLI (reference ``validator/main.go`` urfave/cli binary).

Run as initContainers inside the operand DaemonSets (``--component X``) and
as the long-running node-status exporter (``--component nodestatus``).
Flags mirror env vars like the reference (``validator/main.go:212-315``).
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys

from tpu_operator import consts
from tpu_operator.validator import components as comp
from tpu_operator.validator.components import StatusFiles, ValidationError

COMPONENTS = (
    "libtpu",
    "runtime",
    "plugin",
    "jax",
    "slice",
    "slice-workload",
    "ici",
    "ringattn",
    "pipeline",
    "moe",
    "membw",
    "flashattn",
    "vfio-pci",
    "vm-manager",
    "vm-devices",
    "nodestatus",
)


def _env_int(name: str, default: int) -> int:
    """Env-backed int flag default; a malformed value (e.g. an unresolved
    Helm template rendering to "") must fall back, not crash the
    initContainer before argparse can even print usage."""
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        logging.getLogger("tpu-validator").warning(
            "ignoring non-integer %s=%r", name, os.environ.get(name)
        )
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        logging.getLogger("tpu-validator").warning(
            "ignoring non-numeric %s=%r", name, os.environ.get(name)
        )
        return default


def build_parser():
    p = argparse.ArgumentParser("tpu-validator")
    p.add_argument(
        "--component",
        "-c",
        required=True,
        choices=COMPONENTS,
        help="which layer to validate",
    )
    p.add_argument(
        "--output-dir",
        default=os.environ.get("VALIDATION_OUTPUT_DIR", consts.VALIDATION_DIR),
    )
    p.add_argument(
        "--with-wait",
        action="store_true",
        default=os.environ.get("WITH_WAIT", "") == "true",
        help="block on the previous barrier's status file first",
    )
    p.add_argument(
        "--with-workload",
        action="store_true",
        default=os.environ.get("WITH_WORKLOAD", "") == "true",
        help="spawn a workload pod instead of validating in-process",
    )
    p.add_argument("--node-name", default=os.environ.get("NODE_NAME", ""))
    p.add_argument(
        "--namespace",
        default=os.environ.get(consts.OPERATOR_NAMESPACE_ENV, ""),
    )
    p.add_argument(
        "--libtpu-install-dir",
        default=os.environ.get("LIBTPU_INSTALL_DIR", consts.LIBTPU_HOST_DIR),
    )
    p.add_argument(
        "--cdi-spec",
        default=os.environ.get("CDI_SPEC_PATH", "/var/run/cdi/google.com-tpu.yaml"),
    )
    p.add_argument("--dev-root", default="/dev")
    p.add_argument("--sysfs", default="/sys/bus/pci/devices")
    p.add_argument(
        "--vm-state-file",
        default=os.environ.get("VM_DEVICE_STATE_FILE", "/run/tpu/vm-devices.json"),
    )
    p.add_argument("--metrics-port", type=int, default=8000)
    p.add_argument("--matmul-size", type=int, default=4096)
    p.add_argument(
        "--ringattn-seq-len",
        type=int,
        default=_env_int("RINGATTN_SEQ_LEN", 2048),
        help="total sequence length for the context-parallel probe",
    )
    p.add_argument(
        "--flashattn-seq",
        type=int,
        # the TUNED operating point (block sweep, docs/flashattn-
        # roofline.md) — the default must measure the shape that ships,
        # not a toy one (2048/4 read 4x under the real kernel rate)
        default=_env_int("FLASHATTN_SEQ", 8192),
        help="flash-attention probe sequence length (shrink for CPU/dev)",
    )
    p.add_argument(
        "--flashattn-heads",
        type=int,
        default=_env_int("FLASHATTN_HEADS", 8),
        help="flash-attention probe head count",
    )
    p.add_argument(
        "--membw-min-utilization",
        type=float,
        default=_env_float("MEMBW_MIN_UTILIZATION", 0.5),
        help="fail membw validation below this fraction of spec HBM bandwidth",
    )
    p.add_argument(
        "--membw-size-mb",
        type=int,
        default=_env_int("MEMBW_SIZE_MB", 0),
        help="probe buffer MiB (0 = auto: 2048 on TPU, tiny off-TPU)",
    )
    p.add_argument(
        "--expect-devices",
        type=int,
        default=_env_int("EXPECT_TPU_DEVICES", 0) or None,
    )
    p.add_argument(
        "--allow-cpu",
        action="store_true",
        help="dev mode: accept a non-TPU JAX platform for --component jax",
    )
    return p


def make_client():
    from tpu_operator.kube.rest import RestClient

    return RestClient()


def _client_or_none(log):
    """Sandbox components degrade to label-gate-less validation when no
    in-cluster API is reachable (dev runs outside a pod)."""
    try:
        return make_client()
    except Exception:
        log.warning("no in-cluster client; workload-config gate disabled")
        return None


def main(argv=None) -> int:
    logging.basicConfig(
        level="INFO", format="%(asctime)s %(name)s %(levelname)s %(message)s"
    )
    log = logging.getLogger("tpu-validator")
    args = build_parser().parse_args(argv)
    status = StatusFiles(args.output_dir)

    try:
        if args.component == "libtpu":
            info = comp.validate_libtpu(
                status,
                install_dir=args.libtpu_install_dir,
                dev_root=args.dev_root,
                with_wait=args.with_wait,
            )
        elif args.component == "runtime":
            info = comp.validate_runtime(
                status, cdi_spec_path=args.cdi_spec, with_wait=args.with_wait
            )
        elif args.component == "plugin":
            info = comp.validate_plugin(
                status,
                make_client(),
                args.node_name,
                with_wait=args.with_wait,
                with_workload=args.with_workload,
                namespace=args.namespace,
            )
        elif args.component == "jax":
            client = make_client() if args.with_workload else None
            info = comp.validate_jax(
                status,
                client=client,
                node_name=args.node_name,
                namespace=args.namespace,
                with_workload=args.with_workload,
                expect_tpu=not args.allow_cpu,
                size=args.matmul_size,
            )
        elif args.component == "slice":
            info = comp.validate_slice(
                status, expect_devices=args.expect_devices
            )
        elif args.component == "slice-workload":
            info = comp.validate_slice_workload(
                status,
                make_client(),
                args.node_name,
                namespace=args.namespace,
            )
        elif args.component == "ici":
            info = comp.validate_ici(
                status, expect_devices=args.expect_devices
            )
        elif args.component == "ringattn":
            info = comp.validate_ringattn(
                status,
                expect_devices=args.expect_devices,
                seq_len=args.ringattn_seq_len,
            )
        elif args.component == "pipeline":
            info = comp.validate_pipeline(
                status, expect_devices=args.expect_devices
            )
        elif args.component == "moe":
            info = comp.validate_moe(
                status, expect_devices=args.expect_devices
            )
        elif args.component == "flashattn":
            info = comp.validate_flashattn(
                status,
                seq=args.flashattn_seq,
                heads=args.flashattn_heads,
                expect_tpu=not args.allow_cpu,
            )
        elif args.component == "membw":
            info = comp.validate_membw(
                status,
                expect_tpu=not args.allow_cpu,
                min_utilization=args.membw_min_utilization,
                size_mb=args.membw_size_mb,
            )
        elif args.component == "vfio-pci":
            info = comp.validate_vfio_pci(
                status,
                sysfs=args.sysfs,
                client=_client_or_none(log),
                node_name=args.node_name,
            )
        elif args.component == "vm-manager":
            info = comp.validate_vm_manager(
                status,
                client=_client_or_none(log),
                node_name=args.node_name,
                dev_root=args.dev_root,
            )
        elif args.component == "vm-devices":
            info = comp.validate_vm_devices(
                status,
                client=_client_or_none(log),
                node_name=args.node_name,
                dev_root=args.dev_root,
                state_file=args.vm_state_file,
            )
        elif args.component == "nodestatus":
            from tpu_operator.validator.metrics import NodeMetrics

            client = None
            try:
                client = make_client()
            except Exception:
                log.warning("no in-cluster client; capacity gauge disabled")
            NodeMetrics(
                client=client,
                node_name=args.node_name,
                status=status,
                port=args.metrics_port,
                install_dir=args.libtpu_install_dir,
                dev_root=args.dev_root,
            ).run()
            return 0
        else:  # pragma: no cover
            raise ValidationError(f"unknown component {args.component}")
        log.info("%s validation OK: %s", args.component, json.dumps(info)[:400])
        return 0
    except ValidationError as e:
        log.error("%s validation FAILED: %s", args.component, e)
        return 1


if __name__ == "__main__":
    sys.exit(main())
