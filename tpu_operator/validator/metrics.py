"""Validator metrics mode — the node-status exporter.

Analogue of ``validator/metrics.go``: a Prometheus endpoint exporting
per-node readiness gauges by watching the status files (30 s), re-running
the libtpu validation (60 s), counting device-plugin resources (30 s) and
counting TPU PCI devices (60 s) (``validator/metrics.go:159-301``).
"""

from __future__ import annotations

import logging
import os
import threading
import time

from tpu_operator import consts
from tpu_operator.validator.components import (
    StatusFiles,
    find_tpu_devices,
    node_tpu_capacity,
)

log = logging.getLogger("tpu-validator.metrics")

# one-release legacy-shape fallback noted once per process, not once per
# 30 s watch tick
_legacy_payload_logged = False


def payload_perf(payload) -> dict:
    """Canonical read of a validation status payload's performance
    fields. The CANONICAL schema is FLAT: ``{"tflops": x, ...}`` for the
    jax payload (``workloads/matmul.py`` ``to_dict``) and
    ``{"gbps": y, ...}`` for the membw payload — every writer now emits
    it (``validator/components.py``). One release of fallback remains
    for the legacy nested ``{"result": {"tflops": ...}}`` shape some
    older workload-pod payloads carried, with a log-once so operators
    notice before the fallback is removed."""
    global _legacy_payload_logged
    if not isinstance(payload, dict):
        return {}
    out = {}
    for key in ("tflops", "gbps"):
        value = payload.get(key)
        if value is None:
            nested = payload.get("result")
            if isinstance(nested, dict) and nested.get(key) is not None:
                value = nested[key]
                if not _legacy_payload_logged:
                    _legacy_payload_logged = True
                    log.warning(
                        "validation payload uses the legacy nested "
                        "result.%s shape; emit the flat canonical schema "
                        "(top-level %s) — this fallback is removed next "
                        "release",
                        key,
                        key,
                    )
        if value is not None:
            try:
                out[key] = float(value)
            except (TypeError, ValueError):
                pass
    return out


class NodeMetrics:
    """reference ``NodeMetrics`` (``validator/metrics.go:52-70``)."""

    WATCH_STATUS_S = 30
    WATCH_PLUGIN_S = 30
    WATCH_LIBTPU_S = 60
    WATCH_PCI_S = 60

    def __init__(
        self,
        client=None,
        node_name: str = "",
        status: StatusFiles = None,
        port: int = 8000,
        install_dir: str = consts.LIBTPU_HOST_DIR,
        dev_root: str = "/dev",
        registry=None,
    ):
        from prometheus_client import Gauge

        self.client = client
        self.node_name = node_name
        self.status = status or StatusFiles()
        self.port = port
        self.install_dir = install_dir
        self.dev_root = dev_root
        self.registry = registry  # None -> default global registry
        self._stop = threading.Event()

        ns = "tpu_validator"
        kw = {"registry": registry} if registry is not None else {}
        mk = lambda name, doc: Gauge(f"{ns}_{name}", doc, ["node"], **kw)  # noqa: E731
        # per-status-file readiness (reference metric defs :73-157)
        self.g_libtpu = mk("libtpu_ready", "libtpu validation status file present")
        self.g_runtime = mk("runtime_ready", "runtime validation status file present")
        self.g_plugin = mk("plugin_ready", "plugin validation status file present")
        self.g_jax = mk("jax_ready", "jax validation status file present")
        self.g_libtpu_valid = mk(
            "libtpu_validation", "live libtpu re-validation result"
        )
        self.g_capacity = mk("tpu_capacity", "google.com/tpu in node capacity")
        self.g_devices = mk("tpu_devices", "TPU device files visible on host")
        self.g_jax_tflops = mk(
            "jax_matmul_tflops", "TFLOPS recorded by the last jax validation"
        )
        # one labeled series per diagnostic probe (slice/ici/ringattn/
        # pipeline/moe/membw) — 1 when its status file is present; probes
        # are opt-in, so 0 just means "not run on this node"
        self.g_probe = Gauge(
            f"{ns}_probe_ready",
            "diagnostic probe status file present",
            ["node", "probe"],
            **kw,
        )

    # ------------------------------------------------------------------
    def _watch_status_files(self):
        files = {
            consts.STATUS_FILE_LIBTPU: self.g_libtpu,
            consts.STATUS_FILE_RUNTIME: self.g_runtime,
            consts.STATUS_FILE_PLUGIN: self.g_plugin,
            consts.STATUS_FILE_JAX: self.g_jax,
        }
        while not self._stop.is_set():
            for name, gauge in files.items():
                gauge.labels(node=self.node_name).set(
                    1 if self.status.exists(name) else 0
                )
            for name in consts.PROBE_STATUS_FILES:
                probe = name.removesuffix("-ready")
                self.g_probe.labels(node=self.node_name, probe=probe).set(
                    1 if self.status.exists(name) else 0
                )
            # surface the recorded TFLOPS from the jax status payload
            # (canonical flat schema; payload_perf keeps the one-release
            # legacy-nested fallback with a log-once)
            perf = {}
            try:
                import json

                with open(self.status.path(consts.STATUS_FILE_JAX)) as f:
                    payload = json.load(f)
                perf.update(payload_perf(payload))
                if perf.get("tflops"):
                    self.g_jax_tflops.labels(node=self.node_name).set(
                        perf["tflops"]
                    )
            except Exception:
                pass
            try:
                import json

                with open(self.status.path("membw-ready")) as f:
                    perf.update(payload_perf(json.load(f)))
            except Exception:
                pass
            self._publish_perf_annotation(perf)
            self._stop.wait(self.WATCH_STATUS_S)

    def _publish_perf_annotation(self, perf: dict) -> None:
        """Publish the node's live validator perf readings as the
        ``tpu.k8s.io/validator-perf`` annotation — the evidence surface
        the rollout health gate (``controllers/rollout.py``) compares
        against its pre-roll baseline. The ``version`` field tags which
        libtpu produced the readings (the gate only compares readings
        taken AT the roll target): ``LIBTPU_VERSION`` env when the
        deployment injects it, else the node's own TFD version label —
        read inside the conflict-retried mutate so the tag always
        matches the node revision the write lands on. One GET per 30 s
        tick, a write only on change."""
        if self.client is None or not self.node_name or not perf:
            return
        import json

        from tpu_operator.kube.client import mutate_with_retry

        base = {k: round(v, 1) for k, v in sorted(perf.items())}
        env_version = os.environ.get("LIBTPU_VERSION", "")

        def mutate(node):
            doc = dict(base)
            labels = node["metadata"].get("labels", {}) or {}
            version = env_version or labels.get(
                consts.TFD_LIBTPU_VERSION_LABEL, ""
            )
            if version:
                doc["version"] = version
            desired = json.dumps(doc, sort_keys=True)
            ann = node["metadata"].setdefault("annotations", {})
            if ann.get(consts.VALIDATOR_PERF_ANNOTATION) == desired:
                return False
            ann[consts.VALIDATOR_PERF_ANNOTATION] = desired
            return True

        try:
            mutate_with_retry(
                self.client, "v1", "Node", self.node_name, mutate=mutate
            )
        except Exception:
            log.exception("validator-perf annotation publish failed")

    def _watch_libtpu(self):
        """Live re-validation: OPEN-probe every device, not just stat it.
        The reference re-executes `nvidia-smi` through the driver chroot
        (validator/metrics.go:237-250); a wedged chip whose device node
        still exists must flip this gauge to 0."""
        import glob
        import os

        from tpu_operator.native import tpuinfo

        while not self._stop.is_set():
            try:
                devs = find_tpu_devices(self.dev_root)
                # device_probe_path itself stats (never opens) /dev/vfio/*
                # groups — one open file per group is a kernel invariant
                ok = (
                    bool(devs)
                    and all(tpuinfo.device_probe_path(p) for p in devs)
                    and bool(
                        glob.glob(os.path.join(self.install_dir, "libtpu*.so"))
                    )
                )
            except Exception:
                # an unexpected probe failure must read as UNHEALTHY and
                # keep the watcher alive — a dead thread would freeze the
                # gauge at its last (possibly healthy) value forever
                log.exception("libtpu re-validation pass failed")
                ok = False
            self.g_libtpu_valid.labels(node=self.node_name).set(1 if ok else 0)
            self._stop.wait(self.WATCH_LIBTPU_S)

    def _watch_plugin_capacity(self):
        while not self._stop.is_set():
            if self.client is not None and self.node_name:
                try:
                    node = self.client.get("v1", "Node", self.node_name)
                    self.g_capacity.labels(node=self.node_name).set(
                        node_tpu_capacity(node)
                    )
                except Exception:
                    log.exception("capacity watch failed")
            self._stop.wait(self.WATCH_PLUGIN_S)

    def _watch_devices(self):
        while not self._stop.is_set():
            try:
                count = len(find_tpu_devices(self.dev_root))
            except Exception:
                log.exception("device count pass failed")
                count = 0  # fail towards unhealthy, keep the watcher alive
            self.g_devices.labels(node=self.node_name).set(count)
            self._stop.wait(self.WATCH_PCI_S)

    # ------------------------------------------------------------------
    def run(self, block: bool = True):
        """reference ``Run`` (``validator/metrics.go:304-320``)."""
        from prometheus_client import start_http_server

        if self.registry is not None:
            start_http_server(self.port, registry=self.registry)
        else:
            start_http_server(self.port)
        threads = [
            threading.Thread(target=self._watch_status_files, daemon=True),
            threading.Thread(target=self._watch_libtpu, daemon=True),
            threading.Thread(target=self._watch_plugin_capacity, daemon=True),
            threading.Thread(target=self._watch_devices, daemon=True),
        ]
        for t in threads:
            t.start()
        log.info("node-status exporter serving :%d/metrics", self.port)
        if block:
            while not self._stop.is_set():
                time.sleep(1)

    def stop(self):
        self._stop.set()
