"""Validation workload pods.

The reference spawns a ``cuda-vectoradd`` pod and polls it to Succeeded
(``validator/main.go:931-1015,1217-1293``; pod specs in
``validator/cuda-workload-validation.yaml`` /
``plugin-workload-validation.yaml``). TPU equivalents: a JAX matmul pod
(jax-validation) and a 1-chip ``jax.devices()`` smoke pod
(plugin-validation), owner-ref'd to the validator DaemonSet so cluster GC
reaps them (``validator/main.go:1017-1059``).
"""

from __future__ import annotations

import logging
import time

from tpu_operator import consts

log = logging.getLogger("tpu-validator")

POLL_RETRIES = 60  # reference validator/main.go:158-161
POLL_SLEEP_S = 5

JAX_MATMUL_SCRIPT = (
    "import jax, jax.numpy as jnp; "
    "devs = jax.devices(); assert devs and devs[0].platform == 'tpu', devs; "
    "a = jnp.ones((1024, 1024), jnp.bfloat16); "
    "out = jnp.dot(a, a, preferred_element_type=jnp.float32); "
    "out.block_until_ready(); "
    "assert float(out[0, 0]) == 1024.0, float(out[0, 0]); "
    "print('TPU matmul OK on', devs[0].device_kind)"
)

PLUGIN_SMOKE_SCRIPT = (
    "import jax; devs = jax.devices(); "
    "assert devs and devs[0].platform == 'tpu', devs; "
    "print(len(devs), 'TPU device(s) visible')"
)


def _workload_pod(
    name: str, node_name: str, namespace: str, script: str, image: str
) -> dict:
    import os

    # pull policy/secrets follow the validator's own (injected by
    # transform_validator; reference sets ValidatorImage*/PullSecrets env on
    # the cuda/plugin validation containers for the same spin-off purpose,
    # controllers/object_controls.go:1906-1912)
    pull_policy = os.environ.get("JAX_WORKLOAD_PULL_POLICY", "IfNotPresent")
    pull_secrets = [
        {"name": s}
        for s in os.environ.get("JAX_WORKLOAD_PULL_SECRETS", "").split(",")
        if s
    ]
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": name,
            "namespace": namespace,
            "labels": {"app": name},
        },
        "spec": {
            "restartPolicy": "Never",
            "nodeName": node_name,
            "runtimeClassName": None,  # filled by operator policy if needed
            "tolerations": [
                {
                    "key": consts.TPU_RESOURCE,
                    "operator": "Exists",
                    "effect": "NoSchedule",
                }
            ],
            "imagePullSecrets": pull_secrets,
            "containers": [
                {
                    "name": name,
                    "image": image,
                    "imagePullPolicy": pull_policy,
                    "command": ["python3", "-c", script],
                    "resources": {
                        "limits": {consts.TPU_RESOURCE: "1"},
                        "requests": {consts.TPU_RESOURCE: "1"},
                    },
                }
            ],
        },
    }


def _per_node_name(base: str, node_name: str) -> str:
    """Pod name unique PER NODE: every TPU node's validator spawns its own
    workload pod into the shared operator namespace, and a fixed name
    would make concurrent bring-up (a 16-host v5p pool) delete each
    other's in-flight pods. Sanitized + length-bounded (DNS-1123), with a
    short hash so truncation cannot collide."""
    import hashlib
    import re

    safe = re.sub(r"[^a-z0-9-]", "-", node_name.lower()).strip("-")
    suffix = hashlib.sha1(node_name.encode()).hexdigest()[:5]
    # the name doubles as the pod's `app` label value: stay under the
    # 63-char label limit (longest base 20 + 1 + 30 + 1 + 5 = 57)
    return f"{base}-{safe[:30].rstrip('-')}-{suffix}"


def jax_workload_pod(
    node_name: str, namespace: str, image: str = ""
) -> dict:
    import os

    image = image or os.environ.get(
        "JAX_WORKLOAD_IMAGE", consts.DEFAULT_JAX_WORKLOAD_IMAGE
    )
    return _workload_pod(
        _per_node_name("tpu-jax-validator", node_name),
        node_name,
        namespace,
        JAX_MATMUL_SCRIPT,
        image,
    )


def plugin_workload_pod(
    node_name: str, namespace: str, image: str = ""
) -> dict:
    import os

    image = image or os.environ.get(
        "JAX_WORKLOAD_IMAGE", consts.DEFAULT_JAX_WORKLOAD_IMAGE
    )
    return _workload_pod(
        _per_node_name("tpu-plugin-validator", node_name),
        node_name,
        namespace,
        PLUGIN_SMOKE_SCRIPT,
        image,
    )


def set_owner_daemonset(client, pod: dict, namespace: str, app: str) -> None:
    """Owner the workload pod to the validator DaemonSet so it's GC'd with
    it (reference ``:1017-1035``)."""
    ds = client.get_or_none("apps/v1", "DaemonSet", app, namespace)
    if ds is None:
        return
    meta = ds["metadata"]
    pod["metadata"]["ownerReferences"] = [
        {
            "apiVersion": "apps/v1",
            "kind": "DaemonSet",
            "name": meta["name"],
            "uid": meta.get("uid", ""),
            "controller": True,
        }
    ]


def run_to_completion(
    client,
    pod: dict,
    retries: int = POLL_RETRIES,
    sleep_s: float = POLL_SLEEP_S,
) -> str:
    """Create (recreating any stale instance) and poll to Succeeded
    (reference ``:1042-1059``)."""
    meta = pod["metadata"]
    ns, name = meta["namespace"], meta["name"]
    client.delete_if_exists("v1", "Pod", name, ns)
    # pre-per-node-naming leftovers: a stuck pod from an older operator
    # still holds its chip request and would starve the new pod forever
    for legacy in ("tpu-jax-validator", "tpu-plugin-validator"):
        if name != legacy and name.startswith(legacy + "-"):
            client.delete_if_exists("v1", "Pod", legacy, ns)
    set_owner_daemonset(client, pod, ns, "tpu-operator-validator")
    client.create(pod)
    for _ in range(retries):
        live = client.get_or_none("v1", "Pod", name, ns)
        phase = (live or {}).get("status", {}).get("phase", "")
        if phase == "Succeeded":
            return phase
        if phase == "Failed":
            raise RuntimeError(f"workload pod {ns}/{name} failed")
        time.sleep(sleep_s)
    raise RuntimeError(f"workload pod {ns}/{name} did not complete")
