"""Validation workload pods.

The reference spawns a ``cuda-vectoradd`` pod and polls it to Succeeded
(``validator/main.go:931-1015,1217-1293``; pod specs in
``validator/cuda-workload-validation.yaml`` /
``plugin-workload-validation.yaml``). TPU equivalents: a JAX matmul pod
(jax-validation) and a 1-chip ``jax.devices()`` smoke pod
(plugin-validation), owner-ref'd to the validator DaemonSet so cluster GC
reaps them (``validator/main.go:1017-1059``).
"""

from __future__ import annotations

import logging
import time
from typing import Optional

from tpu_operator import consts

log = logging.getLogger("tpu-validator")

POLL_RETRIES = 60  # reference validator/main.go:158-161
POLL_SLEEP_S = 5

JAX_MATMUL_SCRIPT = (
    "import jax, jax.numpy as jnp; "
    "devs = jax.devices(); assert devs and devs[0].platform == 'tpu', devs; "
    "a = jnp.ones((1024, 1024), jnp.bfloat16); "
    "out = jnp.dot(a, a, preferred_element_type=jnp.float32); "
    "out.block_until_ready(); "
    "assert float(out[0, 0]) == 1024.0, float(out[0, 0]); "
    "print('TPU matmul OK on', devs[0].device_kind)"
)

PLUGIN_SMOKE_SCRIPT = (
    "import jax; devs = jax.devices(); "
    "assert devs and devs[0].platform == 'tpu', devs; "
    "print(len(devs), 'TPU device(s) visible')"
)


def _workload_pod(
    name: str, node_name: str, namespace: str, script: str, image: str
) -> dict:
    import os

    # pull policy/secrets follow the validator's own (injected by
    # transform_validator; reference sets ValidatorImage*/PullSecrets env on
    # the cuda/plugin validation containers for the same spin-off purpose,
    # controllers/object_controls.go:1906-1912)
    pull_policy = os.environ.get("JAX_WORKLOAD_PULL_POLICY", "IfNotPresent")
    pull_secrets = [
        {"name": s}
        for s in os.environ.get("JAX_WORKLOAD_PULL_SECRETS", "").split(",")
        if s
    ]
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": name,
            "namespace": namespace,
            "labels": {"app": name},
        },
        "spec": {
            "restartPolicy": "Never",
            "nodeName": node_name,
            "runtimeClassName": None,  # filled by operator policy if needed
            "tolerations": [
                {
                    "key": consts.TPU_RESOURCE,
                    "operator": "Exists",
                    "effect": "NoSchedule",
                }
            ],
            "imagePullSecrets": pull_secrets,
            "containers": [
                {
                    "name": name,
                    "image": image,
                    "imagePullPolicy": pull_policy,
                    "command": ["python3", "-c", script],
                    "resources": {
                        "limits": {consts.TPU_RESOURCE: "1"},
                        "requests": {consts.TPU_RESOURCE: "1"},
                    },
                }
            ],
        },
    }


def _per_node_name(base: str, node_name: str) -> str:
    """Pod name unique PER NODE: every TPU node's validator spawns its own
    workload pod into the shared operator namespace, and a fixed name
    would make concurrent bring-up (a 16-host v5p pool) delete each
    other's in-flight pods. Sanitized + length-bounded (DNS-1123), with a
    short hash so truncation cannot collide."""
    import hashlib
    import re

    safe = re.sub(r"[^a-z0-9-]", "-", node_name.lower()).strip("-")
    suffix = hashlib.sha1(node_name.encode()).hexdigest()[:5]
    # the name doubles as the pod's `app` label value: stay under the
    # 63-char label limit (longest base 20 + 1 + 30 + 1 + 5 = 57)
    return f"{base}-{safe[:30].rstrip('-')}-{suffix}"


def jax_workload_pod(
    node_name: str, namespace: str, image: str = ""
) -> dict:
    import os

    image = image or os.environ.get(
        "JAX_WORKLOAD_IMAGE", consts.DEFAULT_JAX_WORKLOAD_IMAGE
    )
    return _workload_pod(
        _per_node_name("tpu-jax-validator", node_name),
        node_name,
        namespace,
        JAX_MATMUL_SCRIPT,
        image,
    )


def plugin_workload_pod(
    node_name: str, namespace: str, image: str = ""
) -> dict:
    import os

    image = image or os.environ.get(
        "JAX_WORKLOAD_IMAGE", consts.DEFAULT_JAX_WORKLOAD_IMAGE
    )
    return _workload_pod(
        _per_node_name("tpu-plugin-validator", node_name),
        node_name,
        namespace,
        PLUGIN_SMOKE_SCRIPT,
        image,
    )


# Coordinated multi-host startup proof: every gang member initializes the
# JAX distributed runtime off the injected coordination env and allgathers
# across processes — one host failing to join hangs/fails EVERY member,
# which is exactly the acceptance semantics of a multi-host slice
# (reference validator/main.go:931-1015 at gang scale).
SLICE_GANG_SCRIPT = (
    "import os, jax; "
    "jax.distributed.initialize(); "
    "import jax.numpy as jnp; "
    "from jax.experimental.multihost_utils import process_allgather; "
    "g = process_allgather(jnp.ones((4,))); "
    "want = int(os.environ.get('TPU_SLICE_HOSTS', '1')); "
    "assert jax.process_count() == want, (jax.process_count(), want); "
    "print('slice gang OK:', jax.process_index(), '/', jax.process_count())"
)

GANG_PORT = 8476  # the JAX coordination-service port

# epoch label: ties a gang to the validator DaemonSet revision that
# spawned it, so a follower cannot converge on a STALE gang's Succeeded
# pods from before a re-roll (the leader is about to replace them)
GANG_EPOCH_LABEL = f"{consts.GROUP}/gang-epoch"


def gang_epoch(client, namespace: str) -> str:
    """Current gang epoch: validator DS uid+generation ('' degrades the
    check away when the DS does not exist, e.g. bare CLI runs)."""
    ds = client.get_or_none(
        "apps/v1", "DaemonSet", "tpu-operator-validator", namespace
    )
    if ds is None:
        return ""
    meta = ds.get("metadata", {})
    return f"{(meta.get('uid') or 'x')[:8]}-{meta.get('generation', 0)}"


def gang_name(slice_id: str) -> str:
    return _per_node_name("tpu-slice-gang", slice_id)


def gang_service(slice_id: str, namespace: str) -> dict:
    """Headless Service giving gang pods stable DNS (the coordinator
    address must resolve before any pod has an IP)."""
    name = gang_name(slice_id)
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {
            "clusterIP": "None",
            "selector": {"app": name},
            "ports": [{"name": "coordinator", "port": GANG_PORT}],
        },
    }


def slice_gang_pod(
    slice_id: str,
    node_name: str,
    namespace: str,
    ordinal: int,
    num_hosts: int,
    chips: str = "1",
    image: str = "",
    extra_env: Optional[dict] = None,
) -> dict:
    """One gang member pod, GATED on ``tpu.slice.ready`` via nodeSelector
    (the scheduler refuses the pod while the slice verdict is false —
    the label bus is the gate, same as user multi-host jobs), pinned to
    its member host by hostname, with worker ordinal + coordinator env
    injected (the MEGASCALE pattern ``plugin/server.py::slice_env_from_node_labels``)."""
    import os

    name = gang_name(slice_id)
    image = image or os.environ.get(
        "JAX_WORKLOAD_IMAGE", consts.DEFAULT_JAX_WORKLOAD_IMAGE
    )
    hostnames = ",".join(
        f"{name}-{i}.{name}.{namespace}" for i in range(num_hosts)
    )
    env = {
        "TPU_WORKER_ID": str(ordinal),
        "TPU_SLICE_HOSTS": str(num_hosts),
        "TPU_WORKER_HOSTNAMES": hostnames,
        "MEGASCALE_COORDINATOR_ADDRESS": (
            f"{name}-0.{name}.{namespace}:{GANG_PORT}"
        ),
        # jax.distributed.initialize() picks these up directly
        "JAX_COORDINATOR_ADDRESS": f"{name}-0.{name}.{namespace}:{GANG_PORT}",
        "JAX_NUM_PROCESSES": str(num_hosts),
        "JAX_PROCESS_ID": str(ordinal),
    }
    env.update(extra_env or {})
    pod = _workload_pod(
        f"{name}-{ordinal}", node_name, namespace, SLICE_GANG_SCRIPT, image
    )
    pod["metadata"]["labels"]["app"] = name
    spec = pod["spec"]
    # the slice-ready GATE: schedule via selector, not nodeName — a
    # nodeName pin would bypass the scheduler and with it the gate
    del spec["nodeName"]
    spec["nodeSelector"] = {
        "kubernetes.io/hostname": node_name,
        consts.SLICE_READY_LABEL: "true",
    }
    spec["hostname"] = f"{name}-{ordinal}"
    spec["subdomain"] = name
    ctr = spec["containers"][0]
    ctr["name"] = "gang"
    ctr["env"] = [{"name": k, "value": v} for k, v in sorted(env.items())]
    ctr["resources"] = {
        "limits": {consts.TPU_RESOURCE: chips},
        "requests": {consts.TPU_RESOURCE: chips},
    }
    return pod


def run_slice_gang(
    client,
    namespace: str,
    slice_id: str,
    members,
    spawn: bool = True,
    image: str = "",
    retries: int = POLL_RETRIES,
    sleep_s: float = POLL_SLEEP_S,
) -> dict:
    """Spawn (leader) or observe (followers) one gang pod per member
    host and wait for ALL to succeed. ``members`` is the ordered list of
    ``(node_name, chips)`` pairs; failure names every host whose pod did
    not make it — a member that cannot schedule is named with its phase
    so the operator can see WHICH host holds the slice back."""
    name = gang_name(slice_id)
    epoch = gang_epoch(client, namespace)
    pods = [
        slice_gang_pod(
            slice_id,
            node,
            namespace,
            ordinal,
            len(members),
            chips=chips,
            image=image,
        )
        for ordinal, (node, chips) in enumerate(members)
    ]
    host_of = {p["metadata"]["name"]: p["spec"]["nodeSelector"][
        "kubernetes.io/hostname"
    ] for p in pods}
    if epoch:
        for pod in pods:
            pod["metadata"]["labels"][GANG_EPOCH_LABEL] = epoch
    if spawn:
        svc = gang_service(slice_id, namespace)
        set_owner_daemonset(client, svc, namespace, "tpu-operator-validator")
        client.delete_if_exists("v1", "Service", name, namespace)
        client.create(svc)
        for pod in pods:
            client.delete_if_exists(
                "v1", "Pod", pod["metadata"]["name"], namespace
            )
            set_owner_daemonset(client, pod, namespace, "tpu-operator-validator")
            client.create(pod)
    phases: dict = {}
    for _ in range(retries):
        phases = {}
        for pod in pods:
            live = client.get_or_none(
                "v1", "Pod", pod["metadata"]["name"], namespace
            )
            if live is None:
                phases[pod["metadata"]["name"]] = (
                    "Missing" if spawn else "NotCreated"
                )
                continue
            live_epoch = (
                live["metadata"].get("labels", {}) or {}
            ).get(GANG_EPOCH_LABEL, "")
            if epoch and live_epoch != epoch:
                # a previous epoch's gang (validator re-rolled since):
                # its Succeeded means nothing now — a follower must wait
                # for the leader to respawn the current epoch, not pass
                # against history
                phases[pod["metadata"]["name"]] = "StaleEpoch"
                continue
            phase = live.get("status", {}).get("phase", "Pending")
            if phase == "Pending" and not live.get("spec", {}).get("nodeName"):
                phase = "Unschedulable"
            phases[pod["metadata"]["name"]] = phase
        if all(p == "Succeeded" for p in phases.values()):
            return {
                "slice": slice_id,
                "hosts": [n for n, _ in members],
                "gang": name,
                "result": "Succeeded",
            }
        if any(p == "Failed" for p in phases.values()):
            break
        time.sleep(sleep_s)
    notes = {
        "Unschedulable": " (slice gate tpu.slice.ready or cordon is refusing it)",
        "StaleEpoch": " (previous-epoch gang; leader respawn pending)",
    }
    stragglers = "; ".join(
        f"member host {host_of[pname]}: pod {pname} {phase}"
        + notes.get(phase, "")
        for pname, phase in sorted(phases.items())
        if phase != "Succeeded"
    )
    raise RuntimeError(
        f"slice {slice_id} gang validation did not complete: {stragglers}"
    )


def set_owner_daemonset(client, pod: dict, namespace: str, app: str) -> None:
    """Owner the workload pod to the validator DaemonSet so it's GC'd with
    it (reference ``:1017-1035``)."""
    ds = client.get_or_none("apps/v1", "DaemonSet", app, namespace)
    if ds is None:
        return
    meta = ds["metadata"]
    pod["metadata"]["ownerReferences"] = [
        {
            "apiVersion": "apps/v1",
            "kind": "DaemonSet",
            "name": meta["name"],
            "uid": meta.get("uid", ""),
            "controller": True,
        }
    ]


def run_to_completion(
    client,
    pod: dict,
    retries: int = POLL_RETRIES,
    sleep_s: float = POLL_SLEEP_S,
) -> str:
    """Create (recreating any stale instance) and poll to Succeeded
    (reference ``:1042-1059``)."""
    meta = pod["metadata"]
    ns, name = meta["namespace"], meta["name"]
    client.delete_if_exists("v1", "Pod", name, ns)
    # pre-per-node-naming leftovers: a stuck pod from an older operator
    # still holds its chip request and would starve the new pod forever
    for legacy in ("tpu-jax-validator", "tpu-plugin-validator"):
        if name != legacy and name.startswith(legacy + "-"):
            client.delete_if_exists("v1", "Pod", legacy, ns)
    set_owner_daemonset(client, pod, ns, "tpu-operator-validator")
    client.create(pod)
    for _ in range(retries):
        live = client.get_or_none("v1", "Pod", name, ns)
        phase = (live or {}).get("status", {}).get("phase", "")
        if phase == "Succeeded":
            return phase
        if phase == "Failed":
            raise RuntimeError(f"workload pod {ns}/{name} failed")
        time.sleep(sleep_s)
    raise RuntimeError(f"workload pod {ns}/{name} did not complete")
