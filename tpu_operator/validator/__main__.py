import sys

from tpu_operator.validator.main import main

sys.exit(main())
