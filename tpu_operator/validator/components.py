"""Validator components.

TPU-native analogue of ``validator/main.go``'s component switch
(``:439-545``): each component checks one layer of the stack and drops a
status file into ``/run/tpu/validations`` — the host-local barrier that
sequences the operand DaemonSets (``validator/main.go:123-157``).

Component map (reference → TPU):
  driver  → libtpu   (/dev/accel* or vfio devices + libtpu.so present)
  toolkit → runtime  (CDI spec generated / device wiring present)
  plugin  → plugin   (node capacity advertises google.com/tpu; optional
                      1-chip workload pod)
  cuda    → jax      (JAX matmul pod / in-process matmul with TFLOPS)
  mofed   → (absent: no NIC fabric module on TPU; ICI needs no host driver)
  vfio-pci→ vfio-pci (TPU PCI functions bound to vfio-pci)
"""

from __future__ import annotations

import glob
import json
import logging
import os
import time
from typing import Optional

from tpu_operator import consts

log = logging.getLogger("tpu-validator")

WAIT_RETRIES = 60  # reference validator/main.go:158-161 (60x5s)
WAIT_SLEEP_S = 5
PLUGIN_RETRIES = 30  # reference :162-165 (30x5s)


class ValidationError(RuntimeError):
    pass


# ---------------------------------------------------------------------------
# status files (reference validator/main.go:123-157,710-741)
# ---------------------------------------------------------------------------


class StatusFiles:
    def __init__(self, output_dir: str = consts.VALIDATION_DIR):
        self.dir = output_dir

    def path(self, name: str) -> str:
        return os.path.join(self.dir, name)

    def write(self, name: str, payload: Optional[dict] = None) -> None:
        os.makedirs(self.dir, exist_ok=True)
        with open(self.path(name), "w") as f:
            if payload is not None:
                json.dump(payload, f)

    def remove(self, name: str) -> None:
        try:
            os.unlink(self.path(name))
        except FileNotFoundError:
            pass

    def exists(self, name: str) -> bool:
        return os.path.exists(self.path(name))

    def wait_for(self, name: str, retries: int = WAIT_RETRIES) -> None:
        for _ in range(retries):
            if self.exists(name):
                return
            log.info("waiting for %s", self.path(name))
            time.sleep(WAIT_SLEEP_S)
        raise ValidationError(f"timed out waiting for {self.path(name)}")


# ---------------------------------------------------------------------------
# libtpu component (driver slot: reference validator/main.go:607-679)
# ---------------------------------------------------------------------------


def find_tpu_devices(dev_root: str = "/dev") -> list:
    """TPU chips appear as /dev/accel* (PCIe DMA path) or as /dev/vfio/*
    groups on VM-passthrough hosts."""
    accel = sorted(glob.glob(os.path.join(dev_root, "accel*")))
    if accel:
        return accel
    vfio = [
        p
        for p in sorted(glob.glob(os.path.join(dev_root, "vfio", "*")))
        if os.path.basename(p) != "vfio"
    ]
    return vfio


def validate_libtpu(
    status: StatusFiles,
    install_dir: str = consts.LIBTPU_HOST_DIR,
    dev_root: str = "/dev",
    with_wait: bool = False,
) -> dict:
    """Devices visible + libtpu.so installed (chroot-nvidia-smi analogue).

    Falls back to the native probe (``tpu-smoke`` via libtpuinfo) when
    available for a richer chip table.
    """
    if with_wait:
        status.wait_for(consts.STATUS_FILE_LIBTPU_CTR)
    devices = find_tpu_devices(dev_root)
    if not devices:
        raise ValidationError(f"no TPU devices under {dev_root} (accel*/vfio)")
    lib = os.path.join(install_dir, "libtpu.so")
    versioned = sorted(glob.glob(os.path.join(install_dir, "libtpu*.so")))
    if not os.path.exists(lib) and not versioned:
        raise ValidationError(f"libtpu.so not found under {install_dir}")
    info = {"devices": devices, "libtpu": lib if os.path.exists(lib) else versioned}
    from tpu_operator.operands import devchar

    if os.environ.get(devchar.DISABLE_ENV) != "true":
        # systemd cgroup device-filter workaround (reference
        # createDevCharSymlinks, validator/main.go:681-708)
        created = devchar.create_dev_char_symlinks(dev_root)
        if created:
            info["devCharSymlinks"] = len(created)
    try:
        from tpu_operator.native import tpuinfo

        chips = tpuinfo.chip_summary()
        if chips:
            info["chips"] = chips
    except Exception:
        pass
    status.write(consts.STATUS_FILE_LIBTPU, info)
    return info


# ---------------------------------------------------------------------------
# runtime component (toolkit slot: reference validator/main.go:775-801)
# ---------------------------------------------------------------------------


def validate_runtime(
    status: StatusFiles,
    cdi_spec_path: str = "/var/run/cdi/google.com-tpu.yaml",
    with_wait: bool = False,
) -> dict:
    """Device wiring present: the CDI spec exists and names every chip."""
    if with_wait:
        status.wait_for(consts.STATUS_FILE_LIBTPU)
    if not os.path.exists(cdi_spec_path):
        raise ValidationError(f"CDI spec missing at {cdi_spec_path}")
    import yaml

    with open(cdi_spec_path) as f:
        spec = yaml.safe_load(f) or {}
    devices = spec.get("devices", [])
    if not devices:
        raise ValidationError(f"CDI spec at {cdi_spec_path} lists no devices")
    info = {"cdiSpec": cdi_spec_path, "devices": [d.get("name") for d in devices]}
    status.write(consts.STATUS_FILE_RUNTIME, info)
    return info


# ---------------------------------------------------------------------------
# plugin component (reference validator/main.go:931-1161)
# ---------------------------------------------------------------------------


def node_tpu_capacity(node: dict, field: str = "capacity") -> int:
    cap = node.get("status", {}).get(field, {}) or {}
    total = 0
    for key, val in cap.items():
        if key == consts.TPU_RESOURCE or key.startswith(
            consts.TPU_SUBSLICE_RESOURCE_PREFIX
        ):
            try:
                total += int(val)
            except (TypeError, ValueError):
                pass
    return total


def node_tpu_allocatable(node: dict) -> int:
    """Healthy (schedulable) chips: the kubelet's device manager writes
    ``allocatable = capacity - unhealthy``, so a node whose chips all
    failed their open-probe advertises capacity N / allocatable 0 — a
    distinction the capacity-only reference check can't see."""
    status = node.get("status", {})
    if not status.get("allocatable"):
        # no kubelet allocatable accounting (older sims): fall back
        return node_tpu_capacity(node)
    return node_tpu_capacity(node, field="allocatable")


def validate_plugin(
    status: StatusFiles,
    client,
    node_name: str,
    with_wait: bool = False,
    with_workload: bool = False,
    namespace: str = "",
    retries: int = PLUGIN_RETRIES,
    sleep_s: float = WAIT_SLEEP_S,
) -> dict:
    """Node capacity advertises TPU chips (reference ``:1083-1161``) AND
    at least one is allocatable (healthy per the device manager — an
    all-chips-Unhealthy node passes the reference's capacity-only check
    but can never schedule), then optionally proves schedulability with a
    1-chip pod (``:931-1015``)."""
    if with_wait:
        status.wait_for(consts.STATUS_FILE_RUNTIME)
    count = allocatable = 0
    for attempt in range(retries):
        node = client.get("v1", "Node", node_name)
        count = node_tpu_capacity(node)
        allocatable = node_tpu_allocatable(node)
        if count > 0 and allocatable > 0:
            break
        log.info(
            "node %s reports no allocatable %s yet (attempt %d)",
            node_name,
            consts.TPU_RESOURCE,
            attempt,
        )
        time.sleep(sleep_s)
    if count <= 0:
        raise ValidationError(
            f"node {node_name} never advertised {consts.TPU_RESOURCE}"
        )
    if allocatable <= 0:
        raise ValidationError(
            f"node {node_name} advertises {count} {consts.TPU_RESOURCE} "
            "but none are allocatable (all chips Unhealthy)"
        )
    info = {"node": node_name, "capacity": count, "allocatable": allocatable}
    if with_workload:
        from tpu_operator.validator import workload_pods

        pod = workload_pods.plugin_workload_pod(node_name, namespace)
        workload_pods.run_to_completion(client, pod)
        info["workload"] = pod["metadata"]["name"]
    status.write(consts.STATUS_FILE_PLUGIN, info)
    return info


# ---------------------------------------------------------------------------
# jax component (cuda slot: reference validator/main.go:1217-1293)
# ---------------------------------------------------------------------------


def validate_jax(
    status: StatusFiles,
    client=None,
    node_name: str = "",
    namespace: str = "",
    with_workload: bool = False,
    expect_tpu: bool = True,
    size: int = 4096,
) -> dict:
    """End-to-end chip proof.

    ``with_workload`` spawns the JAX matmul pod (the vectorAdd-pod path,
    crossing the API server); otherwise the matmul runs in-process (the
    validator pod already has the chip mounted). Either way the status file
    records achieved TFLOPS — the operator's benchmark surface.
    """
    if with_workload:
        if client is None:
            raise ValidationError("jax workload validation needs a k8s client")
        from tpu_operator.validator import workload_pods

        pod = workload_pods.jax_workload_pod(node_name, namespace)
        result = workload_pods.run_to_completion(client, pod)
        # canonical FLAT payload schema: perf fields (tflops, ...) live
        # top-level when known (validator/metrics.py payload_perf reads
        # only that shape, with a one-release legacy-nested fallback).
        # The workload-pod path records the pod OUTCOME only — the
        # matmul numbers stay in the pod's own logs, so this payload
        # carries no perf fields and the exporter publishes none
        info = {"workload": pod["metadata"]["name"], "result": result}
    else:
        from tpu_operator.workloads.matmul import run_matmul_validation

        res = run_matmul_validation(size=size, expect_tpu=expect_tpu)
        if not res.ok:
            raise ValidationError(f"jax matmul failed: {res.error}")
        info = res.to_dict()
    status.write(consts.STATUS_FILE_JAX, info)
    return info


# ---------------------------------------------------------------------------
# slice component (burn-in across all local chips)
# ---------------------------------------------------------------------------


def validate_slice(
    status: StatusFiles, steps: int = 10, expect_devices: Optional[int] = None
) -> dict:
    """Multi-chip burn-in: sharded train step exercising every ICI axis."""
    from tpu_operator.workloads.burnin import run_burnin

    res = run_burnin(n_devices=expect_devices, steps=steps)
    if not res.ok:
        raise ValidationError(f"slice burn-in failed: {res.error or 'loss did not decrease'}")
    status.write(consts.STATUS_FILE_SLICE, res.to_dict())
    return res.to_dict()


# ---------------------------------------------------------------------------
# slice-workload component (N-pod gang acceptance across member hosts)
# ---------------------------------------------------------------------------


def validate_slice_workload(
    status: StatusFiles,
    client,
    node_name: str,
    namespace: str,
    retries: int = 60,
    sleep_s: float = 5.0,
) -> dict:
    """Coordinated multi-host acceptance: ONE pod per member host of this
    node's slice — gated on ``tpu.slice.ready``, worker ordinal +
    coordinator env injected — all N must succeed before the slice-scoped
    status file is written. The reference validates per node with a single
    workload pod (``/root/reference/validator/main.go:931-1015``); a
    multi-host slice's actual acceptance test is the gang.

    Worker 0 (lowest TFD worker-id, name-ordered fallback) spawns the
    gang; every other member WAITS on the same pods, so all N validators
    converge on one verdict instead of racing N gangs. Single-host slices
    degenerate to a gang of one."""
    if client is None:
        raise ValidationError("slice-workload validation needs a k8s client")
    from tpu_operator.controllers.slice_status import slice_id_for_node
    from tpu_operator.validator import workload_pods

    node = client.get("v1", "Node", node_name)
    sid = slice_id_for_node(node)
    members_nodes = [
        n
        for n in client.list("v1", "Node")
        if slice_id_for_node(n) == sid
    ]

    def ordinal(n):
        labels = n["metadata"].get("labels", {}) or {}
        wid = labels.get(consts.TFD_WORKER_ID_LABEL, "")
        try:
            return (0, int(wid), n["metadata"]["name"])
        except (TypeError, ValueError):
            return (1, 0, n["metadata"]["name"])

    members_nodes.sort(key=ordinal)
    members = []
    for n in members_nodes:
        chips = (n.get("status", {}).get("capacity", {}) or {}).get(
            consts.TPU_RESOURCE, "1"
        )
        members.append((n["metadata"]["name"], str(chips or "1")))
    if not members:
        raise ValidationError(
            f"node {node_name}: no member nodes found for slice {sid}"
        )
    leader = members[0][0] == node_name
    try:
        info = workload_pods.run_slice_gang(
            client,
            namespace,
            sid,
            members,
            spawn=leader,
            retries=retries,
            sleep_s=sleep_s,
        )
    except RuntimeError as e:
        raise ValidationError(str(e))
    info["role"] = "leader" if leader else "follower"
    status.write(consts.STATUS_FILE_SLICE_WORKLOAD, info)
    return info


# ---------------------------------------------------------------------------
# ici component (ring probe: per-link health + bandwidth)
# ---------------------------------------------------------------------------


def validate_ici(
    status: StatusFiles,
    expect_devices: Optional[int] = None,
    payload_mb: float = 4.0,
) -> dict:
    """Rotate a payload around the full device ring via ppermute; every
    shard must return bit-exact (isolates individual ICI links, unlike the
    aggregate burn-in)."""
    from tpu_operator.workloads.ring import run_ring_probe

    res = run_ring_probe(n_devices=expect_devices, payload_mb=payload_mb)
    if not res.ok:
        raise ValidationError(
            f"ICI ring probe failed: {res.error or 'integrity mismatch'}"
        )
    status.write("ici-ready", res.to_dict())
    return res.to_dict()


# ---------------------------------------------------------------------------
# ringattn component (context-parallel long-context probe)
# ---------------------------------------------------------------------------


def validate_ringattn(
    status: StatusFiles,
    expect_devices: Optional[int] = None,
    seq_len: int = 2048,
) -> dict:
    """Long-context readiness: blockwise ring attention over an ``sp`` mesh
    axis (K/V blocks rotated via ppermute, online-softmax accumulation),
    checked bit-for-bit-close against single-pass full attention. Proves
    the slice can run sequence/context-parallel workloads, which the
    aggregate burn-in's dp/tp collectives don't exercise."""
    from tpu_operator.workloads.ringattn import run_ringattn

    res = run_ringattn(n_devices=expect_devices, seq_len=seq_len)
    if not res.ok:
        raise ValidationError(
            f"ring-attention probe failed: {res.error or 'divergence'}"
        )
    status.write("ringattn-ready", res.to_dict())
    return res.to_dict()


# ---------------------------------------------------------------------------
# pipeline component (pipeline-parallel probe)
# ---------------------------------------------------------------------------


def validate_pipeline(
    status: StatusFiles, expect_devices: Optional[int] = None
) -> dict:
    """Pipeline-parallel readiness: GPipe-style microbatch pipeline (stage
    weights sharded over ``pp``, activations streamed stage-to-stage via
    ppermute inside one jitted scan), checked against sequential
    application of all stages on one device."""
    from tpu_operator.workloads.pipeline import run_pipeline

    res = run_pipeline(n_devices=expect_devices)
    if not res.ok:
        raise ValidationError(
            f"pipeline probe failed: {res.error or 'divergence'}"
        )
    status.write("pipeline-ready", res.to_dict())
    return res.to_dict()


# ---------------------------------------------------------------------------
# moe component (expert-parallel all_to_all probe)
# ---------------------------------------------------------------------------


def validate_moe(
    status: StatusFiles, expect_devices: Optional[int] = None
) -> dict:
    """Expert-parallel readiness: top-1-gated MoE layer with all_to_all
    token dispatch/combine (the only standard parallelism exercising the
    all-to-all ICI pattern), checked against dense per-token expert
    application; capacity overflow fails loudly."""
    from tpu_operator.workloads.moe import run_moe

    res = run_moe(n_devices=expect_devices)
    if not res.ok:
        raise ValidationError(f"moe probe failed: {res.error or 'divergence'}")
    status.write("moe-ready", res.to_dict())
    return res.to_dict()


def validate_flashattn(
    status: StatusFiles,
    seq: int = 2048,
    heads: int = 4,
    expect_tpu: bool = True,
) -> dict:
    """Single-chip pallas hot-op probe: blockwise flash attention with
    online softmax (running max + denominator in f32, bf16 MXU tiles),
    checked against naive full attention in f32. Proves the pallas
    kernel path end to end on this chip's VMEM/MXU — the long-context
    serving pattern XLA alone cannot fuse (measured ~150x over XLA's
    materialized-scores attention at seq 8192 on v5e)."""
    from tpu_operator.workloads.flashattn import run_flashattn_probe

    res = run_flashattn_probe(seq=seq, heads=heads, expect_tpu=expect_tpu)
    if not res.ok:
        raise ValidationError(
            f"flash-attention probe failed: {res.error or 'divergence'}"
        )
    status.write("flashattn-ready", res.to_dict())
    return res.to_dict()


# ---------------------------------------------------------------------------
# membw component (HBM bandwidth probe — DCGM-diagnostic analogue)
# ---------------------------------------------------------------------------


def validate_membw(
    status: StatusFiles,
    expect_tpu: bool = True,
    min_utilization: float = 0.5,
    size_mb: int = 0,
) -> dict:
    """Deep hardware diagnostic: achieved HBM streaming bandwidth via the
    pallas DMA memcpy + XLA stream probes (``workloads/membw.py``). A sick
    HBM stack shows a bandwidth cliff long before it corrupts training —
    the reference gets this from ``dcgmi diag`` memory-bandwidth runs."""
    from tpu_operator.workloads.membw import run_membw_probe

    if size_mb <= 0:
        # off-TPU the pallas kernel runs interpreted, Python-stepping the
        # grid — a 2 GiB buffer would take minutes; keep the debug path tiny
        size_mb = 2048 if expect_tpu else 8
    res = run_membw_probe(size_mb=size_mb, expect_tpu=expect_tpu)
    if not res.ok:
        raise ValidationError(f"membw probe failed: {res.error}")
    info = res.to_dict()
    if expect_tpu and res.utilization is None:
        # unknown chip generation: no spec number to gate against — record
        # loudly rather than silently passing a possibly-sick stack
        info["utilization_gate"] = "skipped: unknown generation"
        logging.getLogger("tpu-validator").warning(
            "membw: no HBM spec for device_kind=%r; %.0f GB/s NOT gated",
            res.device_kind,
            res.gbps,
        )
    elif expect_tpu and res.utilization < min_utilization:
        raise ValidationError(
            f"HBM bandwidth {res.gbps:.0f} GB/s is below "
            f"{min_utilization:.0%} of the {res.peak_gbps:.0f} GB/s spec "
            f"for {res.device_kind}"
        )
    status.write("membw-ready", info)
    return info


# ---------------------------------------------------------------------------
# vfio-pci component (reference validator/main.go:1301-1501, go-nvlib PCI)
# ---------------------------------------------------------------------------

GOOGLE_PCI_VENDOR = "0x1ae0"


def validate_vfio_pci(
    status: StatusFiles,
    sysfs: str = "/sys/bus/pci/devices",
    client=None,
    node_name: str = "",
) -> dict:
    """Every Google PCI accelerator function must be bound to vfio-pci.
    With a client, nodes not configured for vm-passthrough skip the check
    (reference ``VfioPCI.validate``, ``validator/main.go:1301-1340``)."""
    skipped = workload_config_gate(status, client, node_name)
    if skipped is not None:
        return skipped
    # clear any stale barrier first: on revalidation failure the
    # sandbox-device-plugin's wait gate must re-block rather than ride a
    # ready file from a previous (since-invalidated) pass
    status.remove("vfio-pci-ready")
    bound, unbound = [], []
    if not os.path.isdir(sysfs):
        raise ValidationError(f"no sysfs PCI tree at {sysfs}")
    for addr in sorted(os.listdir(sysfs)):
        vendor_path = os.path.join(sysfs, addr, "vendor")
        try:
            with open(vendor_path) as f:
                vendor = f.read().strip()
        except OSError:
            continue
        if vendor != GOOGLE_PCI_VENDOR:
            continue
        driver = os.path.join(sysfs, addr, "driver")
        target = os.path.basename(os.readlink(driver)) if os.path.islink(driver) else ""
        (bound if target == "vfio-pci" else unbound).append(addr)
    if unbound:
        raise ValidationError(f"TPU functions not bound to vfio-pci: {unbound}")
    if not bound:
        raise ValidationError("no Google PCI accelerator functions found")
    info = {"bound": bound}
    status.write("vfio-pci-ready", info)
    return info


# ---------------------------------------------------------------------------
# sandbox workload-config gate + vm-manager / vm-devices components
# (reference validator/main.go:1301-1501: each sandbox component reads the
# node's workload config, records it in a status file, and no-ops on nodes
# configured for a different workload)
# ---------------------------------------------------------------------------

WORKLOAD_TYPE_STATUS_FILE = "workload-type"


def workload_config_gate(
    status: StatusFiles, client, node_name: str
) -> Optional[dict]:
    """Record the node's workload config; return a skip-info dict when the
    node is not a vm-passthrough host (sandbox components then succeed as
    no-ops, reference ``VfioPCI.validate``/``VGPUManager.validate``)."""
    if client is None or not node_name:
        # no API access (dev run outside a pod): gate disabled, validate
        return None
    node = None
    err = None
    attempts = 3
    for i in range(attempts):
        # freshly-applied RBAC may still be propagating when the first
        # initContainer starts; transient API errors get a bounded retry and
        # then the structured failure path, not a raw traceback
        try:
            node = client.get("v1", "Node", node_name)
            break
        except Exception as e:  # noqa: BLE001 - any API failure retries
            err = e
            if i < attempts - 1:
                time.sleep(WAIT_SLEEP_S)
    if node is None:
        raise ValidationError(f"cannot read node {node_name}: {err}")
    # single owner of the label -> config mapping (validates values, warns
    # and coerces unknowns to "container")
    from tpu_operator.controllers.state_manager import node_workload_config

    cfg = node_workload_config(node)
    status.write(WORKLOAD_TYPE_STATUS_FILE, {"config": cfg})
    if cfg != consts.WORKLOAD_VM_PASSTHROUGH:
        log.info("workload config %r: sandbox validation not required", cfg)
        return {"skipped": True, "workload_config": cfg}
    return None


def validate_vm_manager(
    status: StatusFiles,
    client=None,
    node_name: str = "",
    dev_root: str = "/dev",
) -> dict:
    """The vm-manager operand prepared a usable passthrough host: vfio
    control node present plus at least one IOMMU group (reference
    vgpu-manager validation, ``validator/main.go:1359-1445``)."""
    skipped = workload_config_gate(status, client, node_name)
    if skipped is not None:
        return skipped
    status.remove("vm-manager-ready")
    control = os.path.join(dev_root, "vfio", "vfio")
    if not os.path.exists(control):
        raise ValidationError(
            f"vfio control node missing at {control} (vfio modules loaded?)"
        )
    from tpu_operator.operands.vm_manager import vfio_iommu_groups

    groups = vfio_iommu_groups(dev_root)
    if not groups:
        raise ValidationError(f"no vfio IOMMU groups under {dev_root}/vfio")
    info = {"groups": groups}
    status.write("vm-manager-ready", info)
    return info


def validate_vm_devices(
    status: StatusFiles,
    client=None,
    node_name: str = "",
    dev_root: str = "/dev",
    state_file: str = "/run/tpu/vm-devices.json",
    retries: int = WAIT_RETRIES,
) -> dict:
    """The vm-device-manager materialized VM-attachable devices: its state
    file lists ≥1 device and every recorded vfio group node exists
    (reference vgpu-devices validation, ``validator/main.go:1447-1501``)."""
    skipped = workload_config_gate(status, client, node_name)
    if skipped is not None:
        return skipped
    status.remove("vm-devices-ready")
    state = None
    for _ in range(retries):
        try:
            with open(state_file) as f:
                state = json.load(f)
            break
        except (OSError, ValueError):
            log.info("waiting for vm device state file %s", state_file)
            time.sleep(WAIT_SLEEP_S)
    if state is None:
        raise ValidationError(f"no vm device state at {state_file}")
    devices = state.get("devices") or []
    if not devices:
        raise ValidationError(f"{state_file} lists no VM devices")
    missing = [
        d.get("vfio_group", "")
        for d in devices
        if not os.path.exists(d.get("vfio_group", ""))
    ]
    if missing:
        raise ValidationError(f"vfio groups missing for VM devices: {missing}")
    info = {"config": state.get("config", ""), "devices": len(devices)}
    status.write("vm-devices-ready", info)
    return info
