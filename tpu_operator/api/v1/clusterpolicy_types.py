"""ClusterPolicy CRD types for the TPU operator.

TPU-native analogue of the reference CRD (``api/v1/clusterpolicy_types.go``):
a cluster-scoped ``ClusterPolicy`` whose spec carries one sub-spec per operand
(reference ``api/v1/clusterpolicy_types.go:36-84``), per-spec ``is_enabled``
semantics via optional booleans (``:1659-1832``), image path resolution with
environment-variable fallback and sha256 digest handling (``:1552-1641``),
and a ``State`` enum ready/notReady/ignored/disabled (``:1496-1507``).

The operand mapping is:

====================  =========================================
reference sub-spec     TPU sub-spec
====================  =========================================
Driver                libtpu (userspace libtpu installer)
Toolkit               runtime (CDI / device wiring)
DevicePlugin          devicePlugin (``google.com/tpu``)
DCGM                  metricsd (standalone metrics daemon)
DCGMExporter          metricsExporter (libtpu Prometheus exporter)
GPUFeatureDiscovery   tfd (TPU feature discovery: chip/ICI labels)
MIG / MIGManager      slice / sliceManager (subslice partitioning)
GDS                   directStorage (GCS DirectPath / fuse)
VGPUManager           vmManager (TPU-VM passthrough host manager)
VGPUDeviceManager     vmDeviceManager
====================  =========================================

Objects are plain dataclasses; the wire format is camelCase dicts produced by
``to_dict``/consumed by ``from_dict`` so CRs round-trip losslessly through
YAML/JSON.
"""

from __future__ import annotations

import dataclasses
import os
import re
import typing
from functools import lru_cache as _functools_lru_cache
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

# ---------------------------------------------------------------------------
# Serialization machinery
# ---------------------------------------------------------------------------

_SNAKE_RE = re.compile(r"_([a-z0-9])")


def _snake_to_camel(name: str) -> str:
    return _SNAKE_RE.sub(lambda m: m.group(1).upper(), name)


def _field_key(f: dataclasses.Field) -> str:
    return f.metadata.get("json", _snake_to_camel(f.name))


def _unwrap_optional(tp):
    origin = typing.get_origin(tp)
    if origin is typing.Union:
        args = [a for a in typing.get_args(tp) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return tp


def _coerce(tp, value):
    """Coerce a decoded JSON value into the annotated field type."""
    if value is None:
        return None
    tp = _unwrap_optional(tp)
    origin = typing.get_origin(tp)
    if origin in (list, List):
        (item_tp,) = typing.get_args(tp) or (Any,)
        return [_coerce(item_tp, v) for v in value]
    if origin in (dict, Dict):
        return dict(value)
    if dataclasses.is_dataclass(tp) and isinstance(value, dict):
        return _from_dict(tp, value)
    return value


@_functools_lru_cache(maxsize=None)
def _class_hints(cls):
    return typing.get_type_hints(cls)


def _from_dict(cls, data: Dict[str, Any]):
    kwargs = {}
    hints = _class_hints(cls)
    for f in dataclasses.fields(cls):
        key = _field_key(f)
        if key in data:
            kwargs[f.name] = _coerce(hints[f.name], data[key])
    return cls(**kwargs)


def _to_jsonable(value):
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        out = {}
        for f in dataclasses.fields(value):
            v = getattr(value, f.name)
            if v is None:
                continue
            if v == [] or v == {}:
                continue
            out[_field_key(f)] = _to_jsonable(v)
        return out
    if isinstance(value, list):
        return [_to_jsonable(v) for v in value]
    if isinstance(value, dict):
        return {k: _to_jsonable(v) for k, v in value.items()}
    return value


class SpecBase:
    """Mixin providing dict round-tripping for all spec dataclasses."""

    @classmethod
    def from_dict(cls, data: Optional[Dict[str, Any]]):
        return _from_dict(cls, data or {})

    def to_dict(self) -> Dict[str, Any]:
        return _to_jsonable(self)


# ---------------------------------------------------------------------------
# State enum (reference api/v1/clusterpolicy_types.go:1496-1507)
# ---------------------------------------------------------------------------


class State:
    IGNORED = "ignored"
    READY = "ready"
    NOT_READY = "notReady"
    DISABLED = "disabled"


# ---------------------------------------------------------------------------
# Image spec helpers (reference api/v1/clusterpolicy_types.go:1552-1641)
# ---------------------------------------------------------------------------


class _ImageSpec(SpecBase):
    """Shared image-resolution behaviour for operand specs.

    ``image_path`` resolves ``repository + image + version`` with a
    per-component environment fallback and sha256 digest support, mirroring
    the reference's ``ImagePath``/``imagePath`` helpers
    (``api/v1/clusterpolicy_types.go:1552-1641``).
    """

    ENV_VAR: str = ""

    def image_path(self) -> str:
        repository = getattr(self, "repository", "") or ""
        image = getattr(self, "image", "") or ""
        version = getattr(self, "version", "") or ""
        if image and version:
            prefix = f"{repository}/{image}" if repository else image
            if version.startswith("sha256:"):
                return f"{prefix}@{version}"
            return f"{prefix}:{version}"
        if self.ENV_VAR:
            env = os.environ.get(self.ENV_VAR, "")
            if env:
                return env
        if image and not version:
            prefix = f"{repository}/{image}" if repository else image
            return prefix
        return ""

    def pull_policy(self) -> str:
        return image_pull_policy(getattr(self, "image_pull_policy", None))

    def is_enabled(self) -> bool:
        enabled = getattr(self, "enabled", None)
        if enabled is None:
            return True
        return bool(enabled)


def image_pull_policy(policy: Optional[str]) -> str:
    """Normalize an imagePullPolicy value (reference ``ImagePullPolicy`` helper)."""
    return policy if policy in ("Always", "Never", "IfNotPresent") else "IfNotPresent"


# ---------------------------------------------------------------------------
# Common nested specs
# ---------------------------------------------------------------------------


@dataclass
class EnvVar(SpecBase):
    name: str = ""
    value: str = ""


@dataclass
class ResourceRequirements(SpecBase):
    limits: Dict[str, str] = field(default_factory=dict)
    requests: Dict[str, str] = field(default_factory=dict)


@dataclass
class RollingUpdateSpec(SpecBase):
    max_unavailable: str = "1"


@dataclass
class InitContainerSpec(_ImageSpec):
    repository: str = ""
    image: str = "busybox"  # minimal init image used for host-prep chores
    version: str = ""
    image_pull_policy: Optional[str] = None
    image_pull_secrets: List[str] = field(default_factory=list)

    ENV_VAR = "TPU_OPERATOR_INIT_CONTAINER_IMAGE"


# ---------------------------------------------------------------------------
# Operator / Daemonsets
# ---------------------------------------------------------------------------


@dataclass
class ProxySpec(SpecBase):
    """Cluster-wide egress proxy for operands that reach the network
    (reference ``applyOCPProxySpec``, ``controllers/object_controls.go:907-960``
    — there read from the OpenShift ``Proxy`` cluster object; here declared
    on the CR directly since GKE has no such object)."""

    http_proxy: str = ""
    https_proxy: str = ""
    no_proxy: str = ""
    # ConfigMap (operator namespace) holding ``ca-bundle.crt`` with the
    # proxy's trusted CA chain (reference trusted-CA mount,
    # ``controllers/object_controls.go:962-1050``)
    trusted_ca_config_map: str = ""


@dataclass
class OperatorSpec(SpecBase):
    """Operator-level knobs (reference ``OperatorSpec``)."""

    default_runtime: str = "containerd"
    runtime_class: str = "tpu"
    init_container: InitContainerSpec = field(default_factory=InitContainerSpec)
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    proxy: Optional[ProxySpec] = None


@dataclass
class DaemonsetsSpec(SpecBase):
    """Settings applied to every operand DaemonSet (reference ``DaemonsetsSpec``)."""

    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    tolerations: List[Dict[str, Any]] = field(default_factory=list)
    priority_class_name: str = "system-node-critical"
    update_strategy: str = "RollingUpdate"
    rolling_update: Optional[RollingUpdateSpec] = None


# ---------------------------------------------------------------------------
# Upgrade policy (reference DriverUpgradePolicySpec via k8s-operator-libs)
# ---------------------------------------------------------------------------


@dataclass
class PodDeletionSpec(SpecBase):
    force: Optional[bool] = None
    timeout_seconds: int = 300
    delete_emptydir_data: Optional[bool] = None


@dataclass
class DrainSpec(SpecBase):
    enable: Optional[bool] = None
    force: Optional[bool] = None
    pod_selector: str = ""
    timeout_seconds: int = 300
    delete_emptydir_data: Optional[bool] = None


@dataclass
class UpgradePolicySpec(SpecBase):
    """Safe rolling libtpu upgrades (reference ``v1alpha1.DriverUpgradePolicySpec``,
    vendored ``k8s-operator-libs/api/upgrade/v1alpha1``)."""

    auto_upgrade: Optional[bool] = None
    max_parallel_upgrades: int = 1
    max_unavailable: str = "25%"
    wait_for_completion: Optional[Dict[str, Any]] = None
    pod_deletion: Optional[PodDeletionSpec] = None
    drain: Optional[DrainSpec] = None

    def is_auto_upgrade_enabled(self) -> bool:
        return bool(self.auto_upgrade)


# ---------------------------------------------------------------------------
# Operand specs
# ---------------------------------------------------------------------------


@dataclass
class LibtpuSpec(_ImageSpec):
    """libtpu installer — the reference's ``DriverSpec`` slot
    (``api/v1/clusterpolicy_types.go``; DS at ``assets/state-driver/0500_daemonset.yaml``).

    TPU-native: there is no kernel module to build; the operand installs a
    versioned ``libtpu.so`` onto the host and probes ``/dev/accel*``. The
    per-kernel precompiled fan-out of the reference becomes per-TPU-generation
    image fan-out (v4/v5e/v5p/v6e) via ``generation_configs``.
    """

    enabled: Optional[bool] = None
    repository: str = ""
    image: str = "libtpu-installer"
    version: str = ""
    image_pull_policy: Optional[str] = None
    image_pull_secrets: List[str] = field(default_factory=list)
    env: List[EnvVar] = field(default_factory=list)
    args: List[str] = field(default_factory=list)
    resources: Optional[ResourceRequirements] = None
    install_dir: str = "/home/kubernetes/lib/tpu"
    # map of TPU generation (v4, v5e, v5p, v6e) -> image version override;
    # drives one DaemonSet per generation (reference per-kernel fan-out,
    # controllers/object_controls.go:3405-3441).
    generation_configs: Dict[str, str] = field(default_factory=dict)
    # custom artifact-source config mounted into the installer (reference
    # driver ``repoConfig`` {configMapName}, ``object_controls.go:2770-2800``:
    # there it is apt/yum repo lists; here libtpu mirror/endpoint config)
    repo_config: Dict[str, str] = field(default_factory=dict)
    # extra CA certificates for the installer's download endpoint (reference
    # driver ``certConfig`` {name}, ``object_controls.go:2802-2830``)
    cert_config: Dict[str, str] = field(default_factory=dict)
    upgrade_policy: Optional[UpgradePolicySpec] = None
    rolling_update: Optional[RollingUpdateSpec] = None
    startup_probe: Optional[Dict[str, Any]] = None
    liveness_probe: Optional[Dict[str, Any]] = None
    readiness_probe: Optional[Dict[str, Any]] = None

    ENV_VAR = "LIBTPU_INSTALLER_IMAGE"


@dataclass
class RuntimeSpec(_ImageSpec):
    """TPU runtime/device wiring — the reference's ``ToolkitSpec`` slot.

    Instead of rewriting containerd/docker/crio configs
    (``controllers/object_controls.go:1052-1184``), the TPU path generates a
    CDI spec exposing ``/dev/accel*``, ``/dev/vfio`` and ``libtpu.so`` and
    (optionally) installs a minimal containerd runtime hook for non-CDI
    clusters.
    """

    enabled: Optional[bool] = None
    repository: str = ""
    image: str = "tpu-runtime"
    version: str = ""
    image_pull_policy: Optional[str] = None
    image_pull_secrets: List[str] = field(default_factory=list)
    env: List[EnvVar] = field(default_factory=list)
    install_dir: str = "/usr/local/tpu"

    ENV_VAR = "TPU_RUNTIME_IMAGE"


@dataclass
class DevicePluginConfig(SpecBase):
    """Custom plugin config via ConfigMap (reference ``DevicePluginConfig``)."""

    name: str = ""
    default: str = ""


@dataclass
class DevicePluginSpec(_ImageSpec):
    """TPU device plugin advertising ``google.com/tpu`` with topology-aware
    allocation — the reference's ``DevicePluginSpec`` slot."""

    enabled: Optional[bool] = None
    repository: str = ""
    image: str = "tpu-device-plugin"
    version: str = ""
    image_pull_policy: Optional[str] = None
    image_pull_secrets: List[str] = field(default_factory=list)
    env: List[EnvVar] = field(default_factory=list)
    args: List[str] = field(default_factory=list)
    resources: Optional[ResourceRequirements] = None
    config: Optional[DevicePluginConfig] = None

    ENV_VAR = "TPU_DEVICE_PLUGIN_IMAGE"


@dataclass
class MetricsdSpec(_ImageSpec):
    """Standalone TPU metrics daemon — the reference's ``DCGMSpec`` slot
    (standalone hostengine, ``controllers/object_controls.go:95-98,1441-1495``)."""

    enabled: Optional[bool] = None
    repository: str = ""
    image: str = "tpu-metricsd"
    version: str = ""
    image_pull_policy: Optional[str] = None
    image_pull_secrets: List[str] = field(default_factory=list)
    host_port: int = 5555
    env: List[EnvVar] = field(default_factory=list)
    # run the chip-owning JAX sampler sidecar next to the native hostengine
    # (TPU runtime is single-client; only enable on nodes the daemon may own)
    sample_on_chip: Optional[bool] = None

    ENV_VAR = "TPU_METRICSD_IMAGE"


@dataclass
class MetricsConfig(SpecBase):
    name: str = ""


@dataclass
class MetricsExporterSpec(_ImageSpec):
    """libtpu Prometheus metrics exporter — the reference's
    ``DCGMExporterSpec`` slot (``controllers/object_controls.go:1302-1439``)."""

    enabled: Optional[bool] = None
    repository: str = ""
    image: str = "tpu-metrics-exporter"
    version: str = ""
    image_pull_policy: Optional[str] = None
    image_pull_secrets: List[str] = field(default_factory=list)
    env: List[EnvVar] = field(default_factory=list)
    resources: Optional[ResourceRequirements] = None
    metrics_config: Optional[MetricsConfig] = None
    service_monitor: Optional[Dict[str, Any]] = None

    ENV_VAR = "TPU_METRICS_EXPORTER_IMAGE"


@dataclass
class NodeStatusExporterSpec(_ImageSpec):
    """Validator image in metrics mode (reference ``NodeStatusExporterSpec``,
    ``assets/state-node-status-exporter/0700_daemonset.yaml:31-37``)."""

    enabled: Optional[bool] = None
    repository: str = ""
    image: str = "tpu-operator-validator"
    version: str = ""
    image_pull_policy: Optional[str] = None
    image_pull_secrets: List[str] = field(default_factory=list)
    env: List[EnvVar] = field(default_factory=list)

    ENV_VAR = "TPU_VALIDATOR_IMAGE"


@dataclass
class TFDSpec(_ImageSpec):
    """TPU feature discovery — the reference's ``GPUFeatureDiscoverySpec``
    slot. Emits chip type/count, ICI topology and slice labels."""

    enabled: Optional[bool] = None
    repository: str = ""
    image: str = "tpu-feature-discovery"
    version: str = ""
    image_pull_policy: Optional[str] = None
    image_pull_secrets: List[str] = field(default_factory=list)
    env: List[EnvVar] = field(default_factory=list)
    resources: Optional[ResourceRequirements] = None

    ENV_VAR = "TPU_FEATURE_DISCOVERY_IMAGE"


@dataclass
class MaintenanceHandlerSpec(_ImageSpec):
    """Host-maintenance watcher (TPU-specific; no reference analogue).

    Cloud TPU hosts announce maintenance through the GCE metadata server;
    this operand cordons, labels, and evicts TPU workloads ahead of the
    window (``tpu_operator/operands/maintenance.py``). Opt-in: absent or
    ``enabled: false`` deploys nothing."""

    enabled: Optional[bool] = None
    repository: str = ""
    image: str = "tpu-operator"
    version: str = ""
    image_pull_policy: Optional[str] = None
    image_pull_secrets: List[str] = field(default_factory=list)
    env: List[EnvVar] = field(default_factory=list)
    resources: Optional[ResourceRequirements] = None
    # metadata poll cadence; GCE gives >= 60 s of notice
    poll_interval_seconds: int = 10
    # also delete unmanaged (ownerless) TPU pods when a window opens
    force_evict: Optional[bool] = None
    # cordon/label only; leave workloads to ride out the window
    evict_workloads: Optional[bool] = None

    ENV_VAR = "TPU_OPERATOR_IMAGE"

    def is_enabled(self) -> bool:
        # opt-in, unlike most operands: maintenance eviction is a policy
        # decision (it kills running training pods on purpose)
        return bool(self.enabled)


@dataclass
class RemediationSpec(SpecBase):
    """Node-health remediation FSM knobs (TPU-specific; no reference
    analogue — SURVEY §5 failure detection). Opt-in like the maintenance
    handler: remediation cordons, taints and drains nodes on purpose.

    ``maxUnavailable`` is the fleet-wide disruption budget SHARED with
    rolling libtpu upgrades: both admissions count the same JOINT set of
    disrupted slices (upgrade-active/failed + remediation-quarantined,
    ``upgrade_state.slice_budget``), each against its own cap — with this
    knob equal to ``upgradePolicy.maxUnavailable`` (both default "25%")
    that is exactly one pool; if they differ, the tighter cap governs new
    disruptions on its own side.
    ``maxAttempts`` caps escalation steps per node before ``exhausted``;
    ``backoffSeconds`` is the jittered-exponential base between steps.
    ``systemicThreshold`` is the systemic-failure breaker: when at least
    that fraction of TPU nodes turns unhealthy in one pass, remediation
    halts with zero drains (a bad libtpu push must not drain the fleet).
    """

    enabled: Optional[bool] = None
    max_unavailable: str = "25%"
    max_attempts: int = 5
    backoff_seconds: int = 30
    systemic_threshold: str = "50%"

    def is_enabled(self) -> bool:
        # opt-in: remediation issues disruptions (cordon/taint/drain)
        return bool(self.enabled)


@dataclass
class RolloutSpec(SpecBase):
    """Health-gated progressive rollouts (TPU-specific; the reference's
    closest analogue is its second, upgrade-only reconciler —
    ``controllers/upgrade_controller.go``). When enabled, any fleet-wide
    version/layout change (``libtpu.version`` through the upgrade FSM,
    ``sliceManager.config.default`` through the re-partition roller) is
    staged through **canary → wave(s) → fleet** slice cohorts
    (``controllers/rollout.py``), with a live health gate between stages:
    validator TFLOPS/membw deltas vs the pre-roll per-node baseline, new
    remediation quarantines, upgrade failures, operand crashloops,
    Degraded conditions, and alloc-latency regression. A regressing
    canary pauses the roll and — with ``autoRollback`` (default on) —
    re-rolls the cohort to the recorded previous version.

    ``canary``/``waves`` are int-or-percent of the fleet's SLICES (the
    disruption unit): canary defaults to 1 slice, then one 25% wave,
    then the rest of the fleet. ``observeSeconds`` is the per-stage soak
    after the cohort finishes rolling before promotion. The degraded-
    percent knobs are regression thresholds vs the recorded baseline."""

    enabled: Optional[bool] = None
    canary: str = "1"
    waves: List[str] = field(default_factory=lambda: ["25%"])
    observe_seconds: int = 60
    tflops_degraded_pct: int = 10
    membw_degraded_pct: int = 10
    alloc_p99_degraded_pct: int = 100
    auto_rollback: Optional[bool] = None

    def is_enabled(self) -> bool:
        # opt-in: staged rolls deliberately slow fleet-wide changes down
        return bool(self.enabled)

    def rollback_enabled(self) -> bool:
        # default ON: a staged roll without automatic rollback only
        # contains the blast radius, it doesn't undo it
        return True if self.auto_rollback is None else bool(self.auto_rollback)


@dataclass
class SliceSpec(SpecBase):
    """Subslice exposure strategy — the reference's ``MIGSpec``.

    ``strategy`` is ``none`` | ``single`` | ``mixed``: whether partitioned
    subslices are advertised as uniform ``google.com/tpu`` or as
    ``google.com/tpu-<shape>`` resources.
    """

    strategy: str = "single"


@dataclass
class SliceManagerSpec(_ImageSpec):
    """TPU slice/partition manager — the reference's ``MIGManagerSpec`` slot
    (``assets/state-mig-manager/``, named layouts ConfigMap, node-label FSM).

    ``config.default`` (the reference's ``mig.config`` default profile)
    doubles as the FLEET-WIDE desired layout: when set, the live
    re-partition controller (``controllers/repartition.py``) rolls every
    TPU node whose applied layout differs, slice-by-slice, through the
    shared disruption budget. ``maxUnavailable`` is that roll's cap over
    the JOINT disrupted set (upgrades + remediation + re-partition draw
    on one pool; with the three knobs equal — all default "25%" — it is
    exactly one budget)."""

    enabled: Optional[bool] = None
    repository: str = ""
    image: str = "tpu-slice-manager"
    version: str = ""
    image_pull_policy: Optional[str] = None
    image_pull_secrets: List[str] = field(default_factory=list)
    env: List[EnvVar] = field(default_factory=list)
    config: Optional[DevicePluginConfig] = None
    chip_clients_config: Optional[MetricsConfig] = None
    max_unavailable: str = "25%"

    ENV_VAR = "TPU_SLICE_MANAGER_IMAGE"


@dataclass
class ValidatorSpec(_ImageSpec):
    """Validation harness (reference ``ValidatorSpec``; binary in
    ``validator/main.go``). Components: libtpu, runtime, plugin, jax, slice,
    nodestatus (metrics mode)."""

    enabled: Optional[bool] = None
    repository: str = ""
    image: str = "tpu-operator-validator"
    version: str = ""
    image_pull_policy: Optional[str] = None
    image_pull_secrets: List[str] = field(default_factory=list)
    env: List[EnvVar] = field(default_factory=list)
    resources: Optional[ResourceRequirements] = None
    plugin: Optional[Dict[str, Any]] = None
    jax: Optional[Dict[str, Any]] = None
    libtpu: Optional[Dict[str, Any]] = None
    runtime: Optional[Dict[str, Any]] = None
    # optional deep diagnostic: HBM bandwidth probe ({"enabled": true,
    # "env": [...]}) appended to the validation chain — the reference's
    # ``dcgmi diag`` memory-bandwidth analogue, off by default because it
    # holds the chip for a few extra seconds per validation pass
    membw: Optional[Dict[str, Any]] = None
    # optional long-context probe: blockwise ring attention over an ``sp``
    # mesh axis checked against full attention ({"enabled": true, "env":
    # [...]}); proves the context-parallel path on multi-chip hosts, off by
    # default for the same chip-holding reason as membw
    ringattn: Optional[Dict[str, Any]] = None
    # optional ICI ring probe: per-link integrity + bandwidth via ppermute
    ici: Optional[Dict[str, Any]] = None
    # optional pipeline-parallel probe: GPipe microbatch schedule over pp
    pipeline: Optional[Dict[str, Any]] = None
    # optional expert-parallel probe: MoE all_to_all dispatch/combine
    moe: Optional[Dict[str, Any]] = None
    # optional pallas hot-op probe: single-chip flash attention with
    # online softmax checked against full attention (see
    # workloads/flashattn.py); off by default (chip-holding)
    flashattn: Optional[Dict[str, Any]] = None

    ENV_VAR = "TPU_VALIDATOR_IMAGE"


@dataclass
class DirectStorageSpec(_ImageSpec):
    """High-bandwidth storage path — the reference's ``GPUDirectStorageSpec``
    (GDS / nvidia-fs) slot. On TPU this wires GCS DirectPath / gcsfuse for
    data loading; disabled by default."""

    enabled: Optional[bool] = None
    repository: str = ""
    image: str = "tpu-direct-storage"
    version: str = ""
    image_pull_policy: Optional[str] = None
    image_pull_secrets: List[str] = field(default_factory=list)
    env: List[EnvVar] = field(default_factory=list)

    ENV_VAR = "TPU_DIRECT_STORAGE_IMAGE"

    def is_enabled(self) -> bool:
        # storage fast-path defaults OFF, like the reference's GDS
        return bool(self.enabled)


@dataclass
class SandboxWorkloadsSpec(SpecBase):
    """Sandbox (VM-passthrough) workload gating — reference
    ``SandboxWorkloadsSpec``. ``default_workload``: container | vm-passthrough."""

    enabled: Optional[bool] = None
    default_workload: str = "container"

    def is_enabled(self) -> bool:
        return bool(self.enabled)


@dataclass
class VFIOManagerSpec(_ImageSpec):
    """Binds TPU PCI functions to vfio-pci for VM passthrough — reference
    ``VFIOManagerSpec`` slot."""

    enabled: Optional[bool] = None
    repository: str = ""
    image: str = "tpu-vfio-manager"
    version: str = ""
    image_pull_policy: Optional[str] = None
    image_pull_secrets: List[str] = field(default_factory=list)
    env: List[EnvVar] = field(default_factory=list)

    ENV_VAR = "TPU_VFIO_MANAGER_IMAGE"


@dataclass
class SandboxDevicePluginSpec(_ImageSpec):
    """Device plugin for VM workloads (kubevirt style) — reference
    ``SandboxDevicePluginSpec`` slot."""

    enabled: Optional[bool] = None
    repository: str = ""
    image: str = "tpu-sandbox-device-plugin"
    version: str = ""
    image_pull_policy: Optional[str] = None
    image_pull_secrets: List[str] = field(default_factory=list)
    env: List[EnvVar] = field(default_factory=list)
    args: List[str] = field(default_factory=list)

    ENV_VAR = "TPU_SANDBOX_DEVICE_PLUGIN_IMAGE"


@dataclass
class VMManagerSpec(_ImageSpec):
    """TPU-VM passthrough host manager — reference ``VGPUManagerSpec`` slot."""

    enabled: Optional[bool] = None
    repository: str = ""
    image: str = "tpu-vm-manager"
    version: str = ""
    image_pull_policy: Optional[str] = None
    image_pull_secrets: List[str] = field(default_factory=list)
    env: List[EnvVar] = field(default_factory=list)

    ENV_VAR = "TPU_VM_MANAGER_IMAGE"


@dataclass
class VMDeviceManagerSpec(_ImageSpec):
    """Creates passthrough TPU devices per named config — reference
    ``VGPUDeviceManagerSpec`` slot."""

    enabled: Optional[bool] = None
    repository: str = ""
    image: str = "tpu-vm-device-manager"
    version: str = ""
    image_pull_policy: Optional[str] = None
    image_pull_secrets: List[str] = field(default_factory=list)
    env: List[EnvVar] = field(default_factory=list)
    config: Optional[DevicePluginConfig] = None

    ENV_VAR = "TPU_VM_DEVICE_MANAGER_IMAGE"


@dataclass
class CDISpec(SpecBase):
    """Container Device Interface knobs (reference ``CDIConfigSpec``,
    ``controllers/object_controls.go:125-138``). On TPU, CDI is the default
    device-injection path."""

    enabled: Optional[bool] = None
    default: Optional[bool] = None

    def is_enabled(self) -> bool:
        # CDI defaults ON for the TPU operator (modern path).
        if self.enabled is None:
            return True
        return bool(self.enabled)

    def is_default(self) -> bool:
        if self.default is None:
            return True
        return bool(self.default)


@dataclass
class KataManagerSpec(_ImageSpec):
    """Kata runtime artifacts — reference ``KataManagerSpec`` slot
    (``controllers/object_controls.go:4336-4428``)."""

    enabled: Optional[bool] = None
    repository: str = ""
    image: str = "tpu-kata-manager"
    version: str = ""
    image_pull_policy: Optional[str] = None
    image_pull_secrets: List[str] = field(default_factory=list)
    env: List[EnvVar] = field(default_factory=list)
    config: Optional[Dict[str, Any]] = None

    ENV_VAR = "TPU_KATA_MANAGER_IMAGE"


@dataclass
class PSPSpec(SpecBase):
    enabled: Optional[bool] = None

    def is_enabled(self) -> bool:
        return bool(self.enabled)


@dataclass
class PSASpec(SpecBase):
    enabled: Optional[bool] = None

    def is_enabled(self) -> bool:
        return bool(self.enabled)


# ---------------------------------------------------------------------------
# ClusterPolicy
# ---------------------------------------------------------------------------


@dataclass
class ClusterPolicySpec(SpecBase):
    """Spec with one sub-spec per operand (reference
    ``api/v1/clusterpolicy_types.go:36-84`` — 23 sub-specs)."""

    operator: OperatorSpec = field(default_factory=OperatorSpec)
    daemonsets: DaemonsetsSpec = field(default_factory=DaemonsetsSpec)
    libtpu: LibtpuSpec = field(default_factory=LibtpuSpec)
    runtime: RuntimeSpec = field(default_factory=RuntimeSpec)
    device_plugin: DevicePluginSpec = field(default_factory=DevicePluginSpec)
    direct_storage: DirectStorageSpec = field(default_factory=DirectStorageSpec)
    metricsd: MetricsdSpec = field(default_factory=MetricsdSpec)
    metrics_exporter: MetricsExporterSpec = field(default_factory=MetricsExporterSpec)
    node_status_exporter: NodeStatusExporterSpec = field(
        default_factory=NodeStatusExporterSpec
    )
    tfd: TFDSpec = field(default_factory=TFDSpec)
    maintenance_handler: MaintenanceHandlerSpec = field(
        default_factory=MaintenanceHandlerSpec
    )
    remediation: RemediationSpec = field(default_factory=RemediationSpec)
    rollout: RolloutSpec = field(default_factory=RolloutSpec)
    slice: SliceSpec = field(default_factory=SliceSpec)
    slice_manager: SliceManagerSpec = field(default_factory=SliceManagerSpec)
    validator: ValidatorSpec = field(default_factory=ValidatorSpec)
    sandbox_workloads: SandboxWorkloadsSpec = field(
        default_factory=SandboxWorkloadsSpec
    )
    vfio_manager: VFIOManagerSpec = field(default_factory=VFIOManagerSpec)
    sandbox_device_plugin: SandboxDevicePluginSpec = field(
        default_factory=SandboxDevicePluginSpec
    )
    vm_manager: VMManagerSpec = field(default_factory=VMManagerSpec)
    vm_device_manager: VMDeviceManagerSpec = field(default_factory=VMDeviceManagerSpec)
    cdi: CDISpec = field(default_factory=CDISpec)
    kata_manager: KataManagerSpec = field(default_factory=KataManagerSpec)
    psp: PSPSpec = field(default_factory=PSPSpec)
    psa: PSASpec = field(default_factory=PSASpec)

    def sandbox_enabled(self) -> bool:
        return self.sandbox_workloads.is_enabled()


@dataclass
class ClusterPolicyStatus(SpecBase):
    """Status (reference ``api/v1/clusterpolicy_types.go:1509-1523``)."""

    state: str = ""
    namespace: str = ""
    conditions: List[Dict[str, Any]] = field(default_factory=list)
    # slice-scoped readiness aggregate (no reference analogue; SURVEY.md §7
    # multi-host hard part): {"total": N, "ready": M, "degraded": [ids]}
    slices: Dict[str, Any] = field(default_factory=dict)
    # per-state error isolation: states whose step() raised this pass,
    # [{"state": name, "error": "Type: message"}]; the pass continues to
    # independent states and a Degraded condition summarizes this block
    errored_states: List[Dict[str, Any]] = field(default_factory=list)
    # node-health remediation counts: {"unhealthy": N, "quarantined": N,
    # "exhausted": N, "breakerOpen": bool} — the fleet-repair truth at a
    # glance; breakerOpen mirrors the Degraded/SystemicNodeFailure
    # condition
    remediation: Dict[str, Any] = field(default_factory=dict)
    # health-gated rollout progress: {"kind": "libtpu"|"layout",
    # "target": v, "state": "rolling"|"paused"|"rolledBack"|"complete",
    # "stage": "k/n", "evidence": [...]} — mirrors the durable rollout
    # ledger annotation (controllers/rollout.py)
    rollout: Dict[str, Any] = field(default_factory=dict)


@dataclass
class ClusterPolicy(SpecBase):
    """The single cluster-scoped CR (reference ``ClusterPolicy`` ``:1525``)."""

    api_version: str = "tpu.k8s.io/v1"
    kind: str = "ClusterPolicy"
    metadata: Dict[str, Any] = field(default_factory=dict)
    spec: ClusterPolicySpec = field(default_factory=ClusterPolicySpec)
    status: ClusterPolicyStatus = field(default_factory=ClusterPolicyStatus)

    @property
    def name(self) -> str:
        return self.metadata.get("name", "")

    def set_status(self, state: str, namespace: str) -> None:
        """reference ``SetStatus`` (``api/v1/clusterpolicy_types.go:1547``)."""
        self.status.state = state
        self.status.namespace = namespace

def clusterpolicy_from_obj(obj: Dict[str, Any]) -> ClusterPolicy:
    """Decode a raw dict (as stored in the API server) into a ClusterPolicy."""
    cp = ClusterPolicy(
        api_version=obj.get("apiVersion", "tpu.k8s.io/v1"),
        kind=obj.get("kind", "ClusterPolicy"),
        metadata=dict(obj.get("metadata", {})),
        spec=ClusterPolicySpec.from_dict(obj.get("spec", {})),
        status=ClusterPolicyStatus.from_dict(obj.get("status", {})),
    )
    return cp


def clusterpolicy_to_obj(cp: ClusterPolicy) -> Dict[str, Any]:
    obj = {
        "apiVersion": cp.api_version,
        "kind": cp.kind,
        "metadata": cp.metadata,
        "spec": cp.spec.to_dict(),
    }
    status = cp.status.to_dict()
    if status:
        obj["status"] = status
    return obj
