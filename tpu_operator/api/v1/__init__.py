from tpu_operator.api.v1.clusterpolicy_types import (  # noqa: F401
    ClusterPolicy,
    ClusterPolicySpec,
    ClusterPolicyStatus,
    State,
)
