"""TPU slice/partition manager — the mig-manager slot.

The reference's mig-manager (external image + ``assets/state-mig-manager/``)
reacts to the ``nvidia.com/mig.config`` node label, drains GPU clients,
applies a named mig-parted layout, and reports via
``nvidia.com/mig.config.state``. The TPU equivalent:

* watches ``tpu.k8s.io/tpu.slice.config`` for a named profile from the
  layouts ConfigMap (``assets/state-slice-manager/0400_configmap.yaml``);
* partitions the host's chips into ICI-contiguous subslices
  (``workloads/topology.enumerate_subslices``) — a *logical* partition:
  TPU chips need no hardware mode switch, so "apply" means (1) writing the
  partition state file the device plugin reads to advertise
  ``google.com/tpu-<shape>`` resources, and (2) regenerating the CDI spec
  with one composite device per subslice;
* pauses chip clients first by flipping their deploy labels to
  ``paused-for-slice-config`` (the reference's k8s-client pause pattern),
  restoring them afterwards;
* reports through ``tpu.k8s.io/tpu.slice.config.state`` ∈
  pending|success|failed.

**Fleet rolls**: this daemon is deliberately per-node and level-
triggered — a CHANGED desired config label re-enters the apply path on
the next pass (the ``want == applied and state == success`` early
return only holds while both match), so the fleet-level re-partition
controller (``controllers/repartition.py``) can roll a new named layout
across a busy fleet by rewriting ``tpu.k8s.io/tpu.slice.config`` node
by node under the shared disruption budget, resetting the state label
to ``pending`` at admission (a stale ``success`` from the PREVIOUS
layout must not read as done). The ``STATE_*`` values here are that
controller's contract; see docs/robustness.md "Live slice
re-partitioning".
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Dict, List, Optional

import yaml

from tpu_operator import consts
from tpu_operator.kube.client import ConflictError
from tpu_operator.native import tpuinfo
from tpu_operator.workloads import topology as topo

log = logging.getLogger("tpu-slice-manager")

STATE_PENDING = "pending"
STATE_SUCCESS = "success"
STATE_FAILED = "failed"

DEFAULT_PARTITION_FILE = "/run/tpu/partitions.json"
PAUSED_VALUE = "paused-for-slice-config"


def load_slice_configs(path: str) -> Dict[str, List[dict]]:
    with open(path) as f:
        doc = yaml.safe_load(f) or {}
    configs = doc.get("slice-configs", {})
    if not isinstance(configs, dict) or not configs:
        raise ValueError(f"{path}: no slice-configs")
    return configs


def load_chip_clients(path: str) -> List[str]:
    try:
        with open(path) as f:
            doc = yaml.safe_load(f) or {}
        return list(doc.get("kubernetes-labels", []) or [])
    except OSError:
        return []


def resolve_shape(profile: List[dict], host_topology: str) -> Optional[str]:
    """Profile entries -> concrete subslice shape string, or None for
    unpartitioned."""
    for entry in profile:
        if not entry.get("partitioned", False):
            return None
        layout = entry.get("layout", {}) or {}
        shape = layout.get("shape", "")
        if shape == "host":
            return host_topology
        if shape:
            return shape
    return None


def compute_partitions(
    host_topology: str, generation: str, shape: Optional[str]
) -> dict:
    """The partition state the device plugin consumes."""
    if shape is None:
        return {"partitioned": False, "subslices": []}
    tiles = topo.enumerate_subslices(host_topology, topo.parse_topology(shape))
    dims = topo.parse_topology(host_topology)
    subslices = []
    for i, tile in enumerate(tiles):
        chips = [topo.coord_to_index(c, dims) for c in tile.coords()]
        subslices.append(
            {
                "id": i,
                "shape": tile.name(),
                "chips": sorted(chips),
                "resource": consts.TPU_SUBSLICE_RESOURCE_PREFIX + tile.name(),
            }
        )
    return {
        "partitioned": True,
        "topology": host_topology,
        "generation": generation,
        "shape": shape,
        "subslices": subslices,
    }


def write_partition_state(state: dict, path: str = DEFAULT_PARTITION_FILE) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(state, f, indent=1, sort_keys=True)
    os.replace(tmp, path)


def read_partition_state(path: str = DEFAULT_PARTITION_FILE) -> Optional[dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


class SliceManager:
    def __init__(
        self,
        client,
        node_name: str,
        config_file: str,
        chip_clients_file: str = "",
        partition_file: str = DEFAULT_PARTITION_FILE,
        cdi_spec_path: str = "",
        dev_root: str = "/dev",
    ):
        self.client = client
        self.node_name = node_name
        self.config_file = config_file
        self.chip_clients_file = chip_clients_file
        self.partition_file = partition_file
        self.cdi_spec_path = cdi_spec_path
        self.dev_root = dev_root
        self._applied: Optional[str] = None

    # ------------------------------------------------------------------
    def _node(self) -> dict:
        return self.client.get("v1", "Node", self.node_name)

    def _mutate_labels(self, mutate) -> None:
        """Apply ``mutate(labels) -> bool(changed)`` under optimistic
        concurrency: the Node object is shared with other label writers
        (the operator's deploy-label bus, the upgrade FSM, TFD), so a 409
        means re-GET and re-apply, not failure."""
        from tpu_operator.kube.client import mutate_with_retry

        mutate_with_retry(
            self.client,
            "v1",
            "Node",
            self.node_name,
            mutate=lambda node: mutate(node["metadata"].setdefault("labels", {})),
        )

    def _set_state(self, value: str) -> None:
        def mutate(labels: dict) -> bool:
            if labels.get(consts.SLICE_CONFIG_STATE_LABEL) == value:
                return False
            labels[consts.SLICE_CONFIG_STATE_LABEL] = value
            return True

        self._mutate_labels(mutate)

    def _pause_clients(self, pause: bool) -> None:
        """Flip chip-client deploy labels so their DaemonSets release the
        chips during repartition (reference pauses device-plugin/dcgm/gfd
        via paused-for-mig-change label values)."""
        client_labels = load_chip_clients(self.chip_clients_file)
        if not client_labels:
            return

        def mutate(labels: dict) -> bool:
            changed = False
            for key in client_labels:
                if pause and labels.get(key) == "true":
                    labels[key] = PAUSED_VALUE
                    changed = True
                elif not pause and labels.get(key) == PAUSED_VALUE:
                    labels[key] = "true"
                    changed = True
            return changed

        self._mutate_labels(mutate)

    # ------------------------------------------------------------------
    def apply_config(self, config_name: str) -> dict:
        configs = load_slice_configs(self.config_file)
        if config_name not in configs:
            raise ValueError(f"unknown slice config {config_name!r}")
        node = self._node()
        labels = node["metadata"].get("labels", {}) or {}
        host_topology = labels.get(consts.GKE_TPU_TOPOLOGY_LABEL) or labels.get(
            consts.TFD_TOPOLOGY_LABEL
        )
        if not host_topology:
            # derive a 1-D fallback from visible chips
            n = tpuinfo.chip_count(self.dev_root)
            if not n:
                raise RuntimeError("no topology label and no visible chips")
            host_topology = f"1x{n}"
        generation = labels.get(consts.TFD_CHIP_TYPE_LABEL, "") or labels.get(
            f"{consts.GROUP}/tpu.generation", ""
        )
        shape = resolve_shape(configs[config_name], host_topology)
        state = compute_partitions(host_topology, generation, shape)
        state["config"] = config_name
        write_partition_state(state, self.partition_file)
        if self.cdi_spec_path:
            self._regenerate_cdi(state)
        return state

    def _regenerate_cdi(self, state: dict) -> None:
        # build_spec reads the partition file we just wrote, so the subslice
        # composite devices land in the shared spec path that runtime-wire
        # also maintains — both writers produce identical content
        from tpu_operator.plugin import cdi

        cdi.write_spec(
            self.cdi_spec_path,
            dev_root=self.dev_root,
            partition_file=self.partition_file,
        )

    # ------------------------------------------------------------------
    def reconcile_once(self) -> Optional[str]:
        """One pass of the label FSM; returns the state written (or None)."""
        node = self._node()
        labels = node["metadata"].get("labels", {}) or {}
        want = labels.get(consts.SLICE_CONFIG_LABEL)
        if not want:
            return None
        # clients still paused (a prior pass crashed/409'd between apply
        # and unpause, or a previous process died mid-window) veto the
        # early return: the re-apply below is idempotent and retries the
        # unpause
        paused = any(
            labels.get(k) == PAUSED_VALUE
            for k in load_chip_clients(self.chip_clients_file)
        )
        if (
            want == self._applied
            and labels.get(consts.SLICE_CONFIG_STATE_LABEL) == STATE_SUCCESS
            and not paused
        ):
            return STATE_SUCCESS
        try:
            self._set_state(STATE_PENDING)
            self._pause_clients(True)
            self.apply_config(want)
            self._applied = want
            self._set_state(STATE_SUCCESS)
            result = STATE_SUCCESS
        except ConflictError:
            # a write race that outlasted the retry budget is transient —
            # the next loop pass re-reconciles; marking the partition
            # FAILED over it would misreport a healthy node
            log.warning("slice config %r hit persistent 409s; retrying", want)
            result = None
        except Exception:
            log.exception("slice config %r failed", want)
            try:
                self._set_state(STATE_FAILED)
            except ConflictError:
                log.warning("failed-state write hit 409s; next pass retries")
            result = STATE_FAILED
        try:
            self._pause_clients(False)
        except ConflictError:
            # clients stay paused for now; the paused-veto above makes the
            # next pass retry the unpause instead of early-returning
            log.warning("unpause hit persistent 409s; next pass retries")
        return result

    def run_loop(self, interval_s: float = 15.0, once: bool = False) -> None:
        while True:
            try:
                self.reconcile_once()
            except Exception:
                log.exception("slice reconcile pass failed")
            if once:
                return
            time.sleep(interval_s)


def main(argv=None) -> int:
    import argparse

    logging.basicConfig(level="INFO")
    p = argparse.ArgumentParser("tpu-slice-manager")
    p.add_argument("--node-name", default=os.environ.get("NODE_NAME", ""))
    p.add_argument(
        "--config-file",
        default=os.environ.get("SLICE_CONFIG_FILE", "/slice-config/config.yaml"),
    )
    p.add_argument(
        "--chip-clients-file",
        default=os.environ.get("CHIP_CLIENTS_FILE", "/chip-clients/clients.yaml"),
    )
    p.add_argument("--partition-file", default=DEFAULT_PARTITION_FILE)
    # CDI spec regeneration is opt-in: the operator injects CDI_SPEC_PATH
    # only when cp.spec.cdi is enabled (object_controls.transform_slice_manager);
    # an empty default keeps CDI-off clusters from writing host specs
    p.add_argument(
        "--cdi-spec",
        default=os.environ.get("CDI_SPEC_PATH", ""),
    )
    p.add_argument("--interval", type=float, default=15.0)
    p.add_argument("--once", action="store_true")
    args = p.parse_args(argv)
    if not args.node_name:
        log.error("NODE_NAME required")
        return 1
    from tpu_operator.kube.rest import RestClient

    SliceManager(
        RestClient(),
        args.node_name,
        config_file=args.config_file,
        chip_clients_file=args.chip_clients_file,
        partition_file=args.partition_file,
        cdi_spec_path=args.cdi_spec,
    ).run_loop(interval_s=args.interval, once=args.once)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
