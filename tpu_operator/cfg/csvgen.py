"""OLM ClusterServiceVersion generation for the bundle.

The reference ships hand-maintained per-release CSVs under ``bundle/<ver>/``
(SURVEY.md §2.1 #15). Here the CSV is generated from the same sources the
rest of the repo already treats as truth — the sample ClusterPolicy
(``config/samples``), the operator Deployment (``config/manager``), and the
RBAC rules (``config/rbac``) — so bundle, kustomize base, and chart can
never drift apart. ``tpuop-cfg generate csv`` prints it; ``tpuop-cfg
validate csv`` (reference ``cmd/gpuop-cfg/validate/csv/csv.go:1-117``)
checks the on-disk bundle is fresh and its images resolvable.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List

import yaml

from tpu_operator import consts

OPERATOR_VERSION = consts.VERSION

DESCRIPTION = """\
The TPU Operator manages the software needed to provision Cloud TPU nodes
in a Kubernetes cluster: libtpu install, TPU device plugin, runtime/CDI
wiring, slice partitioning, feature discovery, metrics export, and an
end-to-end JAX validation harness — all driven by a single cluster-scoped
ClusterPolicy resource reconciled through an ordered state machine.
"""


def _load_yaml(path: str):
    with open(path) as f:
        return list(yaml.safe_load_all(f))


def build_csv(
    config_dir: str = "config",
    version: str = OPERATOR_VERSION,
    replaces: str = "",
    skips: List[str] = (),
) -> Dict[str, Any]:
    sample = _load_yaml(os.path.join(config_dir, "samples", "v1_clusterpolicy.yaml"))[0]
    deployment = _load_yaml(os.path.join(config_dir, "manager", "manager.yaml"))[0]
    rbac_docs = _load_yaml(os.path.join(config_dir, "rbac", "role.yaml"))
    cluster_rules: List[dict] = []
    for doc in rbac_docs:
        if doc and doc.get("kind") == "ClusterRole":
            cluster_rules.extend(doc.get("rules", []))

    dep_spec = deployment["spec"]
    service_account = dep_spec["template"]["spec"]["serviceAccountName"]
    operator_image = dep_spec["template"]["spec"]["containers"][0]["image"]

    related = [{"name": "tpu-operator", "image": operator_image}]
    for key, sub in sorted(sample.get("spec", {}).items()):
        if not isinstance(sub, dict) or "image" not in sub:
            continue
        repo, img, ver = sub.get("repository", ""), sub["image"], sub.get("version", "")
        # always emit the entry, even when repository/version are missing:
        # an incomplete ref renders untagged and validate_csv's unpinned
        # check flags it — silently dropping it would hide exactly the
        # misconfiguration the pinning check exists to catch
        ref = f"{repo}/{img}" if repo else img
        if ver:
            sep = "@" if ver.startswith("sha256:") else ":"
            ref = f"{ref}{sep}{ver}"
        related.append({"name": img, "image": ref})

    spec_extra: Dict[str, Any] = {}
    if replaces:
        # OLM upgrade graph (reference per-release CSVs carry
        # `replaces: gpu-operator-certified.v<prev>`)
        spec_extra["replaces"] = f"tpu-operator.v{replaces.lstrip('v')}"
    if skips:
        spec_extra["skips"] = [f"tpu-operator.v{s.lstrip('v')}" for s in skips]

    return {
        "apiVersion": "operators.coreos.com/v1alpha1",
        "kind": "ClusterServiceVersion",
        "metadata": {
            "name": f"tpu-operator.v{version}",
            "namespace": "placeholder",
            "annotations": {
                "alm-examples": json.dumps([sample], indent=2),
                "operators.operatorframework.io/builder": "tpuop-cfg",
                "operators.operatorframework.io/project_layout": "python",
                "capabilities": "Deep Insights",
                "categories": "AI/Machine Learning, OpenShift Optional",
                "description": "Automates provisioning of Cloud TPU nodes.",
                "provider": "tpu-operator authors",
            },
        },
        "spec": {
            "displayName": "TPU Operator",
            "description": DESCRIPTION,
            "version": version,
            "maturity": "alpha",
            "provider": {"name": "tpu-operator authors"},
            "keywords": ["tpu", "jax", "xla", "device plugin", "accelerator"],
            "maintainers": [{"name": "tpu-operator authors"}],
            "links": [],
            "minKubeVersion": "1.24.0",
            "installModes": [
                {"type": "OwnNamespace", "supported": True},
                {"type": "SingleNamespace", "supported": True},
                {"type": "MultiNamespace", "supported": False},
                {"type": "AllNamespaces", "supported": False},
            ],
            "customresourcedefinitions": {
                "owned": [
                    {
                        "name": consts.CRD_NAME,
                        "kind": "ClusterPolicy",
                        "version": "v1",
                        "displayName": "ClusterPolicy",
                        "description": "Desired state of the TPU software "
                        "stack on every TPU node.",
                    }
                ]
            },
            "install": {
                "strategy": "deployment",
                "spec": {
                    "clusterPermissions": [
                        {"serviceAccountName": service_account, "rules": cluster_rules}
                    ],
                    "deployments": [
                        {"name": deployment["metadata"]["name"], "spec": dep_spec}
                    ],
                },
            },
            "relatedImages": related,
            **spec_extra,
        },
    }


def render_csv_yaml(config_dir: str = "config") -> str:
    return yaml.safe_dump(build_csv(config_dir), sort_keys=False, width=100)


def validate_csv(
    path: str, config_dir: str = "config", check_fresh: bool = True
) -> List[str]:
    """Problems list (empty = valid): decodability, alm-examples validity,
    owned-CRD consistency, image resolvability, freshness vs generator
    (``check_fresh=False`` for historical release bundles, which are
    frozen snapshots of older sources)."""
    from tpu_operator.cfg.main import validate_clusterpolicy_obj

    problems: List[str] = []
    try:
        with open(path) as f:
            csv = yaml.safe_load(f)
    except (OSError, yaml.YAMLError) as e:
        return [f"cannot read {path}: {e}"]
    if not isinstance(csv, dict) or csv.get("kind") != "ClusterServiceVersion":
        return [f"{path}: not a ClusterServiceVersion"]

    # alm-examples decode + validate (reference csv.go alm-examples check)
    alm = csv.get("metadata", {}).get("annotations", {}).get("alm-examples", "[]")
    try:
        examples = json.loads(alm)
    except json.JSONDecodeError as e:
        examples = []
        problems.append(f"alm-examples not valid JSON: {e}")
    if not isinstance(examples, list) or not all(
        isinstance(e, dict) for e in examples
    ):
        problems.append("alm-examples is not a list of objects")
        examples = [e for e in examples if isinstance(e, dict)] if isinstance(
            examples, list
        ) else []
    cps = [e for e in examples if e.get("kind") == "ClusterPolicy"]
    if not cps:
        problems.append("alm-examples has no ClusterPolicy example")
    for example in cps:
        problems.extend(validate_clusterpolicy_obj(example))

    # owned CRD (reference csv.go owned-CRD check)
    owned = (
        csv.get("spec", {})
        .get("customresourcedefinitions", {})
        .get("owned", [])
    )
    names = {(o.get("name"), o.get("version"), o.get("kind")) for o in owned}
    if (consts.CRD_NAME, "v1", "ClusterPolicy") not in names:
        problems.append(
            f"owned CRDs {sorted(names)} missing "
            f"({consts.CRD_NAME!r}, 'v1', 'ClusterPolicy')"
        )

    # every image pinned (reference images.go)
    for entry in csv.get("spec", {}).get("relatedImages", []):
        image = entry.get("image", "")
        if ":" not in image.rsplit("/", 1)[-1] and "@" not in image:
            problems.append(f"relatedImage {entry.get('name')}: {image!r} unpinned")
    for dep in (
        csv.get("spec", {}).get("install", {}).get("spec", {}).get("deployments", [])
    ):
        if not isinstance(dep, dict):
            problems.append(f"install.spec.deployments entry not an object: {dep!r}")
            continue
        pod_spec = (
            (dep.get("spec") or {}).get("template", {}) or {}
        ).get("spec", {}) or {}
        containers = pod_spec.get("containers")
        if not containers:
            problems.append(
                f"deployment {dep.get('name', '?')}: no pod template containers"
            )
            continue
        for ctr in containers:
            image = ctr.get("image", "")
            if ":" not in image.rsplit("/", 1)[-1] and "@" not in image:
                problems.append(
                    f"deployment container {ctr.get('name', '?')}: {image!r} unpinned"
                )

    # freshness vs the generator (same pattern as the chart CRD check);
    # compare at the CSV's own version/graph position so versioned
    # release bundles validate too
    if check_fresh and os.path.isdir(config_dir):
        spec = csv.get("spec", {})
        version = str(spec.get("version", OPERATOR_VERSION))
        if version != OPERATOR_VERSION:
            # check_fresh means "this should be the CURRENT release": a
            # version left behind after a versions.mk bump must fail
            # standalone `validate csv`, not only `validate bundle`
            problems.append(
                f"{path}: version {version} != current {OPERATOR_VERSION}; "
                "run 'make bundle'"
            )
        replaces = str(spec.get("replaces", "")).removeprefix("tpu-operator.v")
        skips = [
            s.removeprefix("tpu-operator.v") for s in spec.get("skips", [])
        ]
        if csv != build_csv(config_dir, version=version, replaces=replaces, skips=skips):
            problems.append(
                f"{path} is stale; regenerate with 'make bundle' "
                "(tpuop-cfg release bundle keeps the replaces edge)"
            )
    return problems
