"""OpenAPI v3 structural-schema validation.

The enforcement half of ``crdgen``: the reference relies on the apiserver
validating CRs against the CRD's ``openAPIV3Schema`` (hand-maintained in
``deployments/gpu-operator/crds/nvidia.com_clusterpolicies_crd.yaml``).
This module implements the subset of OpenAPI v3 validation that the
generated CRD uses — types, enums, patterns, numeric bounds, typed maps
(``additionalProperties``) and ``x-kubernetes-preserve-unknown-fields`` —
so both ``tpuop-cfg validate`` and the test apiserver (kubesim) reject a
malformed CR exactly where a real apiserver would: at admission.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List


def validate(schema: Dict[str, Any], obj: Any, path: str = "") -> List[str]:
    """Validate ``obj`` against an openAPIV3Schema node; returns problems
    (empty = valid). ``path`` is the JSON path prefix for messages."""
    problems: List[str] = []
    where = path or "<root>"

    if schema.get("x-kubernetes-preserve-unknown-fields") and "type" not in schema:
        return problems

    if schema.get("x-kubernetes-int-or-string"):
        # apiserver semantics: integer or string ONLY (floats rejected);
        # `pattern` applies to the string arm
        if isinstance(obj, bool) or not isinstance(obj, (int, str)):
            return [f"{where}: expected int-or-string, got {type(obj).__name__}"]
        if isinstance(obj, str):
            pat = schema.get("pattern")
            if pat and not re.search(pat, obj):
                problems.append(f"{where}: {obj!r} does not match {pat!r}")
        return problems

    t = schema.get("type")
    if t == "object":
        if not isinstance(obj, dict):
            return [f"{where}: expected object, got {type(obj).__name__}"]
        props = schema.get("properties", {})
        addl = schema.get("additionalProperties")
        preserve = schema.get("x-kubernetes-preserve-unknown-fields", False)
        for key, value in obj.items():
            child = f"{path}.{key}" if path else key
            if key in props:
                problems += validate(props[key], value, child)
            elif isinstance(addl, dict):
                problems += validate(addl, value, child)
            elif props and not preserve and addl is None:
                # structural schemas prune unknown fields; flag them so
                # `cfg validate` catches typos the apiserver would drop
                problems.append(f"{child}: unknown field")
        for req in schema.get("required", []):
            if req not in obj:
                problems.append(f"{where}: missing required field {req!r}")
    elif t == "array":
        if not isinstance(obj, list):
            return [f"{where}: expected array, got {type(obj).__name__}"]
        item_schema = schema.get("items", {})
        for i, item in enumerate(obj):
            problems += validate(item_schema, item, f"{path}[{i}]")
    elif t == "string":
        if not isinstance(obj, str):
            return [f"{where}: expected string, got {type(obj).__name__}"]
        pat = schema.get("pattern")
        if pat and not re.search(pat, obj):
            # k8s applies `pattern` unanchored (re.search semantics);
            # the generated patterns anchor themselves with ^...$
            problems.append(f"{where}: {obj!r} does not match {pat!r}")
    elif t == "boolean":
        if not isinstance(obj, bool):
            return [f"{where}: expected boolean, got {type(obj).__name__}"]
    elif t == "integer":
        if isinstance(obj, bool) or not isinstance(obj, int):
            return [f"{where}: expected integer, got {type(obj).__name__}"]
    elif t == "number":
        if isinstance(obj, bool) or not isinstance(obj, (int, float)):
            return [f"{where}: expected number, got {type(obj).__name__}"]

    if "enum" in schema and obj not in schema["enum"]:
        problems.append(
            f"{where}: {obj!r} not in {schema['enum']}"
        )
    if isinstance(obj, (int, float)) and not isinstance(obj, bool):
        if "minimum" in schema and obj < schema["minimum"]:
            problems.append(f"{where}: {obj} below minimum {schema['minimum']}")
        if "maximum" in schema and obj > schema["maximum"]:
            problems.append(f"{where}: {obj} above maximum {schema['maximum']}")
    return problems


def apply_defaults(schema: Dict[str, Any], obj: Any) -> None:
    """Structural-schema defaulting, in place (apiserver semantics:
    defaulting happens at decode time, BEFORE validation, and applies
    only inside objects that are present in the payload — an absent
    sub-object does not get materialized just because its children have
    defaults)."""
    if schema.get("type") == "object" and isinstance(obj, dict):
        props = schema.get("properties", {})
        for key, prop_schema in props.items():
            if key not in obj and "default" in prop_schema:
                import copy

                obj[key] = copy.deepcopy(prop_schema["default"])
            if key in obj:
                apply_defaults(prop_schema, obj[key])
        addl = schema.get("additionalProperties")
        if isinstance(addl, dict):
            for value in obj.values():
                apply_defaults(addl, value)
    elif schema.get("type") == "array" and isinstance(obj, list):
        item_schema = schema.get("items", {})
        for item in obj:
            apply_defaults(item_schema, item)


def default_cr(crd: Dict[str, Any], cr_obj: Dict[str, Any]) -> None:
    """Apply the CRD's schema defaults to a CR in place (metadata is the
    apiserver's own domain and is skipped, matching ``validate_cr``)."""
    schema = crd_schema(crd)
    for key, prop_schema in schema.get("properties", {}).items():
        if key == "metadata":
            continue
        if key not in cr_obj and "default" in prop_schema:
            import copy

            cr_obj[key] = copy.deepcopy(prop_schema["default"])
        if key in cr_obj:
            apply_defaults(prop_schema, cr_obj[key])


def crd_schema(crd: Dict[str, Any], version: str = "v1") -> Dict[str, Any]:
    """Extract the openAPIV3Schema for ``version`` from a CRD manifest."""
    for v in crd.get("spec", {}).get("versions", []):
        if v.get("name") == version:
            return v.get("schema", {}).get("openAPIV3Schema", {})
    raise KeyError(f"CRD has no version {version!r}")


def validate_cr(crd: Dict[str, Any], cr_obj: Dict[str, Any]) -> List[str]:
    """Validate a CR object against its CRD the way the apiserver would;
    ``metadata`` is validated by the apiserver's own rules, not the CRD
    schema, so it is skipped here."""
    schema = crd_schema(crd)
    trimmed = {k: v for k, v in cr_obj.items() if k != "metadata"}
    return validate(schema, trimmed)
