"""OLM release engineering: versioned bundles + upgrade-graph validation.

The reference ships one bundle directory per release
(``bundle/<version>/manifests`` + ``metadata``) whose CSV carries a
``replaces: <previous>`` edge, forming the OLM upgrade graph
(``bundle/v1.10.1/manifests/gpu-operator-certified.clusterserviceversion.yaml:684``).
Round 1 shipped a single unversioned bundle with no graph; this module
adds:

* ``cut_release(version, replaces)`` — writes ``bundle/<version>/``
  (manifests: CSV + CRD; metadata: annotations) and refreshes the
  top-level ``bundle/manifests`` to the new head;
* ``validate_bundle_tree(bundle_dir)`` — the ``operator-sdk bundle
  validate`` slot: annotations sanity, per-release CSV/CRD sanity, and
  a well-formed upgrade graph (single head, acyclic ``replaces`` chain
  whose every edge lands on a shipped version).
"""

from __future__ import annotations

import os
import re
import shutil
from typing import Any, Dict, List

import yaml

from tpu_operator import consts
from tpu_operator.cfg.csvgen import (
    OPERATOR_VERSION,
    build_csv,
    validate_csv,
)

_VERSION_DIR = re.compile(r"^v\d+\.\d+\.\d+$")
CSV_NAME = "tpu-operator.clusterserviceversion.yaml"


def _crd_filename() -> str:
    return f"{consts.GROUP}_clusterpolicies.yaml"


def cut_release(
    version: str,
    replaces: str = "",
    bundle_dir: str = "bundle",
    config_dir: str = "config",
) -> str:
    """Write ``bundle/v<version>/`` and refresh the top-level bundle to
    match (the reference keeps the newest release mirrored at
    ``bundle/manifests``). Returns the release directory path."""
    from tpu_operator.cfg.crdgen import render_crd_yaml

    ver = version.lstrip("v")
    rel_dir = os.path.join(bundle_dir, f"v{ver}")
    manifests = os.path.join(rel_dir, "manifests")
    metadata = os.path.join(rel_dir, "metadata")
    os.makedirs(manifests, exist_ok=True)
    os.makedirs(metadata, exist_ok=True)

    csv = build_csv(config_dir, version=ver, replaces=replaces)
    csv_yaml = yaml.safe_dump(csv, sort_keys=False, width=100)
    with open(os.path.join(manifests, CSV_NAME), "w") as f:
        f.write(csv_yaml)
    with open(os.path.join(manifests, _crd_filename()), "w") as f:
        f.write(render_crd_yaml())
    shutil.copy(
        os.path.join(bundle_dir, "metadata", "annotations.yaml"),
        os.path.join(metadata, "annotations.yaml"),
    )
    # head mirror: top-level manifests == newest release
    with open(os.path.join(bundle_dir, "manifests", CSV_NAME), "w") as f:
        f.write(csv_yaml)
    with open(
        os.path.join(bundle_dir, "manifests", _crd_filename()), "w"
    ) as f:
        f.write(render_crd_yaml())
    return rel_dir


def _load(path: str):
    with open(path) as f:
        return yaml.safe_load(f)


def validate_bundle_tree(
    bundle_dir: str = "bundle", config_dir: str = "config"
) -> List[str]:
    """The ``operator-sdk bundle validate`` slot, run in CI."""
    problems: List[str] = []

    # -- annotations -----------------------------------------------------
    ann_path = os.path.join(bundle_dir, "metadata", "annotations.yaml")
    try:
        ann = _load(ann_path)["annotations"]
    except Exception as e:
        return [f"{ann_path}: unreadable ({e})"]
    for key, want in (
        ("operators.operatorframework.io.bundle.mediatype.v1", "registry+v1"),
        ("operators.operatorframework.io.bundle.manifests.v1", "manifests/"),
        ("operators.operatorframework.io.bundle.metadata.v1", "metadata/"),
        ("operators.operatorframework.io.bundle.package.v1", "tpu-operator"),
    ):
        if ann.get(key) != want:
            problems.append(f"annotations: {key} = {ann.get(key)!r}, want {want!r}")
    default_channel = ann.get(
        "operators.operatorframework.io.bundle.channel.default.v1", ""
    )
    channels = ann.get(
        "operators.operatorframework.io.bundle.channels.v1", ""
    ).split(",")
    if default_channel not in channels:
        problems.append(
            f"annotations: default channel {default_channel!r} not in {channels}"
        )

    # -- per-release bundles --------------------------------------------
    versions: Dict[str, Dict[str, Any]] = {}
    for entry in sorted(os.listdir(bundle_dir)):
        if not _VERSION_DIR.match(entry):
            continue
        rel = os.path.join(bundle_dir, entry)
        csv_path = os.path.join(rel, "manifests", CSV_NAME)
        crd_path = os.path.join(rel, "manifests", _crd_filename())
        meta_path = os.path.join(rel, "metadata", "annotations.yaml")
        for req in (csv_path, crd_path, meta_path):
            if not os.path.exists(req):
                problems.append(f"{entry}: missing {os.path.relpath(req, rel)}")
        if not os.path.exists(csv_path):
            continue
        csv = _load(csv_path)
        ver = entry[1:]
        if csv.get("metadata", {}).get("name") != f"tpu-operator.v{ver}":
            problems.append(
                f"{entry}: CSV name {csv.get('metadata', {}).get('name')!r} "
                f"!= tpu-operator.v{ver}"
            )
        if str(csv.get("spec", {}).get("version")) != ver:
            problems.append(
                f"{entry}: spec.version {csv.get('spec', {}).get('version')!r} != {ver}"
            )
        if os.path.exists(crd_path):
            crd = _load(crd_path)
            if crd.get("metadata", {}).get("name") != consts.CRD_NAME:
                problems.append(f"{entry}: wrong CRD {crd.get('metadata', {}).get('name')!r}")
        # full CSV lint; freshness only for the current release (older
        # bundles are frozen snapshots of older sources)
        problems += [
            f"{entry}: {p}"
            for p in validate_csv(
                csv_path,
                config_dir=config_dir,
                check_fresh=(ver == OPERATOR_VERSION),
            )
        ]
        versions[ver] = csv

    if not versions:
        problems.append(f"{bundle_dir}: no versioned release bundles (bundle/vX.Y.Z)")
        return problems

    # -- upgrade graph ---------------------------------------------------
    replaces: Dict[str, str] = {}
    for ver, csv in versions.items():
        target = str(csv.get("spec", {}).get("replaces", ""))
        if target:
            target_ver = target.removeprefix("tpu-operator.v")
            if target_ver not in versions:
                problems.append(
                    f"v{ver}: replaces {target!r} which is not a shipped bundle"
                )
            replaces[ver] = target_ver
        # skips edges are graph edges too: in this self-contained tree
        # every skipped version must be a shipped bundle
        for skip in csv.get("spec", {}).get("skips", []):
            skip_ver = str(skip).removeprefix("tpu-operator.v")
            if skip_ver not in versions:
                problems.append(
                    f"v{ver}: skips {skip!r} which is not a shipped bundle"
                )

    replaced = set(replaces.values())
    heads = [v for v in versions if v not in replaced]
    if len(heads) != 1:
        problems.append(
            f"upgrade graph must have exactly one head, got {sorted(heads)}"
        )
    else:
        # walk the chain head -> tail; every shipped version reachable
        seen = []
        cur = heads[0]
        while cur is not None and cur not in seen:
            seen.append(cur)
            cur = replaces.get(cur)
        if cur is not None:
            problems.append(f"upgrade graph has a replaces cycle at v{cur}")
        missing = set(versions) - set(seen)
        if missing:
            problems.append(
                f"versions unreachable from head v{heads[0]}: "
                f"{sorted('v' + m for m in missing)}"
            )
        if heads[0] != OPERATOR_VERSION:
            problems.append(
                f"graph head v{heads[0]} != current version v{OPERATOR_VERSION}"
            )

    # -- head mirror -----------------------------------------------------
    top_csv_path = os.path.join(bundle_dir, "manifests", CSV_NAME)
    if os.path.exists(top_csv_path):
        top = _load(top_csv_path)
        head_ver = heads[0] if len(heads) == 1 else OPERATOR_VERSION
        if head_ver in versions and top != versions[head_ver]:
            problems.append(
                "bundle/manifests CSV is not the graph head "
                f"(v{head_ver}); re-run cut_release"
            )
    else:
        problems.append("missing top-level bundle/manifests CSV")
    return problems
