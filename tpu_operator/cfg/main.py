"""``tpuop-cfg`` — config validation CLI (reference ``cmd/gpuop-cfg``).

Subcommands:
  validate clusterpolicy --input FILE   decode + image-resolution checks
                                        (reference ``cmd/gpuop-cfg/validate/
                                        clusterpolicy/clusterpolicy.go:30-112``)
  validate chart --dir DIR              chart values render a decodable CR
                                        (CSV-validation slot: we have no OLM
                                        bundle; the chart is the packaging)
  generate crd                          print the CRD manifest
"""

from __future__ import annotations

import argparse
import sys

import yaml

from tpu_operator.api.v1.clusterpolicy_types import (
    ClusterPolicySpec,
    clusterpolicy_from_obj,
)


def validate_clusterpolicy(path: str) -> list:
    """Returns a list of problems (empty = valid)."""
    with open(path) as f:
        obj = yaml.safe_load(f)
    if not isinstance(obj, dict):
        return [f"{path}: not a mapping"]
    return validate_clusterpolicy_obj(obj)


def validate_clusterpolicy_obj(obj: dict) -> list:
    problems = []
    if obj.get("kind") != "ClusterPolicy":
        problems.append(f"kind is {obj.get('kind')!r}, want ClusterPolicy")
    # schema validation first — exactly what the apiserver enforces at
    # admission against the generated CRD (enums, typed maps, bounds)
    from tpu_operator.cfg.crdgen import build_crd
    from tpu_operator.cfg.schema_validate import validate_cr

    problems += validate_cr(build_crd(), obj)
    try:
        cp = clusterpolicy_from_obj(obj)
    except Exception as e:
        # a CR the apiserver would reject may not decode at all; report
        # the admission problems instead of crashing on the decoder
        problems.append(f"spec does not decode: {e}")
        return problems
    spec = cp.spec
    # every enabled operand must resolve to a pullable image ref
    # (reference checks image paths resolve, images.go:1-171)
    named = [
        ("libtpu", spec.libtpu),
        ("runtime", spec.runtime),
        ("devicePlugin", spec.device_plugin),
        ("metricsd", spec.metricsd),
        ("metricsExporter", spec.metrics_exporter),
        ("nodeStatusExporter", spec.node_status_exporter),
        ("tfd", spec.tfd),
        ("sliceManager", spec.slice_manager),
        ("validator", spec.validator),
    ]
    for name, sub in named:
        if not sub.is_enabled():
            continue
        image = sub.image_path()
        if not image:
            problems.append(f"spec.{name}: no image (repository/image/version or env)")
        elif ":" not in image.rsplit("/", 1)[-1] and "@" not in image:
            problems.append(f"spec.{name}: image {image!r} has no tag or digest")
    if spec.slice.strategy not in ("none", "single", "mixed"):
        problems.append(f"spec.slice.strategy {spec.slice.strategy!r} invalid")
    if spec.sandbox_workloads.default_workload not in (
        "container",
        "vm-passthrough",
    ):
        problems.append(
            f"spec.sandboxWorkloads.defaultWorkload "
            f"{spec.sandbox_workloads.default_workload!r} invalid"
        )
    pol = spec.libtpu.upgrade_policy
    if pol is not None:
        mu = str(pol.max_unavailable)
        if mu.endswith("%"):
            try:
                float(mu[:-1])
            except ValueError:
                problems.append(f"upgradePolicy.maxUnavailable {mu!r} invalid")
        if pol.max_parallel_upgrades < 0:
            problems.append("upgradePolicy.maxParallelUpgrades negative")
    return problems


def validate_chart(chart_dir: str) -> list:
    """The chart's values must decode as a ClusterPolicySpec and the CRD in
    crds/ must match the generated one."""
    import os

    problems = []
    values_path = os.path.join(chart_dir, "values.yaml")
    try:
        with open(values_path) as f:
            values = yaml.safe_load(f) or {}
    except OSError as e:
        return [f"cannot read {values_path}: {e}"]
    # chart values mirror the CR spec 1:1 (reference values.yaml shape)
    ClusterPolicySpec.from_dict(values)
    crd_path = os.path.join(chart_dir, "crds", "tpu.k8s.io_clusterpolicies.yaml")
    if not os.path.exists(crd_path):
        problems.append(f"missing CRD at {crd_path}")
    else:
        from tpu_operator.cfg.crdgen import build_crd

        with open(crd_path) as f:
            on_disk = yaml.safe_load(f)
        if on_disk != build_crd():
            problems.append(
                f"{crd_path} is stale; regenerate with 'tpuop-cfg generate crd'"
            )
    return problems


def main(argv=None) -> int:
    p = argparse.ArgumentParser("tpuop-cfg")
    sub = p.add_subparsers(dest="cmd", required=True)
    v = sub.add_parser("validate")
    vsub = v.add_subparsers(dest="what", required=True)
    vcp = vsub.add_parser("clusterpolicy")
    vcp.add_argument("--input", required=True)
    vch = vsub.add_parser("chart")
    vch.add_argument("--dir", required=True)
    vcsv = vsub.add_parser("csv")
    vcsv.add_argument("--input", required=True)
    vcsv.add_argument("--config-dir", default="config")
    vb = vsub.add_parser("bundle")
    vb.add_argument("--dir", default="bundle")
    vb.add_argument("--config-dir", default="config")
    g = sub.add_parser("generate")
    gsub = g.add_subparsers(dest="what", required=True)
    gsub.add_parser("crd")
    gcsv = gsub.add_parser("csv")
    gcsv.add_argument("--config-dir", default="config")
    r = sub.add_parser("release")
    rsub = r.add_subparsers(dest="what", required=True)
    rb = rsub.add_parser("bundle")
    rb.add_argument("--version", required=True)
    rb.add_argument("--replaces", default="")
    rb.add_argument("--bundle-dir", default="bundle")
    rb.add_argument("--config-dir", default="config")
    args = p.parse_args(argv)

    if args.cmd == "validate" and args.what == "clusterpolicy":
        problems = validate_clusterpolicy(args.input)
    elif args.cmd == "validate" and args.what == "chart":
        problems = validate_chart(args.dir)
    elif args.cmd == "validate" and args.what == "csv":
        from tpu_operator.cfg.csvgen import validate_csv

        problems = validate_csv(args.input, config_dir=args.config_dir)
    elif args.cmd == "generate" and args.what == "crd":
        from tpu_operator.cfg.crdgen import render_crd_yaml

        sys.stdout.write(render_crd_yaml())
        return 0
    elif args.cmd == "generate" and args.what == "csv":
        from tpu_operator.cfg.csvgen import render_csv_yaml

        sys.stdout.write(render_csv_yaml(args.config_dir))
        return 0
    elif args.cmd == "validate" and args.what == "bundle":
        from tpu_operator.cfg.release import validate_bundle_tree

        problems = validate_bundle_tree(args.dir, config_dir=args.config_dir)
    elif args.cmd == "release" and args.what == "bundle":
        from tpu_operator.cfg.release import cut_release

        rel = cut_release(
            args.version,
            replaces=args.replaces,
            bundle_dir=args.bundle_dir,
            config_dir=args.config_dir,
        )
        print(f"release bundle written: {rel}")
        return 0
    else:  # pragma: no cover
        p.error("unknown command")
        return 2

    for prob in problems:
        print(f"INVALID: {prob}", file=sys.stderr)
    if problems:
        return 1
    print("OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
