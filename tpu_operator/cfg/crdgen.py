"""CRD manifest generation from the ClusterPolicy dataclasses.

The reference ships a hand-maintained 1.5k-line CRD YAML
(``deployments/gpu-operator/crds/nvidia.com_clusterpolicies_crd.yaml``)
plus controller-gen. Here the dataclasses are the single source of truth:
the openAPI v3 schema is derived by introspection, so spec fields can never
drift from the decoder.
"""

from __future__ import annotations

import dataclasses
import typing
from typing import Any, Dict

from tpu_operator import consts
from tpu_operator.api.v1 import clusterpolicy_types as cpt


# Typed toleration items (reference CRD depth: the hand-maintained
# nvidia.com CRD schema types tolerations fully rather than
# preserve-unknown-fields)
TOLERATION_SCHEMA: Dict[str, Any] = {
    "type": "object",
    "properties": {
        "key": {"type": "string"},
        "operator": {"type": "string", "enum": ["Exists", "Equal"]},
        "value": {"type": "string"},
        "effect": {
            "type": "string",
            "enum": ["NoSchedule", "PreferNoSchedule", "NoExecute"],
        },
        # plain int64 like the k8s core type: negative values are legal
        # (documented as "treated as 0"), so no minimum here
        "tolerationSeconds": {"type": "integer"},
    },
}

# Validation enrichment keyed by the serialized field name. The decoder
# stays permissive (Python dataclasses); the apiserver enforces these at
# admission — a malformed CR is rejected before it reaches the operator.
_FIELD_RULES: Dict[str, Dict[str, Any]] = {
    "imagePullPolicy": {"enum": ["Always", "IfNotPresent", "Never"]},
    "updateStrategy": {"enum": ["RollingUpdate", "OnDelete"]},
    "defaultRuntime": {"enum": ["docker", "containerd", "crio"]},
    "defaultWorkload": {"enum": ["container", "vm-passthrough"]},
    # k8s intstr convention: `maxUnavailable: 1` (int) and `"25%"` are
    # both valid; the pattern constrains the string arm only
    "maxUnavailable": {
        "x-kubernetes-int-or-string": True,
        "pattern": r"^\d+%?$",
    },
    "timeoutSeconds": {"minimum": 0},
    "maxParallelUpgrades": {"minimum": 0},
    # remediation FSM knobs: the breaker threshold is int-or-percent like
    # maxUnavailable; attempts/backoff are plain non-negative integers
    "systemicThreshold": {
        "x-kubernetes-int-or-string": True,
        "pattern": r"^\d+%?$",
    },
    "maxAttempts": {"minimum": 0},
    "backoffSeconds": {"minimum": 0},
    # rollout stage sizes are int-or-percent of the fleet's slices, like
    # maxUnavailable; the health-gate knobs are plain bounded integers
    "canary": {
        "x-kubernetes-int-or-string": True,
        "pattern": r"^\d+%?$",
    },
    "waves": {
        "items": {
            "x-kubernetes-int-or-string": True,
            "pattern": r"^\d+%?$",
        }
    },
    "observeSeconds": {"minimum": 0},
    "tflopsDegradedPct": {"minimum": 0, "maximum": 100},
    "membwDegradedPct": {"minimum": 0, "maximum": 100},
    "allocP99DegradedPct": {"minimum": 0},
    "hostPort": {"minimum": 1, "maximum": 65535},
    "tolerations": {"items": TOLERATION_SCHEMA},
    # k8s Quantities: `cpu: 2` and `cpu: "2"` are both valid, so these
    # maps take int-or-string values, not plain strings
    "limits": {
        "additionalProperties": {"x-kubernetes-int-or-string": True}
    },
    "requests": {
        "additionalProperties": {"x-kubernetes-int-or-string": True}
    },
}


def _schema_for(tp) -> Dict[str, Any]:
    tp = cpt._unwrap_optional(tp)
    origin = typing.get_origin(tp)
    if origin in (list, typing.List):
        (item,) = typing.get_args(tp) or (Any,)
        return {"type": "array", "items": _schema_for(item)}
    if origin in (dict, typing.Dict):
        args = typing.get_args(tp)
        # typed maps (labels/annotations/nodeSelector/...): enforce
        # string values instead of preserve-unknown-fields
        if args and args[1] is str:
            return {
                "type": "object",
                "additionalProperties": {"type": "string"},
            }
        return {"type": "object", "x-kubernetes-preserve-unknown-fields": True}
    if dataclasses.is_dataclass(tp):
        return _dataclass_schema(tp)
    if tp is str:
        return {"type": "string"}
    if tp is bool:
        return {"type": "boolean"}
    if tp is int:
        return {"type": "integer"}
    if tp is float:
        return {"type": "number"}
    return {"x-kubernetes-preserve-unknown-fields": True}


def _dataclass_schema(cls) -> Dict[str, Any]:
    hints = typing.get_type_hints(cls)
    props = {}
    for f in dataclasses.fields(cls):
        key = cpt._field_key(f)
        schema = _schema_for(hints[f.name])
        rules = _FIELD_RULES.get(key)
        if rules:
            if rules.get("x-kubernetes-int-or-string"):
                # int-or-string replaces the schema wholesale: a `type`
                # key would make the structural schema invalid
                schema = dict(rules)
            else:
                for rk, rv in rules.items():
                    if rk == "items":
                        if schema.get("type") == "array":
                            schema["items"] = rv
                        continue
                    if rk in ("minimum", "maximum") and schema.get(
                        "type"
                    ) not in ("integer", "number"):
                        continue  # bounds only apply to numerics
                    schema[rk] = rv
        # per-field overrides declared on the dataclass win over the table
        for meta_key in ("enum", "minimum", "maximum", "pattern"):
            if meta_key in f.metadata:
                schema[meta_key] = f.metadata[meta_key]
        doc = f.metadata.get("doc")
        if doc:
            schema["description"] = doc
        # structural-schema defaulting: the dataclass scalar defaults ARE
        # the defaults the decoder would apply, so stamping them into the
        # schema makes the apiserver materialize them at admission —
        # kubectl get then shows the effective config, exactly like the
        # reference's hand-maintained CRD defaults. k8s semantics:
        # defaults apply only within objects present in the payload, which
        # matches the decoder (absent sub-spec => absent defaults).
        if (
            f.default is not dataclasses.MISSING
            and isinstance(f.default, (str, int, float, bool))
            and f.default != ""
        ):
            schema["default"] = f.default
        props[key] = schema
    return {"type": "object", "properties": props}


def build_crd() -> Dict[str, Any]:
    spec_schema = _dataclass_schema(cpt.ClusterPolicySpec)
    status_schema = _dataclass_schema(cpt.ClusterPolicyStatus)
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": consts.CRD_NAME},
        "spec": {
            "group": consts.GROUP,
            "names": {
                "kind": consts.CLUSTER_POLICY_KIND,
                "listKind": "ClusterPolicyList",
                "plural": "clusterpolicies",
                "singular": "clusterpolicy",
            },
            "scope": "Cluster",
            "versions": [
                {
                    "name": "v1",
                    "served": True,
                    "storage": True,
                    "subresources": {"status": {}},
                    "additionalPrinterColumns": [
                        {
                            "jsonPath": ".status.state",
                            "name": "Status",
                            "type": "string",
                        },
                        {
                            "jsonPath": ".metadata.creationTimestamp",
                            "name": "Age",
                            "type": "date",
                        },
                    ],
                    "schema": {
                        "openAPIV3Schema": {
                            "type": "object",
                            "properties": {
                                "apiVersion": {"type": "string"},
                                "kind": {"type": "string"},
                                "metadata": {"type": "object"},
                                "spec": spec_schema,
                                "status": status_schema,
                            },
                        }
                    },
                }
            ],
        },
    }


def render_crd_yaml() -> str:
    import yaml

    return yaml.safe_dump(build_crd(), sort_keys=False)
