"""CRD manifest generation from the ClusterPolicy dataclasses.

The reference ships a hand-maintained 1.5k-line CRD YAML
(``deployments/gpu-operator/crds/nvidia.com_clusterpolicies_crd.yaml``)
plus controller-gen. Here the dataclasses are the single source of truth:
the openAPI v3 schema is derived by introspection, so spec fields can never
drift from the decoder.
"""

from __future__ import annotations

import dataclasses
import typing
from typing import Any, Dict

from tpu_operator import consts
from tpu_operator.api.v1 import clusterpolicy_types as cpt


def _schema_for(tp) -> Dict[str, Any]:
    tp = cpt._unwrap_optional(tp)
    origin = typing.get_origin(tp)
    if origin in (list, typing.List):
        (item,) = typing.get_args(tp) or (Any,)
        return {"type": "array", "items": _schema_for(item)}
    if origin in (dict, typing.Dict):
        return {"type": "object", "x-kubernetes-preserve-unknown-fields": True}
    if dataclasses.is_dataclass(tp):
        return _dataclass_schema(tp)
    if tp is str:
        return {"type": "string"}
    if tp is bool:
        return {"type": "boolean"}
    if tp is int:
        return {"type": "integer"}
    if tp is float:
        return {"type": "number"}
    return {"x-kubernetes-preserve-unknown-fields": True}


def _dataclass_schema(cls) -> Dict[str, Any]:
    hints = typing.get_type_hints(cls)
    props = {}
    for f in dataclasses.fields(cls):
        key = cpt._field_key(f)
        props[key] = _schema_for(hints[f.name])
        doc = f.metadata.get("doc")
        if doc:
            props[key]["description"] = doc
    return {"type": "object", "properties": props}


def build_crd() -> Dict[str, Any]:
    spec_schema = _dataclass_schema(cpt.ClusterPolicySpec)
    status_schema = _dataclass_schema(cpt.ClusterPolicyStatus)
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": consts.CRD_NAME},
        "spec": {
            "group": consts.GROUP,
            "names": {
                "kind": consts.CLUSTER_POLICY_KIND,
                "listKind": "ClusterPolicyList",
                "plural": "clusterpolicies",
                "singular": "clusterpolicy",
            },
            "scope": "Cluster",
            "versions": [
                {
                    "name": "v1",
                    "served": True,
                    "storage": True,
                    "subresources": {"status": {}},
                    "additionalPrinterColumns": [
                        {
                            "jsonPath": ".status.state",
                            "name": "Status",
                            "type": "string",
                        },
                        {
                            "jsonPath": ".metadata.creationTimestamp",
                            "name": "Age",
                            "type": "date",
                        },
                    ],
                    "schema": {
                        "openAPIV3Schema": {
                            "type": "object",
                            "properties": {
                                "apiVersion": {"type": "string"},
                                "kind": {"type": "string"},
                                "metadata": {"type": "object"},
                                "spec": spec_schema,
                                "status": status_schema,
                            },
                        }
                    },
                }
            ],
        },
    }


def render_crd_yaml() -> str:
    import yaml

    return yaml.safe_dump(build_crd(), sort_keys=False)
