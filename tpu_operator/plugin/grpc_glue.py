"""gRPC service/stub glue for the DevicePlugin API.

grpcio-tools isn't in the image, so the servicer/stub wiring that
``protoc-gen-grpc`` would emit is written here by hand against the
protoc-generated ``deviceplugin_pb2`` messages.
"""

from __future__ import annotations

import grpc

from tpu_operator.plugin.proto import pb2

API_VERSION = "v1beta1"
SERVICE_DEVICE_PLUGIN = "v1beta1.DevicePlugin"
SERVICE_REGISTRATION = "v1beta1.Registration"


def device_plugin_handler(servicer) -> grpc.GenericRpcHandler:
    """Build the generic handler for a DevicePlugin servicer exposing
    GetDevicePluginOptions / ListAndWatch / GetPreferredAllocation /
    Allocate / PreStartContainer methods."""
    rpcs = {
        "GetDevicePluginOptions": grpc.unary_unary_rpc_method_handler(
            servicer.GetDevicePluginOptions,
            request_deserializer=pb2.Empty.FromString,
            response_serializer=pb2.DevicePluginOptions.SerializeToString,
        ),
        "ListAndWatch": grpc.unary_stream_rpc_method_handler(
            servicer.ListAndWatch,
            request_deserializer=pb2.Empty.FromString,
            response_serializer=pb2.ListAndWatchResponse.SerializeToString,
        ),
        "GetPreferredAllocation": grpc.unary_unary_rpc_method_handler(
            servicer.GetPreferredAllocation,
            request_deserializer=pb2.GetPreferredAllocationRequest.FromString,
            response_serializer=pb2.GetPreferredAllocationResponse.SerializeToString,
        ),
        "Allocate": grpc.unary_unary_rpc_method_handler(
            servicer.Allocate,
            request_deserializer=pb2.AllocateRequest.FromString,
            response_serializer=pb2.AllocateResponse.SerializeToString,
        ),
        "PreStartContainer": grpc.unary_unary_rpc_method_handler(
            servicer.PreStartContainer,
            request_deserializer=pb2.PreStartContainerRequest.FromString,
            response_serializer=pb2.PreStartContainerResponse.SerializeToString,
        ),
    }
    return grpc.method_handlers_generic_handler(SERVICE_DEVICE_PLUGIN, rpcs)


def registration_handler(servicer) -> grpc.GenericRpcHandler:
    """Generic handler for a Registration servicer (used by the fake kubelet
    in tests; the real kubelet implements this side)."""
    rpcs = {
        "Register": grpc.unary_unary_rpc_method_handler(
            servicer.Register,
            request_deserializer=pb2.RegisterRequest.FromString,
            response_serializer=pb2.Empty.SerializeToString,
        ),
    }
    return grpc.method_handlers_generic_handler(SERVICE_REGISTRATION, rpcs)


class DevicePluginStub:
    """Client stub (what the kubelet uses against our server; tests use it
    to drive the plugin end-to-end)."""

    def __init__(self, channel: grpc.Channel):
        base = f"/{SERVICE_DEVICE_PLUGIN}/"
        self.GetDevicePluginOptions = channel.unary_unary(
            base + "GetDevicePluginOptions",
            request_serializer=pb2.Empty.SerializeToString,
            response_deserializer=pb2.DevicePluginOptions.FromString,
        )
        self.ListAndWatch = channel.unary_stream(
            base + "ListAndWatch",
            request_serializer=pb2.Empty.SerializeToString,
            response_deserializer=pb2.ListAndWatchResponse.FromString,
        )
        self.GetPreferredAllocation = channel.unary_unary(
            base + "GetPreferredAllocation",
            request_serializer=pb2.GetPreferredAllocationRequest.SerializeToString,
            response_deserializer=pb2.GetPreferredAllocationResponse.FromString,
        )
        self.Allocate = channel.unary_unary(
            base + "Allocate",
            request_serializer=pb2.AllocateRequest.SerializeToString,
            response_deserializer=pb2.AllocateResponse.FromString,
        )
        self.PreStartContainer = channel.unary_unary(
            base + "PreStartContainer",
            request_serializer=pb2.PreStartContainerRequest.SerializeToString,
            response_deserializer=pb2.PreStartContainerResponse.FromString,
        )


class RegistrationStub:
    def __init__(self, channel: grpc.Channel):
        self.Register = channel.unary_unary(
            f"/{SERVICE_REGISTRATION}/Register",
            request_serializer=pb2.RegisterRequest.SerializeToString,
            response_deserializer=pb2.Empty.FromString,
        )
