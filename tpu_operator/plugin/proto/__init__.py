import os
import sys

# protoc --python_out generates a flat import-style module; expose it as a
# package member regardless of how the process was launched.
_here = os.path.dirname(__file__)
if _here not in sys.path:
    sys.path.insert(0, _here)

import deviceplugin_pb2 as pb2  # noqa: E402,F401
