"""CDI spec generation — the TPU runtime-wiring core.

The reference's container-toolkit rewrites containerd/docker/crio configs
and installs a runtime hook (``controllers/object_controls.go:1052-1184``).
TPU-native collapses that to generating a Container Device Interface spec:
every chip becomes a named CDI device carrying its device nodes, the libtpu
mount and base env; runtimes with native CDI support inject them with no
custom hook binary.
"""

from __future__ import annotations

import os
from typing import List, Optional

import yaml

from tpu_operator import consts
from tpu_operator.native import tpuinfo

CDI_VERSION = "0.6.0"
CDI_KIND = "google.com/tpu"
DEFAULT_SPEC_PATH = "/var/run/cdi/google.com-tpu.yaml"
DEFAULT_PARTITION_FILE = "/run/tpu/partitions.json"


def _load_partitions(partition_file: str) -> Optional[dict]:
    import json

    try:
        with open(partition_file) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def build_spec(
    dev_root: str = "/dev",
    libtpu_dir: str = consts.LIBTPU_HOST_DIR,
    chips: Optional[List[dict]] = None,
    partition_file: str = DEFAULT_PARTITION_FILE,
) -> dict:
    """Every spec writer is partition-aware: when the slice manager has
    partitioned the host (``partitions.json``), one composite CDI device per
    subslice is included, so the device plugin's
    ``google.com/tpu=subslice-<id>-<shape>`` names always resolve no matter
    which operand wrote the spec last."""
    chips = chips if chips is not None else tpuinfo.chip_summary(dev_root)
    devices = []
    all_nodes = []
    for chip in chips:
        path = chip.get("path", os.path.join(dev_root, f"accel{chip['index']}"))
        node = {"path": path, "permissions": "rw"}
        all_nodes.append(node)
        devices.append(
            {
                "name": str(chip["index"]),
                "containerEdits": {
                    "deviceNodes": [dict(node)],
                    "env": [f"TPU_CHIP_{chip['index']}=present"],
                },
            }
        )
    # the "all" composite device mirrors nvidia.com/gpu=all
    devices.append(
        {
            "name": "all",
            "containerEdits": {"deviceNodes": [dict(n) for n in all_nodes]},
        }
    )
    partitions = _load_partitions(partition_file)
    if partitions and partitions.get("partitioned"):
        chip_nodes = {c["index"]: all_nodes[i] for i, c in enumerate(chips)}
        for sub in partitions.get("subslices", []):
            nodes = [
                dict(chip_nodes[c]) for c in sub["chips"] if c in chip_nodes
            ]
            devices.append(
                {
                    "name": f"subslice-{sub['id']}-{sub['shape']}",
                    "containerEdits": {"deviceNodes": nodes},
                }
            )
    return {
        "cdiVersion": CDI_VERSION,
        "kind": CDI_KIND,
        "containerEdits": {
            "mounts": [
                {
                    "hostPath": libtpu_dir,
                    "containerPath": "/usr/lib/tpu",
                    "options": ["ro", "rbind"],
                }
            ],
            "env": ["TPU_LIBRARY_PATH=/usr/lib/tpu/libtpu.so"],
        },
        "devices": devices,
    }


def write_spec(
    output_path: str = DEFAULT_SPEC_PATH,
    dev_root: str = "/dev",
    libtpu_dir: str = consts.LIBTPU_HOST_DIR,
    chips: Optional[List[dict]] = None,
    partition_file: str = DEFAULT_PARTITION_FILE,
) -> dict:
    spec = build_spec(
        dev_root=dev_root,
        libtpu_dir=libtpu_dir,
        chips=chips,
        partition_file=partition_file,
    )
    os.makedirs(os.path.dirname(output_path), exist_ok=True)
    tmp = output_path + ".tmp"
    with open(tmp, "w") as f:
        yaml.safe_dump(spec, f, sort_keys=False)
    os.replace(tmp, output_path)  # atomic: runtimes watch this directory
    return spec
