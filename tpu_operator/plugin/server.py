"""TPU device plugin.

The kubelet-facing operand (reference external image ``k8s-device-plugin``
— Go + NVML; SURVEY.md §2.3): serves the DevicePlugin v1beta1 API on a
unix socket, registers with the kubelet, and advertises ``google.com/tpu``
(or ``google.com/tpu-<shape>`` subslice resources under the ``mixed``
strategy).

TPU-native behaviours:

* **topology-aware allocation**: ``GetPreferredAllocation`` picks
  ICI-contiguous chip blocks (``workloads/topology.pick_chips``) so a
  2-chip tenant gets a real ICI pair, not two opposite corners;
* **CDI-first injection**: ``Allocate`` returns CDI device names when CDI
  is enabled, falling back to raw ``DeviceSpec``/mounts otherwise (the
  reference's toolkit-injected mounts);
* **multi-host env**: allocations carry the slice coordination env
  (worker id/hostnames, topology) read from the node's TFD labels — the
  MEGASCALE/JAX-coordinator pattern (SURVEY.md §2.4);
* chips come from native ``libtpuinfo`` with a devfs fallback, and health
  flips Unhealthy when the device node disappears.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from concurrent import futures
from typing import Dict, List, Optional

import grpc

from tpu_operator import consts
from tpu_operator.native import tpuinfo
from tpu_operator.plugin import grpc_glue
from tpu_operator.plugin.proto import pb2
from tpu_operator.workloads import topology as topo

log = logging.getLogger("tpu-device-plugin")

KUBELET_SOCKET_DIR = "/var/lib/kubelet/device-plugins"
PLUGIN_SOCKET_NAME = "tpu.sock"
HEALTHY = "Healthy"
UNHEALTHY = "Unhealthy"


def _chip_id_sort_key(dev_id: str):
    """Order device ids numerically when they are numbers, lexically
    after them otherwise — a total order that never raises."""
    try:
        return (0, int(dev_id), "")
    except (TypeError, ValueError):
        return (1, 0, str(dev_id))


class TPUDevicePluginServicer:
    """DevicePlugin service implementation."""

    def __init__(
        self,
        dev_root: str = "/dev",
        resource_name: str = consts.TPU_RESOURCE,
        generation: str = "",
        host_topology: str = "",
        cdi_enabled: bool = True,
        libtpu_dir: str = consts.LIBTPU_HOST_DIR,
        slice_env: Optional[Dict[str, str]] = None,
        poll_interval_s: float = 5.0,
        health_probe_interval_s: float = 30.0,
    ):
        self.dev_root = dev_root
        self.resource_name = resource_name
        self.generation = generation
        self.host_topology = host_topology
        if host_topology:
            # validate the node label once; a malformed topology must not
            # crash every GetPreferredAllocation RPC later
            try:
                topo.chip_count(host_topology)
            except ValueError:
                log.warning(
                    "invalid host topology %r; topology-aware allocation "
                    "disabled",
                    host_topology,
                )
                self.host_topology = ""
        self.cdi_enabled = cdi_enabled
        self.libtpu_dir = libtpu_dir
        self.slice_env = slice_env or {}
        self.poll_interval_s = poll_interval_s
        self.health_probe_interval_s = health_probe_interval_s
        self._stop = threading.Event()
        # Condition + version counter (not a shared Event): every
        # ListAndWatch stream must see every change — an Event consumed by
        # one stream would starve concurrent/zombie streams of wakeups.
        self._cond = threading.Condition()
        # serializes re-enumeration (discover + publish) so a slow refresh
        # can't publish a stale snapshot over a newer one
        self._refresh_lock = threading.Lock()
        self._version = 0
        self._devices: Dict[str, pb2.Device] = {}
        # ids forced Unhealthy by an external prober (health loop); sticky
        # across re-enumeration until mark_healthy clears them
        self._forced_unhealthy: set = set()
        # device id -> node path recorded at discovery time; probes use
        # these, never a fresh positional enumeration
        self._device_paths: Dict[str, str] = {}
        self._poller: Optional[threading.Thread] = None
        self.refresh_devices()

    # ------------------------------------------------------------------
    def discover(self) -> List[dict]:
        return tpuinfo.chip_summary(self.dev_root)

    def refresh_devices(self) -> bool:
        """Re-enumerate chips; returns True when the set/health changed."""
        with self._refresh_lock:
            return self._refresh_devices_locked()

    def _refresh_devices_locked(self) -> bool:
        chips = self.discover()
        new: Dict[str, pb2.Device] = {}
        paths: Dict[str, str] = {}
        for chip in chips:
            dev_id = str(chip["index"])
            d = pb2.Device(ID=dev_id, health=HEALTHY)
            numa = chip.get("numa_node")
            if numa is not None and numa >= 0:
                d.topology.nodes.add().ID = numa
            new[dev_id] = d
            paths[dev_id] = chip.get("path", "")
        with self._cond:
            for dev_id in self._forced_unhealthy:
                if dev_id in new:
                    new[dev_id].health = UNHEALTHY
            changed = set(new) != set(self._devices) or any(
                new[k].health != self._devices[k].health for k in new
            )
            self._devices = new
            self._device_paths = paths
            if changed:
                self._version += 1
                self._cond.notify_all()
        return changed

    def mark_unhealthy(self, dev_id: str) -> None:
        """Flip one device to Unhealthy (sticky across re-enumeration —
        an external health prober owns the flag) and wake every stream."""
        dev_id = str(dev_id)
        with self._cond:
            self._forced_unhealthy.add(dev_id)
            dev = self._devices.get(dev_id)
            if dev is not None and dev.health != UNHEALTHY:
                dev.health = UNHEALTHY
                self._version += 1
                self._cond.notify_all()

    def mark_healthy(self, dev_id: str) -> None:
        """Clear a forced-Unhealthy flag (device passed a probe again)."""
        dev_id = str(dev_id)
        with self._cond:
            self._forced_unhealthy.discard(dev_id)
            dev = self._devices.get(dev_id)
            if dev is not None and dev.health != HEALTHY:
                dev.health = HEALTHY
                self._version += 1
                self._cond.notify_all()

    def stop(self):
        self._stop.set()
        with self._cond:
            self._cond.notify_all()

    # -- background polling --------------------------------------------
    def _ensure_poller(self):
        """One shared poller re-enumerates devices; watch streams only
        wait on the Condition (N zombie streams must not mean N scans)."""
        with self._cond:
            if self._poller is None or not self._poller.is_alive():
                self._poller = threading.Thread(
                    target=self._poll_loop, daemon=True
                )
                self._poller.start()

    def _poll_loop(self):
        # start the probe clock NOW: monotonic() is huge, so a 0.0 seed
        # would fire the first probe on the first tick no matter what
        # health_probe_interval_s says — overriding health decisions an
        # external prober just made
        last_probe = time.monotonic()
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.refresh_devices()
                now = time.monotonic()
                if now - last_probe >= self.health_probe_interval_s:
                    last_probe = now
                    self.probe_health()
            except Exception:
                log.exception("device re-enumeration failed")

    def snapshot(self) -> Dict[str, str]:
        """Advertisement snapshot ``{device_id: health}`` for in-process
        embedders (the scheduling-churn engine's host agents drive the
        real RPC handlers without a gRPC stream; they read the device
        set here instead of holding a ListAndWatch per host)."""
        with self._cond:
            return {i: d.health for i, d in self._devices.items()}

    def device_probe(self, dev_id: str) -> bool:
        """Open-probe one advertised device at the path recorded when it
        was discovered; existence is not liveness, and a fresh positional
        enumeration could attribute health to the wrong chip."""
        with self._cond:
            path = self._device_paths.get(str(dev_id), "")
        return tpuinfo.device_probe_path(path)

    def probe_health(self) -> None:
        """Open-probe every advertised device and flip its health — the
        TPU analogue of the reference's periodic `nvidia-smi` re-run
        (validator/metrics.go:237-250). A wedged chip whose device node
        still exists goes Unhealthy so the kubelet stops placing pods."""
        for dev_id in list(self._devices):
            try:
                ok = self.device_probe(dev_id)
            except Exception:
                log.exception("health probe failed for device %s", dev_id)
                continue
            if ok:
                self.mark_healthy(dev_id)
            else:
                with self._cond:
                    already = str(dev_id) in self._forced_unhealthy
                if not already:  # warn on the transition, not every cycle
                    log.warning(
                        "device %s failed open-probe; marking Unhealthy",
                        dev_id,
                    )
                self.mark_unhealthy(dev_id)

    # -- RPCs ------------------------------------------------------------
    def GetDevicePluginOptions(self, request, context):
        return pb2.DevicePluginOptions(
            pre_start_required=False,
            get_preferred_allocation_available=True,
        )

    def ListAndWatch(self, request, context):
        """Stream the device list; initial send then re-send ONLY on
        change (kubelet holds this stream for the plugin's lifetime —
        real plugins don't re-send an unchanged list every poll tick).

        Each stream tracks the version it last sent, so concurrent
        streams (e.g. a zombie from before a kubelet reconnect) can't
        steal each other's wakeups; the shared background poller does the
        re-enumeration exactly once regardless of stream count."""
        self._ensure_poller()
        last_sent = None
        while not self._stop.is_set():
            with self._cond:
                if last_sent is not None and self._version == last_sent:
                    # wait for a change broadcast (or time out and loop to
                    # re-check _stop and the peer)
                    self._cond.wait(self.poll_interval_s)
                    if self._version == last_sent:
                        if context is not None and not context.is_active():
                            # dead peer (kubelet redialed): free the
                            # worker thread instead of pinning it forever
                            return
                        continue
                ver = self._version
                devices = list(self._devices.values())
            if self._stop.is_set():
                return
            resp = pb2.ListAndWatchResponse()
            for dev in devices:
                resp.devices.append(dev)
            yield resp
            last_sent = ver

    def GetPreferredAllocation(self, request, context):
        """Defensive contract: this RPC sits on the kubelet's pod-admission
        path, so a malformed or stale request must get a well-formed —
        possibly partial or empty — response, never a mid-RPC exception
        that fails admission for reasons the kubelet can't distinguish
        from a dead plugin. Specifically: zero/negative sizes answer an
        empty preference; ids that aren't integers (fallback registries)
        skip chip-coordinate topology and take the naive must-first fill;
        a size no contiguous group (or even the whole offer) can cover
        returns the honest short answer; and must-include devices that
        have since vanished from both the offer and the device registry
        are dropped rather than crashing the selection — the kubelet's
        own fail-closed checks then decide the allocation's fate."""
        resp = pb2.GetPreferredAllocationResponse()
        for creq in request.container_requests:
            cresp = resp.container_responses.add()
            try:
                chosen = self._preferred_one(creq)
            except Exception:
                # last-resort guard: degrade to the naive fill rather
                # than poison the RPC (and with it every allocation the
                # kubelet routes here)
                log.exception(
                    "GetPreferredAllocation degraded to naive selection"
                )
                chosen = self._naive_fill(creq)
            cresp.deviceIDs.extend(chosen)
        return resp

    @staticmethod
    def _naive_fill(creq) -> List[str]:
        """must-first best-fill on raw string ids — the selection that
        cannot fail, shared by the non-numeric-id path and the defensive
        catch-all."""
        size = max(creq.allocation_size, 0)
        offered = list(dict.fromkeys(str(i) for i in creq.available_deviceIDs))
        offered_set = set(offered)
        must = [
            i
            for i in dict.fromkeys(
                str(i) for i in creq.must_include_deviceIDs
            )
            if i in offered_set
        ]
        if len(must) > size:
            # contract violation (must > size): a preferred set must
            # contain every must id, so return them all unranked rather
            # than silently truncating
            return must
        must_set = set(must)
        return (must + [i for i in offered if i not in must_set])[:size]

    def _preferred_one(self, creq) -> List[str]:
        """Preference for one container request; returns string ids."""
        size = max(creq.allocation_size, 0)
        offered = {str(i) for i in creq.available_deviceIDs}
        try:
            avail_set = {int(i) for i in offered}
            # the kubelet contract guarantees must ⊆ available; enforce it
            # defensively — never recommend a device we weren't offered
            must = [
                i
                for i in (int(i) for i in creq.must_include_deviceIDs)
                if i in avail_set
            ]
        except ValueError:
            # non-numeric ids (a fallback registry naming devices, not
            # indexing chips): no geometry to reason about
            return self._naive_fill(creq)
        if size == 0 and not must:
            return []
        use_topology = bool(self.host_topology)
        if use_topology:
            # drop ids outside the labeled topology on EVERY path (the
            # fallback too) — never recommend a device that can't
            # exist; host_topology was validated in __init__. But ids
            # the plugin itself advertised must survive: if a
            # must-include id (or the whole set) falls outside the
            # mesh, these ids aren't chip coordinates (e.g. vfio
            # group numbers) — degrade to naive instead of dropping
            # kubelet-required devices.
            n_total = topo.chip_count(self.host_topology)
            filtered = {i for i in avail_set if 0 <= i < n_total}
            if filtered and set(must) <= filtered:
                avail_set = filtered
            else:
                use_topology = False
        available = sorted(avail_set)
        chosen = None
        if use_topology and size > 0:
            chosen = topo.pick_chips(
                self.host_topology,
                self.generation or "v5e",
                size,
                available,
                must_include=must,
            )
        if chosen is None:
            must_set = set(must)
            if len(must_set) > size:
                # contract violation: see _naive_fill
                chosen = sorted(must_set)
            else:
                # must ∪ best-fill, deduped, when topology can't help
                pool = sorted(must_set) + [
                    i for i in sorted(avail_set) if i not in must_set
                ]
                chosen = pool[:size]
        return [str(i) for i in sorted(chosen)]

    def Allocate(self, request, context):
        resp = pb2.AllocateResponse()
        for creq in request.container_requests:
            ids = list(creq.devicesIDs)
            cresp = resp.container_responses.add()
            if self.cdi_enabled:
                for dev_id in ids:
                    cresp.cdi_devices.add().name = (
                        f"google.com/tpu={dev_id}"
                    )
            else:
                for dev_id in ids:
                    # mount the path recorded at discovery (devfs truth),
                    # not a reconstructed accelN guess — they differ on
                    # vfio-fallback hosts
                    with self._cond:
                        host_path = self._device_paths.get(str(dev_id), "")
                    if not host_path:
                        host_path = os.path.join(
                            self.dev_root, f"accel{dev_id}"
                        )
                    # preserve the path shape under /dev: VFIO userspace
                    # opens /dev/vfio/<group> in-container, so flattening
                    # to /dev/<group> would break passthrough
                    rel = os.path.relpath(host_path, self.dev_root)
                    if rel.startswith(".."):
                        rel = os.path.basename(host_path)
                    spec = cresp.devices.add()
                    spec.host_path = host_path
                    spec.container_path = os.path.join("/dev", rel)
                    spec.permissions = "rw"
                mount = cresp.mounts.add()
                mount.host_path = self.libtpu_dir
                mount.container_path = "/usr/lib/tpu"
                mount.read_only = True
            env = dict(self.slice_env)
            # numeric ids sort numerically; non-numeric ids (fallback
            # registries) sort after them lexically — int() alone would
            # crash Allocate for exactly the id class
            # GetPreferredAllocation just learned to tolerate
            env["TPU_CHIPS_VISIBLE"] = ",".join(
                sorted(ids, key=_chip_id_sort_key)
            )
            if self.host_topology:
                env["TPU_HOST_TOPOLOGY"] = self.host_topology
            if self.generation:
                env["TPU_ACCELERATOR_GENERATION"] = self.generation
            for k, v in sorted(env.items()):
                cresp.envs[k] = v
        return resp

    def PreStartContainer(self, request, context):
        return pb2.PreStartContainerResponse()


def slice_env_from_node_labels(labels: Dict[str, str]) -> Dict[str, str]:
    """Multi-host coordination env derived from TFD labels (SURVEY.md §2.4:
    DCN hostname/ordinal injection, MEGASCALE/JAX coordinator pattern)."""
    env = {}
    topology = labels.get(consts.GKE_TPU_TOPOLOGY_LABEL) or labels.get(
        consts.TFD_TOPOLOGY_LABEL
    )
    if topology:
        env["TPU_TOPOLOGY"] = topology
    worker_id = labels.get(consts.TFD_WORKER_ID_LABEL)
    if worker_id is not None and worker_id != "":
        env["TPU_WORKER_ID"] = str(worker_id)
    hosts = labels.get(consts.TFD_SLICE_HOSTS_LABEL)
    if hosts:
        env["TPU_SLICE_HOSTS"] = str(hosts)
    acc = labels.get(consts.GKE_TPU_ACCELERATOR_LABEL)
    if acc:
        env["TPU_ACCELERATOR_TYPE"] = acc
    return env


class DevicePluginServer:
    """Owns the gRPC server + kubelet registration + socket lifecycle."""

    def __init__(
        self,
        servicer: TPUDevicePluginServicer,
        socket_dir: str = KUBELET_SOCKET_DIR,
        socket_name: str = PLUGIN_SOCKET_NAME,
    ):
        self.servicer = servicer
        self.socket_dir = socket_dir
        self.socket_path = os.path.join(socket_dir, socket_name)
        self.socket_name = socket_name
        self.server: Optional[grpc.Server] = None
        self._bound_ino: Optional[int] = None

    def start(self) -> str:
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)
        os.makedirs(self.socket_dir, exist_ok=True)
        self.server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
        self.server.add_generic_rpc_handlers(
            (grpc_glue.device_plugin_handler(self.servicer),)
        )
        addr = f"unix://{self.socket_path}"
        self.server.add_insecure_port(addr)
        self.server.start()
        try:
            self._bound_ino = os.stat(self.socket_path).st_ino
        except OSError:
            self._bound_ino = None
        log.info(
            "device plugin serving %s on %s",
            self.servicer.resource_name,
            self.socket_path,
        )
        return addr

    def register_with_kubelet(
        self, kubelet_socket: str = ""
    ) -> None:
        kubelet_socket = kubelet_socket or os.path.join(
            self.socket_dir, "kubelet.sock"
        )
        with grpc.insecure_channel(f"unix://{kubelet_socket}") as channel:
            stub = grpc_glue.RegistrationStub(channel)
            stub.Register(
                pb2.RegisterRequest(
                    version=grpc_glue.API_VERSION,
                    endpoint=self.socket_name,
                    resource_name=self.servicer.resource_name,
                    options=pb2.DevicePluginOptions(
                        get_preferred_allocation_available=True
                    ),
                )
            )
        log.info("registered with kubelet at %s", kubelet_socket)

    def stop(self):
        self.servicer.stop()
        if self.server is None:
            return
        # grpc unlinks the unix socket PATH at shutdown even when a newer
        # server instance (plugin restart with the fixed socket name) has
        # since re-bound it — deleting the successor's socket file and
        # breaking every later kubelet re-dial. If the path's inode is no
        # longer ours, shield the successor's file across the shutdown.
        guard = None
        try:
            if (
                self._bound_ino is not None
                and os.stat(self.socket_path).st_ino != self._bound_ino
            ):
                guard = self.socket_path + ".shutdown-guard"
                os.rename(self.socket_path, guard)
        except OSError:
            pass
        stopped = False
        done = None
        try:
            done = self.server.stop(grace=1)
            stopped = done.wait(timeout=5)
        finally:
            # only restore the successor's socket once shutdown has
            # CONFIRMED completion — a timed-out stop may still unlink
            # the path after os.replace put the real file back, deleting
            # the very socket the guard existed to protect.
            if guard is not None:
                if stopped:
                    try:
                        os.replace(guard, self.socket_path)
                    except OSError:
                        pass
                else:
                    log.warning(
                        "grpc shutdown did not confirm within 5s; holding "
                        "socket guard %s until it does",
                        guard,
                    )
                    if done is not None:
                        # deferred restore: once the late shutdown (and
                        # its unlink) finally completes, put the
                        # successor's socket back so the kubelet's
                        # re-dial finds it again
                        def _restore(ev=done, g=guard, path=self.socket_path):
                            ev.wait()
                            try:
                                os.replace(g, path)
                            except OSError:
                                pass

                        threading.Thread(
                            target=_restore,
                            daemon=True,
                            name="socket-guard-restore",
                        ).start()


def main(argv=None) -> int:
    import argparse

    logging.basicConfig(level="INFO")
    p = argparse.ArgumentParser("tpu-device-plugin")
    p.add_argument("--dev-root", default="/dev")
    p.add_argument("--socket-dir", default=KUBELET_SOCKET_DIR)
    p.add_argument("--node-name", default=os.environ.get("NODE_NAME", ""))
    p.add_argument(
        "--cdi", default=os.environ.get("CDI_ENABLED", "true") == "true"
    )
    p.add_argument(
        "--strategy", default=os.environ.get("SLICE_STRATEGY", "single")
    )
    args = p.parse_args(argv)

    labels: Dict[str, str] = {}
    if args.node_name:
        try:
            from tpu_operator.kube.rest import RestClient

            node = RestClient().get("v1", "Node", args.node_name)
            labels = node["metadata"].get("labels", {}) or {}
        except Exception:
            log.warning("could not read node labels; slice env disabled")

    from tpu_operator.controllers.state_manager import node_generation
    from tpu_operator.plugin.manager import PluginManager

    mgr = PluginManager(
        strategy=args.strategy,
        socket_dir=args.socket_dir,
        servicer_kw=dict(
            dev_root=args.dev_root,
            generation=node_generation({"metadata": {"labels": labels}}) or "",
            host_topology=labels.get(consts.GKE_TPU_TOPOLOGY_LABEL, ""),
            cdi_enabled=bool(args.cdi),
            slice_env=slice_env_from_node_labels(labels),
        ),
    )
    try:
        mgr.run(register=True, block=True)
    except KeyboardInterrupt:
        mgr.stop()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
