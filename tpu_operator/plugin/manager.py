"""Plugin manager: one DevicePlugin server per advertised resource.

Closes the slice-manager → device-plugin loop (the reference's
mig-strategy plumbing, ``controllers/object_controls.go:1187-1256``):

* ``single`` strategy (or unpartitioned): one ``google.com/tpu`` plugin
  over whole chips;
* ``mixed`` strategy with a partition state file
  (``sliceman.write_partition_state``): one ``google.com/tpu-<shape>``
  plugin per subslice shape, each subslice one schedulable device whose
  Allocate expands to its member chips;
* sandbox mode: a ``google.com/tpu-vm`` plugin advertising vfio groups
  from the vm-device state file (the kubevirt-style sandbox plugin slot).

Watches the partition file and restarts resource servers on change — the
device-plugin side of the ``tpu.slice.config`` label FSM.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import Dict, List, Optional

from tpu_operator import consts
from tpu_operator.plugin.proto import pb2
from tpu_operator.plugin.server import (
    KUBELET_SOCKET_DIR,
    DevicePluginServer,
    TPUDevicePluginServicer,
)

log = logging.getLogger("tpu-device-plugin")


class SubslicePluginServicer(TPUDevicePluginServicer):
    """Advertises one device per subslice; Allocate expands to member chips."""

    def __init__(self, subslices: List[dict], resource_name: str, **kw):
        self.subslices = {str(s["id"]): s for s in subslices}
        super().__init__(resource_name=resource_name, **kw)
        # device ids here are SUBSLICE ids, not chip ids: the base class's
        # chip-coordinate ICI preference would compute geometry on
        # meaningless indices. Each subslice is already ICI-contiguous by
        # construction (enumerate_subslices), so fall back to the naive
        # must-include-first preference.
        self.host_topology = ""

    def discover(self):
        return [{"index": int(i)} for i in sorted(self.subslices, key=int)]

    def device_probe(self, dev_id: str) -> bool:
        """A subslice is alive only when every member CHIP open-probes
        (subslice ids are not chip indices). Member chip N maps to
        /dev/accelN — the same convention this class's Allocate uses."""
        from tpu_operator.native import tpuinfo

        sub = self.subslices.get(str(dev_id))
        if sub is None:
            return False
        return all(
            tpuinfo.device_probe_path(
                os.path.join(self.dev_root, f"accel{int(c)}")
            )
            for c in sub["chips"]
        )

    def Allocate(self, request, context):
        resp = pb2.AllocateResponse()
        for creq in request.container_requests:
            cresp = resp.container_responses.add()
            chips: List[int] = []
            for sub_id in creq.devicesIDs:
                chips.extend(self.subslices[str(sub_id)]["chips"])
            if self.cdi_enabled:
                for sub_id in creq.devicesIDs:
                    sub = self.subslices[str(sub_id)]
                    cresp.cdi_devices.add().name = (
                        f"google.com/tpu=subslice-{sub['id']}-{sub['shape']}"
                    )
            else:
                for chip in sorted(chips):
                    spec = cresp.devices.add()
                    spec.host_path = os.path.join(self.dev_root, f"accel{chip}")
                    spec.container_path = f"/dev/accel{chip}"
                    spec.permissions = "rw"
            env = dict(self.slice_env)
            env["TPU_CHIPS_VISIBLE"] = ",".join(str(c) for c in sorted(chips))
            env["TPU_SUBSLICE_SHAPE"] = self.subslices[
                str(creq.devicesIDs[0])
            ]["shape"] if creq.devicesIDs else ""
            for k, v in sorted(env.items()):
                cresp.envs[k] = v
        return resp


class VfioPluginServicer(TPUDevicePluginServicer):
    """Sandbox device plugin: advertises vfio groups for VM workloads."""

    def __init__(self, vm_state_file: str, **kw):
        self.vm_state_file = vm_state_file
        kw.setdefault("resource_name", "google.com/tpu-vm")
        super().__init__(**kw)
        # vfio group numbers are kernel-assigned, not chip coordinates:
        # small sequential groups would pass the mesh filter and get
        # fictitious ICI geometry (same reasoning as SubslicePluginServicer)
        self.host_topology = ""

    def discover(self):
        try:
            with open(self.vm_state_file) as f:
                state = json.load(f)
        except (OSError, json.JSONDecodeError):
            return []
        return [{"index": d["id"], "path": d["vfio_group"]} for d in state.get("devices", [])]

    def device_probe(self, dev_id: str) -> bool:
        """stat-only, never open: every device here is a VFIO group
        (one open file per group is a kernel invariant), wherever the
        state file placed it — so force the shared helper's stat path."""
        from tpu_operator.native import tpuinfo

        with self._cond:
            path = self._device_paths.get(str(dev_id), "")
        return bool(path) and tpuinfo.device_probe_path(path, stat_only=True)

    def Allocate(self, request, context):
        resp = pb2.AllocateResponse()
        try:
            with open(self.vm_state_file) as f:
                devices = {
                    str(d["id"]): d for d in json.load(f).get("devices", [])
                }
        except (OSError, json.JSONDecodeError) as e:
            import grpc

            context.abort(
                grpc.StatusCode.UNAVAILABLE,
                f"vm device state unreadable ({e}); retry after "
                "tpu-vm-device-manager rewrites it",
            )
        for creq in request.container_requests:
            cresp = resp.container_responses.add()
            for dev_id in creq.devicesIDs:
                dev = devices.get(str(dev_id))
                if dev is None:
                    import grpc

                    context.abort(
                        grpc.StatusCode.NOT_FOUND,
                        f"stale allocation: vfio device {dev_id!r} no longer "
                        "in vm device state (repartitioned?)",
                    )
                group = dev["vfio_group"]
                spec = cresp.devices.add()
                spec.host_path = group
                spec.container_path = group
                spec.permissions = "rw"
            ctl = cresp.devices.add()
            ctl.host_path = ctl.container_path = "/dev/vfio/vfio"
            ctl.permissions = "rw"
        return resp


def kubelet_socket_id(socket_dir: str):
    """Identity of the kubelet registration socket. A change means the
    kubelet restarted: it wiped the device-plugins dir (our serving sockets
    are gone from the filesystem) and forgot every registration — plugins
    must restart and re-register or the node's TPU capacity silently drops
    to zero. ctime is part of the key because a freed inode number is often
    reused immediately, while recreation always bumps ctime (an
    over-trigger just costs one harmless re-registration)."""
    try:
        st = os.stat(os.path.join(socket_dir, "kubelet.sock"))
        return (st.st_dev, st.st_ino, st.st_ctime_ns)
    except OSError:
        return None


class PluginManager:
    def __init__(
        self,
        strategy: str = "single",
        partition_file: str = "/run/tpu/partitions.json",
        socket_dir: str = KUBELET_SOCKET_DIR,
        servicer_kw: Optional[dict] = None,
        poll_interval_s: float = 10.0,
    ):
        self.strategy = strategy
        self.partition_file = partition_file
        self.socket_dir = socket_dir
        self.servicer_kw = servicer_kw or {}
        self.poll_interval_s = poll_interval_s
        self.servers: Dict[str, DevicePluginServer] = {}
        self._stop = threading.Event()
        self._last_sig = None
        self._kubelet_id = self._kubelet_socket_id()

    def _kubelet_socket_id(self):
        return kubelet_socket_id(self.socket_dir)

    # ------------------------------------------------------------------
    def _partition_state(self) -> Optional[dict]:
        try:
            with open(self.partition_file) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return None

    def desired_resources(self) -> Dict[str, dict]:
        """resource name -> config for the servicer factory (the MIG
        single/mixed strategy semantics)."""
        state = self._partition_state()
        partitioned = bool(
            state and state.get("partitioned") and state.get("subslices")
        )
        if partitioned and self.strategy == "mixed":
            by_shape: Dict[str, List[dict]] = {}
            for sub in state["subslices"]:
                by_shape.setdefault(sub["shape"], []).append(sub)
            return {
                consts.TPU_SUBSLICE_RESOURCE_PREFIX + shape: {
                    "kind": "subslice",
                    "subslices": subs,
                }
                for shape, subs in by_shape.items()
            }
        if partitioned and self.strategy == "single":
            # uniform partition advertised under the plain resource name:
            # each schedulable unit is one subslice (MIG single strategy)
            return {
                consts.TPU_RESOURCE: {
                    "kind": "subslice",
                    "subslices": state["subslices"],
                }
            }
        return {consts.TPU_RESOURCE: {"kind": "chips"}}

    def _make_server(self, resource: str, cfg: dict) -> DevicePluginServer:
        if cfg["kind"] == "subslice":
            servicer = SubslicePluginServicer(
                cfg["subslices"], resource_name=resource, **self.servicer_kw
            )
        else:
            servicer = TPUDevicePluginServicer(
                resource_name=resource, **self.servicer_kw
            )
        sock = "tpu-" + resource.split("/")[-1] + ".sock"
        return DevicePluginServer(
            servicer, socket_dir=self.socket_dir, socket_name=sock
        )

    def sync(self, register: bool = False) -> bool:
        """Reconcile running servers against desired resources; returns True
        when the server set changed."""
        kubelet_id = self._kubelet_socket_id()
        if kubelet_id != self._kubelet_id:
            self._kubelet_id = kubelet_id
            if kubelet_id is not None:
                log.info("kubelet socket changed; restarting + re-registering")
                self._last_sig = None  # force a full restart below
        desired = self.desired_resources()
        sig = json.dumps(desired, sort_keys=True)
        if sig == self._last_sig:
            return False
        for resource, server in list(self.servers.items()):
            server.stop()
            del self.servers[resource]
        all_registered = True
        for resource, cfg in desired.items():
            server = self._make_server(resource, cfg)
            server.start()
            if register:
                try:
                    server.register_with_kubelet()
                except Exception:
                    all_registered = False
                    log.exception("kubelet registration failed for %s", resource)
            self.servers[resource] = server
        # cache the signature only when every server started AND registered:
        # a failure leaves it stale so the next sync retries (start failures
        # raise out of the loop above; registration failures land here)
        if all_registered:
            self._last_sig = sig
        log.info(
            "serving resources: %s%s",
            sorted(self.servers),
            "" if all_registered else " (registration pending retry)",
        )
        return True

    def run(self, register: bool = True, block: bool = True):
        self.sync(register=register)
        def loop():
            while not self._stop.is_set():
                try:
                    self.sync(register=register)
                except Exception:
                    log.exception("plugin sync failed")
                self._stop.wait(self.poll_interval_s)
        t = threading.Thread(target=loop, daemon=True)
        t.start()
        if block:
            while not self._stop.is_set():
                import time

                time.sleep(1)

    def stop(self):
        self._stop.set()
        for server in self.servers.values():
            server.stop()


def sandbox_main(argv=None) -> int:
    """``tpu-sandbox-device-plugin`` entrypoint: vfio-group device plugin for
    VM workloads (reference sandbox-device-plugin slot)."""
    import argparse
    import time

    logging.basicConfig(level="INFO")
    p = argparse.ArgumentParser("tpu-sandbox-device-plugin")
    p.add_argument(
        "--vm-state-file",
        default=os.environ.get("VM_STATE_FILE", "/run/tpu/vm-devices.json"),
    )
    p.add_argument("--socket-dir", default=KUBELET_SOCKET_DIR)
    p.add_argument("--dev-root", default="/dev")
    args = p.parse_args(argv)
    def make_server():
        servicer = VfioPluginServicer(
            args.vm_state_file, dev_root=args.dev_root, cdi_enabled=False
        )
        server = DevicePluginServer(
            servicer, socket_dir=args.socket_dir, socket_name="tpu-vm.sock"
        )
        server.start()
        registered = False
        try:
            server.register_with_kubelet()
            registered = True
        except Exception:
            log.exception("kubelet registration failed; will retry")
        return server, registered

    server, registered = make_server()
    last_id = kubelet_socket_id(args.socket_dir)
    try:
        while True:
            time.sleep(5)
            now_id = kubelet_socket_id(args.socket_dir)
            if now_id != last_id:
                last_id = now_id
                registered = True  # no socket yet -> nothing to register with
                if now_id is not None:
                    # kubelet restarted: it wiped our socket and forgot the
                    # registration (same contract as PluginManager.sync)
                    log.info("kubelet socket changed; re-registering")
                    server.stop()
                    server, registered = make_server()
            elif not registered and now_id is not None:
                # a registration that failed transiently (e.g. the kubelet's
                # plugin manager was still initializing) keeps retrying —
                # otherwise the node's capacity stays at zero until the NEXT
                # kubelet restart
                try:
                    server.register_with_kubelet()
                    registered = True
                except Exception:
                    log.exception("kubelet registration retry failed")
    except KeyboardInterrupt:
        server.stop()
    return 0
