"""Fixed-overhead-cancelling throughput timing, shared by the validation
workloads (``matmul.py``, ``membw.py``).

The only reliable completion barrier on remote/tunneled PJRT platforms is a
scalar fetch, and that round-trip can rival the measured work itself. Three
per-iteration estimators are combined:

* the plain mean ``t(iters)/iters`` — includes the overhead, biased high;
* the zero-length-subtracted mean ``(t(iters) - t(0))/iters`` — the
  overhead measured directly;
* the two-length delta ``(t(iters) - t(lo))/(iters - lo)`` — every cost
  that does not scale with iterations cancelled algebraically.

The median of the three is robust to any single measurement being polluted
by tunnel jitter, and cannot exceed the plain mean by more than the honest
overhead correction.
"""

from __future__ import annotations

import time
from typing import Callable


def chain_per_iter_seconds(step: Callable, x, force: Callable, iters: int) -> float:
    """Seconds per iteration of the serial chain ``v = step(v)``, fixed
    overhead (dispatch + completion fetch) cancelled.

    ``step`` must make each dispatch depend on the previous one (so device
    work can't overlap across iterations) and ``force`` must block until
    ``v`` is fully materialized (e.g. a scalar fetch).
    """

    def timed(n: int) -> float:
        t0 = time.perf_counter()
        v = x
        for _ in range(n):
            v = step(v)
        force(v)
        return time.perf_counter() - t0

    force(step(x))  # warmup (compile + first execution)
    t_zero = timed(0)  # pure sync/fetch round-trip
    t_full = timed(iters)
    candidates = [t_full / iters]
    sub0 = (t_full - t_zero) / iters
    if sub0 > 0:
        candidates.append(sub0)
    lo = max(1, iters // 4)
    if iters > lo:
        t_lo = timed(lo)
        delta = (t_full - t_lo) / (iters - lo)
        if delta > 0:
            candidates.append(delta)
    candidates.sort()
    # lower-middle on even counts: with [plain, sub0] the overhead-corrected
    # estimate must win, not the overhead-inclusive plain mean
    return candidates[(len(candidates) - 1) // 2]

def adjacent_ratio_stats(
    measure: Callable,
    base,
    cands: dict,
    reps: int = 9,
    transform: Callable = None,
):
    """Drift-cancelled A/B comparison on a chip whose state wanders by
    the hour: each rep times every candidate ADJACENT to a fresh base
    measurement and records ``base/candidate`` (>1 means the candidate
    is faster) — slow drift multiplies both sides of a rep equally, so
    the ratio isolates the kernel/structure difference the raw numbers
    bury. Returns ``{key: (median, iqr_lo, iqr_hi, ratios)}``.

    ``measure(fn) -> seconds`` is supplied by the caller (typically a
    ``chain_per_iter_seconds`` closure). ``transform(key, base_s,
    cand_s) -> ratio`` overrides the plain wall ratio — e.g. the
    per-performed-FLOP comparator in ``scripts/fa_blocktune.py``
    (whose docstring explains why wall time is the honest default).
    """
    import statistics

    ratios = {k: [] for k in cands}
    for _ in range(reps):
        for k_, fn in cands.items():
            b = measure(base)
            c = measure(fn)
            ratios[k_].append(
                transform(k_, b, c) if transform is not None else b / c
            )
    out = {}
    for k_, rs in ratios.items():
        rs = sorted(rs)
        out[k_] = (
            statistics.median(rs),
            rs[len(rs) // 4],
            rs[-1 - len(rs) // 4],
            rs,
        )
    return out
