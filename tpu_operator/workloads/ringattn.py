"""Ring-attention context-parallel probe: long-context readiness for a slice.

The burn-in (``workloads/burnin.py``) proves dp/tp collectives; the ring
probe (``workloads/ring.py``) proves raw link health. This workload proves
the *long-context* path: blockwise ring attention over a sequence-parallel
(``sp``) mesh axis, the canonical TPU pattern for contexts that exceed one
chip's HBM. Sequence is sharded over ``sp``; each device keeps its Q block
resident and rotates K/V blocks around the ICI ring with
``jax.lax.ppermute``, folding each incoming block into a numerically-stable
online-softmax accumulator (flash-attention style m/l running max/sum).
After ``sp`` hops every device has attended over the full sequence without
any device ever materializing full K/V — attention memory stays
O(seq/sp · seq/sp) per step instead of O(seq²).

Validation is exact, not statistical: the sharded output is compared
against single-pass full attention on replicated arrays. A broken link,
mis-ordered permute, or accumulator bug shows up as numerical divergence.

TPU-first notes: per-device code via ``shard_map``; the hop loop is a
device-side ``lax.fori_loop`` (one compiled program, no host round-trips);
K/V blocks are static-shaped so each ``ppermute`` lowers onto physical ICI;
contractions run on the MXU in bf16 inputs with f32 accumulation
(``preferred_element_type``).

Used by ``tpu-validator --component ringattn`` (long-context slice
validation) and runnable on the virtual CPU mesh in CI. Reference parity:
the NVIDIA operator has no analogue — its validation stops at vectorAdd
(``validator/cuda-workload-validation.yaml:20``); this is TPU-native
surplus mandated by the slice/topology story (SURVEY.md §2.4).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


@dataclass
class RingAttnResult:
    ok: bool
    n_devices: int
    seq_len: int
    heads: int
    head_dim: int
    max_abs_err: float
    elapsed_s: float
    achieved_tokens_per_s: float
    error: str = ""

    def to_dict(self):
        return {
            "ok": self.ok,
            "n_devices": self.n_devices,
            "seq_len": self.seq_len,
            "heads": self.heads,
            "head_dim": self.head_dim,
            "max_abs_err": round(self.max_abs_err, 8),
            "elapsed_s": round(self.elapsed_s, 6),
            "achieved_tokens_per_s": round(self.achieved_tokens_per_s, 1),
            "error": self.error,
        }


def _ring_attention_block(q, k, v, axis_name: str, axis_size: int, scale: float):
    """Per-device ring attention body (runs inside shard_map).

    q/k/v: [batch, seq_local, heads, head_dim] local blocks. Rotates (k, v)
    ``axis_size`` times; online-softmax accumulates each visiting block.
    """
    import jax
    import jax.numpy as jnp

    b, t, h, d = q.shape
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def hop(_, carry):
        o, m, l, kb, vb = carry
        # scores over the visiting K block: [b, t, h, s]
        s = (
            jnp.einsum(
                "bthd,bshd->bths", q, kb, preferred_element_type=jnp.float32
            )
            * scale
        )
        m_new = jnp.maximum(m, s.max(axis=-1))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l_new = l * corr + p.sum(axis=-1)
        o_new = o * corr[..., None] + jnp.einsum(
            "bths,bshd->bthd",
            p,
            vb.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        kb = jax.lax.ppermute(kb, axis_name=axis_name, perm=perm)
        vb = jax.lax.ppermute(vb, axis_name=axis_name, perm=perm)
        return o_new, m_new, l_new, kb, vb

    def _vary(x):
        # the zero-init accumulators are replicated constants; mark them
        # varying over the ring axis so the fori_loop carry type matches
        # the per-device outputs (strict shard_map varying-axis typing)
        try:
            return jax.lax.pcast(x, (axis_name,), to="varying")
        except (AttributeError, TypeError):  # pragma: no cover - older jax
            try:
                return jax.lax.pvary(x, (axis_name,))
            except AttributeError:
                return x

    o0 = _vary(jnp.zeros((b, t, h, d), jnp.float32))
    m0 = _vary(jnp.full((b, t, h), -jnp.inf, jnp.float32))
    l0 = _vary(jnp.zeros((b, t, h), jnp.float32))
    o, m, l, _, _ = jax.lax.fori_loop(0, axis_size, hop, (o0, m0, l0, k, v))
    return (o / l[..., None]).astype(q.dtype)


def _full_attention(q, k, v, scale: float):
    """Single-pass reference attention on replicated arrays (f32 math)."""
    import jax.numpy as jnp

    s = (
        jnp.einsum("bthd,bshd->bths", q, k, preferred_element_type=jnp.float32)
        * scale
    )
    p = jnp.exp(s - s.max(axis=-1, keepdims=True))
    p = p / p.sum(axis=-1, keepdims=True)
    return jnp.einsum(
        "bths,bshd->bthd",
        p,
        v.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    ).astype(q.dtype)


def build_ringattn(
    n_devices: Optional[int] = None,
    batch: int = 1,
    seq_len: int = 2048,
    heads: int = 4,
    head_dim: int = 128,
):
    """Returns (mesh, jitted sharded attention fn, (q, k, v) sharded)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    try:
        from jax import shard_map  # jax >= 0.8
    except ImportError:  # pragma: no cover
        from jax.experimental.shard_map import shard_map

    devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise RuntimeError(
                f"need {n_devices} devices, have {len(devices)}"
            )
        devices = devices[:n_devices]
    n = len(devices)
    if seq_len % n != 0:
        raise ValueError(f"seq_len {seq_len} not divisible by sp={n}")
    mesh = Mesh(np.asarray(devices), axis_names=("sp",))

    key = jax.random.PRNGKey(7)
    kq, kk, kv = jax.random.split(key, 3)
    shape = (batch, seq_len, heads, head_dim)
    q = jax.random.normal(kq, shape, jnp.bfloat16)
    k = jax.random.normal(kk, shape, jnp.bfloat16)
    v = jax.random.normal(kv, shape, jnp.bfloat16)
    seq_sharding = NamedSharding(mesh, P(None, "sp", None, None))
    q, k, v = (jax.device_put(a, seq_sharding) for a in (q, k, v))

    scale = 1.0 / head_dim**0.5
    fn = jax.jit(
        shard_map(
            lambda qb, kb, vb: _ring_attention_block(
                qb, kb, vb, axis_name="sp", axis_size=n, scale=scale
            ),
            mesh=mesh,
            in_specs=(P(None, "sp", None, None),) * 3,
            out_specs=P(None, "sp", None, None),
        )
    )
    return mesh, fn, (q, k, v)


def run_ringattn(
    n_devices: Optional[int] = None,
    batch: int = 1,
    seq_len: int = 2048,
    heads: int = 4,
    head_dim: int = 128,
    iters: int = 4,
    tol: float = 2e-2,
) -> RingAttnResult:
    """Run the context-parallel probe and check it against full attention.

    ``tol`` bounds max-abs divergence between the ring accumulator and the
    single-pass reference; bf16 inputs with f32 accumulation keep genuine
    runs well inside 2e-2, while a dropped or reordered K/V block produces
    O(1) errors.
    """
    import time

    try:
        import numpy as np

        mesh, fn, (q, k, v) = build_ringattn(
            n_devices=n_devices,
            batch=batch,
            seq_len=seq_len,
            heads=heads,
            head_dim=head_dim,
        )
        n = mesh.devices.size
        out = fn(q, k, v)
        out.block_until_ready()  # compile round
        ref = _full_attention(
            np.asarray(q, np.float32),
            np.asarray(k, np.float32),
            np.asarray(v, np.float32),
            scale=1.0 / head_dim**0.5,
        )
        max_err = float(
            np.max(np.abs(np.asarray(out, np.float32) - np.asarray(ref)))
        )
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(q, k, v)
        out.block_until_ready()
        elapsed = time.perf_counter() - t0
        tokens_per_s = batch * seq_len * iters / elapsed if elapsed > 0 else 0.0
        return RingAttnResult(
            ok=max_err <= tol,
            n_devices=n,
            seq_len=seq_len,
            heads=heads,
            head_dim=head_dim,
            max_abs_err=max_err,
            elapsed_s=elapsed,
            achieved_tokens_per_s=tokens_per_s,
            error="" if max_err <= tol else f"divergence {max_err:.4f} > tol {tol}",
        )
    except Exception as e:
        return RingAttnResult(
            ok=False,
            n_devices=0,
            seq_len=seq_len,
            heads=heads,
            head_dim=head_dim,
            max_abs_err=float("nan"),
            elapsed_s=0.0,
            achieved_tokens_per_s=0.0,
            error=str(e),
        )
