"""ICI topology math.

The TPU-fabric story at the operator's altitude (SURVEY.md §2.4): pure
functions describing chip meshes so that

* feature discovery can publish topology/wrap labels,
* the device plugin can do ICI-contiguity-aware allocation,
* the slice manager can enumerate valid subslice partitions.

A topology string is GKE's ``cloud.google.com/gke-tpu-topology`` form:
``"2x4"`` (v5e/v6e 2-D meshes) or ``"2x2x4"`` (v4/v5p 3-D tori). Wraparound
(torus) links exist on a dimension when its extent is a multiple of 4 on 3-D
generations — the rule used by libtpu for v4/v5p slices.

No k8s, no JAX here: this module is also consumed by the native tooling
tests and must stay dependency-free.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

Coord = Tuple[int, ...]

# chips per host by generation (how kubelet-visible devices map onto hosts)
CHIPS_PER_HOST = {"v4": 4, "v5e": 8, "v5p": 4, "v6e": 8}

# single-chip peak bf16 TFLOPS (public numbers) — used for bench reporting
PEAK_BF16_TFLOPS = {"v4": 275.0, "v5e": 197.0, "v5p": 459.0, "v6e": 918.0}

# HBM GiB per chip
HBM_GB = {"v4": 32, "v5e": 16, "v5p": 95, "v6e": 32}

# HBM bandwidth GB/s per chip (public spec sheets)
PEAK_HBM_GBPS = {"v4": 1228.0, "v5e": 819.0, "v5p": 2765.0, "v6e": 1640.0}


def parse_topology(topology: str) -> Tuple[int, ...]:
    """``"2x2x4"`` -> ``(2, 2, 4)``."""
    if not topology:
        raise ValueError("empty topology")
    try:
        dims = tuple(int(p) for p in topology.lower().split("x"))
    except ValueError as e:
        raise ValueError(f"bad topology {topology!r}") from e
    if not dims or any(d <= 0 for d in dims):
        raise ValueError(f"bad topology {topology!r}")
    return dims


def format_topology(dims: Sequence[int]) -> str:
    return "x".join(str(d) for d in dims)


def chip_count(topology: str) -> int:
    n = 1
    for d in parse_topology(topology):
        n *= d
    return n


def host_count(topology: str, generation: str) -> int:
    per_host = CHIPS_PER_HOST.get(generation, 4)
    chips = chip_count(topology)
    return max(1, chips // per_host)


def wraparound_dims(topology: str, generation: str) -> Tuple[bool, ...]:
    """Which dimensions have torus wrap links.

    3-D generations (v4/v5p) wrap a dimension when its extent is a multiple
    of 4; 2-D mesh generations (v5e/v6e) have no wrap.
    """
    dims = parse_topology(topology)
    if len(dims) < 3:
        return tuple(False for _ in dims)
    return tuple(d >= 4 and d % 4 == 0 for d in dims)


def chip_coords(topology: str) -> List[Coord]:
    """All chip coordinates in row-major order."""
    dims = parse_topology(topology)
    return [c for c in itertools.product(*(range(d) for d in dims))]


def coord_to_index(coord: Coord, dims: Sequence[int]) -> int:
    idx = 0
    for c, d in zip(coord, dims):
        idx = idx * d + c
    return idx


def index_to_coord(index: int, dims: Sequence[int]) -> Coord:
    coord = []
    for d in reversed(dims):
        coord.append(index % d)
        index //= d
    return tuple(reversed(coord))


def neighbors(coord: Coord, topology: str, generation: str) -> List[Coord]:
    """ICI neighbors of a chip (±1 per dimension, wrap where torus)."""
    dims = parse_topology(topology)
    wraps = wraparound_dims(topology, generation)
    out = []
    for axis, extent in enumerate(dims):
        if extent == 1:
            continue
        for delta in (-1, 1):
            c = list(coord)
            nxt = c[axis] + delta
            if 0 <= nxt < extent:
                c[axis] = nxt
            elif wraps[axis]:
                c[axis] = nxt % extent
            else:
                continue
            cand = tuple(c)
            if cand != coord and cand not in out:
                out.append(cand)
    return out


def ici_link_count(topology: str, generation: str) -> int:
    """Total bidirectional ICI links in the slice (for metrics/labels)."""
    total = 0
    for coord in chip_coords(topology):
        total += len(neighbors(coord, topology, generation))
    return total // 2


@dataclass(frozen=True)
class Subslice:
    """An ICI-contiguous block of chips (origin + shape)."""

    origin: Coord
    shape: Tuple[int, ...]

    def coords(self) -> List[Coord]:
        return [
            tuple(o + d for o, d in zip(self.origin, offset))
            for offset in itertools.product(*(range(s) for s in self.shape))
        ]

    def chip_count(self) -> int:
        n = 1
        for s in self.shape:
            n *= s
        return n

    def name(self) -> str:
        return format_topology(self.shape)


def enumerate_subslices(
    topology: str, shape: Sequence[int]
) -> List[Subslice]:
    """Tile the host topology with non-overlapping subslices of ``shape``.

    The MIG-analogue partition: every tile is ICI-contiguous by
    construction. Raises if the shape doesn't tile the topology exactly
    (ragged partitions would strand chips).
    """
    dims = parse_topology(topology)
    shape = tuple(shape)
    if len(shape) != len(dims):
        # pad trailing dims with 1 (e.g. shape 2x1 in topology 2x2x1)
        if len(shape) < len(dims):
            shape = shape + tuple(1 for _ in range(len(dims) - len(shape)))
        else:
            raise ValueError(f"shape {shape} has more dims than topology {dims}")
    for s, d in zip(shape, dims):
        if s > d or d % s != 0:
            raise ValueError(
                f"shape {format_topology(shape)} does not tile topology "
                f"{format_topology(dims)}"
            )
    tiles = []
    steps = [range(0, d, s) for d, s in zip(dims, shape)]
    for origin in itertools.product(*steps):
        tiles.append(Subslice(origin=origin, shape=shape))
    return tiles


def contiguous(coords: Sequence[Coord], topology: str, generation: str) -> bool:
    """Whether a chip set is ICI-connected (BFS over neighbor links)."""
    if not coords:
        return False
    want = set(coords)
    seen = {coords[0]}
    frontier = [coords[0]]
    while frontier:
        cur = frontier.pop()
        for nb in neighbors(cur, topology, generation):
            if nb in want and nb not in seen:
                seen.add(nb)
                frontier.append(nb)
    return seen == want


def pick_chips(
    topology: str,
    generation: str,
    count: int,
    available: Sequence[int],
    must_include: Sequence[int] = (),
) -> Optional[List[int]]:
    """Topology-aware allocation for the device plugin: choose ``count``
    chips from ``available`` (linear device ids) preferring an
    ICI-contiguous block; falls back to any chips if none is contiguous.

    ``must_include`` ids are guaranteed to be in the result (kubelet's
    ``must_include_deviceIDs`` contract): contiguous blocks are only
    accepted when they cover the whole set, and the BFS fallback grows
    the connected region outward from it.

    This is the TPU analogue of NVML topology-aware allocation in the
    reference's device plugin (external image; SURVEY.md §2.3).
    """
    dims = parse_topology(topology)
    n_total = chip_count(topology)
    must = set(must_include)
    # ids outside the topology (stale devfs state, label/plugin mismatch)
    # are dropped so the valid chips still get topology-aware placement;
    # an out-of-range or un-offered must-id is unsatisfiable here
    avail = {i for i in available if 0 <= i < n_total}
    if count <= 0 or len(avail) < count or len(must) > count:
        return None
    if not must <= avail:
        return None
    coords_by_idx: Dict[int, Coord] = {
        i: index_to_coord(i, dims) for i in avail
    }
    topo_str = format_topology(dims)
    # try axis-aligned blocks of exactly `count` chips first
    for shape in _blocks_of(count, dims):
        if any(d % s != 0 for s, d in zip(shape, dims)):
            # non-tiling shape (e.g. 1x3 in 2x4): the BFS below handles it
            continue
        for sub in enumerate_subslices(topo_str, shape):
            idxs = [coord_to_index(c, dims) for c in sub.coords()]
            if all(i in avail for i in idxs) and must <= set(idxs):
                return sorted(idxs)

    def grow(seeds: List[int]) -> List[int]:
        chosen = list(seeds)
        frontier = list(seeds)
        while frontier and len(chosen) < count:
            cur = frontier.pop(0)
            for nb in neighbors(coords_by_idx[cur], topo_str, generation):
                nb_idx = coord_to_index(nb, dims)
                if nb_idx in avail and nb_idx not in chosen:
                    chosen.append(nb_idx)
                    frontier.append(nb_idx)
                    if len(chosen) == count:
                        break
        return chosen

    # greedy BFS fallback: grow a connected set outward from the
    # must-include chips (or from each available chip when unconstrained)
    if must:
        chosen = grow(sorted(must))
        if len(chosen) < count:
            chosen += sorted(avail - set(chosen))[: count - len(chosen)]
        return sorted(chosen)
    for seed in sorted(avail):
        chosen = grow([seed])
        if len(chosen) == count:
            return sorted(chosen)
    # disconnected last resort
    return sorted(avail)[:count]


def _blocks_of(count: int, dims: Sequence[int]) -> Iterator[Tuple[int, ...]]:
    """Axis-aligned shapes with exactly ``count`` chips that fit in dims,
    most compact (cube-like) first."""
    n = len(dims)
    shapes = set()

    def rec(remaining: int, axis: int, shape: List[int]):
        if axis == n:
            if remaining == 1:
                shapes.add(tuple(shape))
            return
        d = 1
        while d <= dims[axis]:
            if remaining % d == 0:
                rec(remaining // d, axis + 1, shape + [d])
            d += 1

    rec(count, 0, [])
    return iter(
        sorted(shapes, key=lambda s: (max(s) - min(s), sorted(s, reverse=True)))
    )
