"""Flash-attention forward as a pallas TPU kernel — the hot-op depth
probe.

The reference's validation workloads stop at CUDA ``vectorAdd``; the
TPU-native validator already proves the MXU (matmul), HBM (pallas DMA
memcpy) and ICI (ring/collective probes). This kernel proves the
``pallas`` path XLA cannot fuse on its own: blockwise attention with
ONLINE softmax — running max + denominator carried in f32 across K/V
blocks while the MXU consumes bf16 tiles — the memory-bound pattern that
dominates long-context serving (same math the ring-attention probe runs
ACROSS chips via ppermute, here tiled WITHIN one chip's VMEM).

Numerics are validated against naive full attention in f32; throughput
is reported as achieved TFLOPS over the exact FLOPs the causal tiling
performs (skipped upper-triangle blocks are not counted).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Optional

LANES = 128  # TPU lane width; head_dim is kept lane-aligned


def diag_stop(i, block_q: int, block_k: int):
    """K-blocks a causal q-block ``i`` must process: through the block
    containing its last row. The single source for both the kernel's loop
    bound and the FLOPs accounting — they must never drift, or reported
    TFLOPS is computed against the wrong work. ``seq % block_k == 0``
    (enforced at build) keeps this <= n_k_blocks. Works on python ints
    and traced values alike."""
    return ((i + 1) * block_q + block_k - 1) // block_k


@dataclass
class FlashAttnResult:
    ok: bool
    platform: str = ""
    device_kind: str = ""
    seq: int = 0
    heads: int = 0
    head_dim: int = 0
    causal: bool = True
    max_err: float = 0.0
    tflops: float = 0.0
    tflops_effective: float = 0.0
    elapsed_s: float = 0.0
    error: str = ""

    def to_dict(self):
        return {
            "ok": self.ok,
            "platform": self.platform,
            "device_kind": self.device_kind,
            "seq": self.seq,
            "heads": self.heads,
            "head_dim": self.head_dim,
            "causal": self.causal,
            "max_err": round(self.max_err, 6),
            "tflops": round(self.tflops, 2),
            "tflops_effective": round(self.tflops_effective, 2),
            "elapsed_s": round(self.elapsed_s, 4),
        }


def make_flash_fn(
    seq: int,
    heads: int,
    head_dim: int = LANES,
    block_q: int = 256,
    block_k: int = 1024,
    causal: bool = True,
    interpret: bool = False,
    variant: str = "full",
):
    """Build the jitted flash-attention forward over ``(H, S, D)`` bf16
    Q/K/V. Grid is (head, q-block); each kernel instance streams K/V
    blocks for its head with a running-max/denominator carry (the flash
    recurrence), masking nothing it can skip: causal q-blocks stop at
    their diagonal block.

    ``variant`` selects instrumented kernels for phase ATTRIBUTION of the
    flashattn-vs-matmul gap (round-4 verdict #3) — same grid, same block
    streaming, surgically removed phases (numerics are wrong by design
    for the stubs; only "full"/"pipelined" pass the oracle):

    * ``full``          — the shipped kernel;
    * ``pipelined``     — software-pipelined: block j's QKᵀ (MXU) issued
      in the same loop body as block j-1's softmax (VPU) + PV, giving
      Mosaic's static scheduler visibility to overlap the units;
    * ``softmax_stub``  — both matmuls, softmax replaced by a copy
      (t_full − t_stub ≈ the un-overlapped softmax/VPU cost);
    * ``qk_only``       — the QKᵀ matmul alone (half the FLOPs: pure
      MXU + K-streaming rate).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    if seq % block_q or seq % block_k:
        raise ValueError(f"seq={seq} must tile by {block_q}/{block_k}")
    if variant not in (
        "full", "pipelined", "softmax_stub", "qk_only", "bf16exp"
    ):
        raise ValueError(f"unknown flash variant {variant!r}")
    scale = 1.0 / (head_dim**0.5)
    n_k_blocks = seq // block_k

    def kernel(q_ref, k_ref, v_ref, o_ref):
        i = pl.program_id(1)
        q = q_ref[0]  # (block_q, D) bf16 — stays bf16 for the MXU

        if causal:
            # blocks fully above the diagonal contribute nothing
            hi = diag_stop(i, block_q, block_k)
            # blocks fully BELOW the diagonal need no mask at all: every
            # kpos <= every qpos when (j+1)*block_k - 1 <= i*block_q.
            # Masking them anyway costs two iotas + compare + select on
            # (block_q, block_k) per block — pure VPU overhead on the
            # vast majority of blocks at long seq (the MXU sits idle
            # while the VPU grinds); splitting the loop removes it
            n_full = (i * block_q) // block_k
        else:
            hi = n_k_blocks
            n_full = n_k_blocks

        def scores(j):
            k = k_ref[0, pl.ds(j * block_k, block_k), :]
            return (
                lax.dot_general(
                    q, k, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                * scale
            )

        def mask(j, s):
            qpos = i * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            kpos = j * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            return jnp.where(qpos >= kpos, s, -jnp.inf)

        def soft_update(j, s, m, l, acc):
            """One online-softmax + PV step against block ``j``'s V."""
            m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
            alpha = jnp.exp(m - m_new)
            if variant == "bf16exp":
                # the (block_q, block_k) exp is the VPU phase's bulk; the
                # MXU consumes p as bf16 anyway, so computing the exp in
                # bf16 (packed VPU lanes) halves the element width on the
                # hot path. Stability lives in the f32 row-max SUBTRACTION
                # (s - m_new ≤ 0, computed in f32 before the cast) and
                # the f32 running denominator; only exp's output mantissa
                # drops, which the bf16 PV matmul was dropping anyway.
                p = jnp.exp((s - m_new).astype(jnp.bfloat16))
                l_new = alpha * l + jnp.sum(
                    p, axis=-1, keepdims=True, dtype=jnp.float32
                )
                pv = p
            else:
                p = jnp.exp(s - m_new)
                l_new = alpha * l + p.sum(axis=-1, keepdims=True)
                pv = p.astype(jnp.bfloat16)
            v = v_ref[0, pl.ds(j * block_k, block_k), :]
            acc_new = acc * alpha + lax.dot_general(
                pv, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            return m_new, l_new, acc_new

        m0 = jnp.full((block_q, 1), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((block_q, 1), jnp.float32)
        acc0 = jnp.zeros((block_q, head_dim), jnp.float32)

        if variant == "qk_only":
            # attribution: QKᵀ + K-streaming alone — the output is a
            # reduction of the scores so the matmul cannot be DCE'd
            def body(j, acc):
                s = scores(j)
                return acc + s[:, :head_dim]

            acc = lax.fori_loop(0, hi, body, acc0)
            o_ref[0] = acc.astype(o_ref.dtype)
            return

        if variant == "softmax_stub":
            # attribution: both matmuls at full rate, softmax replaced
            # by a cast (no exp/max/renorm — the VPU phase removed)
            def body(j, acc):
                s = scores(j)
                v = v_ref[0, pl.ds(j * block_k, block_k), :]
                return acc + lax.dot_general(
                    (s * 0.001).astype(jnp.bfloat16),
                    v,
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )

            acc = lax.fori_loop(0, hi, body, acc0)
            o_ref[0] = acc.astype(o_ref.dtype)
            return

        if variant == "pipelined":
            # software pipeline over the UNMASKED range: the loop body
            # computes block j's scores (MXU) next to block j-1's
            # softmax+PV (VPU + MXU) — independent work, so the static
            # scheduler can overlap the units instead of serializing
            # qkT → softmax → pv per block
            def pipe_body(j, carry):
                m, l, acc, s_prev = carry
                s_cur = scores(j)
                m2, l2, acc2 = soft_update(j - 1, s_prev, m, l, acc)
                return m2, l2, acc2, s_cur

            # no outer cond: when n_full == 0 the prefetch reads block 0
            # (harmless) and the drain below is select-skipped — keeping
            # ONE score carry live instead of cond-duplicated buffers
            # (the cond form blew the 16M scoped-vmem limit at bk=2048)
            s0 = scores(0)
            m, l, acc, s_last = lax.fori_loop(
                1, n_full, pipe_body, (m0, l0, acc0, s0)
            )
            carry = lax.cond(
                n_full > 0,
                lambda c: soft_update(n_full - 1, c[3], c[0], c[1], c[2]),
                lambda c: (c[0], c[1], c[2]),
                (m, l, acc, s_last),
            )
            if causal:

                def tail_body(j, carry):
                    m, l, acc = carry
                    return soft_update(j, mask(j, scores(j)), m, l, acc)

                carry = lax.fori_loop(n_full, hi, tail_body, carry)
            m, l, acc = carry
            o_ref[0] = (acc / l).astype(o_ref.dtype)
            return

        def make_body(masked: bool):
            def body(j, carry):
                m, l, acc = carry
                s = scores(j)
                if masked:
                    s = mask(j, s)
                return soft_update(j, s, m, l, acc)

            return body

        carry = lax.fori_loop(0, n_full, make_body(False), (m0, l0, acc0))
        if causal:
            # only the diagonal-straddling tail pays for masking
            carry = lax.fori_loop(n_full, hi, make_body(True), carry)
        m, l, acc = carry
        o_ref[0] = (acc / l).astype(o_ref.dtype)

    kwargs = {}
    if not interpret:
        # every grid step is independent (the flash carry lives INSIDE
        # one kernel instance): telling Mosaic both dims are parallel
        # frees its scheduler to reorder/partition grid steps. The API
        # moved across jax versions (TPUCompilerParams + strings before
        # CompilerParams + GridDimensionSemantics); a jax without either
        # still runs the kernel, just without the scheduling hint —
        # never fail the probe over an optional optimization.
        try:
            from jax.experimental.pallas import tpu as pltpu

            params_cls = getattr(pltpu, "CompilerParams", None) or getattr(
                pltpu, "TPUCompilerParams", None
            )
            sem = getattr(pltpu, "GridDimensionSemantics", None)
            parallel = sem.PARALLEL if sem is not None else "parallel"
            if params_cls is not None:
                params = {"dimension_semantics": (parallel, parallel)}
                # Mosaic's DEFAULT scoped-vmem budget is 16 MiB — a
                # compiler default, not the hardware (v5e carries 128 MiB
                # VMEM). The round-3 tuning note "512/4096 exceeds VMEM"
                # was this default's ceiling, and the pipelined variant's
                # score carry tips 512/2048 over it too. 64 MiB leaves
                # the pipeline framework ample headroom while freeing
                # the block space the tuning actually wants.
                try:
                    kwargs["compiler_params"] = params_cls(
                        vmem_limit_bytes=64 * 1024 * 1024, **params
                    )
                except TypeError:  # older API without the knob
                    kwargs["compiler_params"] = params_cls(**params)
        except Exception:  # pragma: no cover - version-dependent
            pass

    def flash(q, k, v):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((heads, seq, head_dim), q.dtype),
            grid=(heads, seq // block_q),
            in_specs=[
                pl.BlockSpec((1, block_q, head_dim), lambda h, i: (h, i, 0)),
                pl.BlockSpec((1, seq, head_dim), lambda h, i: (h, 0, 0)),
                pl.BlockSpec((1, seq, head_dim), lambda h, i: (h, 0, 0)),
            ],
            out_specs=pl.BlockSpec(
                (1, block_q, head_dim), lambda h, i: (h, i, 0)
            ),
            interpret=interpret,
            **kwargs,
        )(q, k, v)

    return jax.jit(flash)


def reference_attention(q, k, v, causal: bool = True):
    """Naive full attention in f32 — the numerics oracle."""
    import jax.numpy as jnp

    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("hqd,hkd->hqk", qf, kf) * scale
    if causal:
        seq = q.shape[1]
        mask = jnp.tril(jnp.ones((seq, seq), bool))
        s = jnp.where(mask[None], s, -jnp.inf)
    p = jnp.exp(s - s.max(axis=-1, keepdims=True))
    p = p / p.sum(axis=-1, keepdims=True)
    return jnp.einsum("hqk,hkd->hqd", p, vf)


def causal_flops(seq: int, heads: int, head_dim: int, block_q: int, block_k: int) -> float:
    """Exact FLOPs the causal tiling performs: two bf16 matmuls per
    processed (q-block, k-block) pair, skipped blocks not counted."""
    n_q = seq // block_q
    total_blocks = sum(diag_stop(i, block_q, block_k) for i in range(n_q))
    return 4.0 * heads * total_blocks * block_q * block_k * head_dim


def run_flashattn_breakdown(
    seq: int = 8192,
    heads: int = 8,
    head_dim: int = LANES,
    block_q: int = 256,
    block_k: int = 1024,
    iters: int = 32,
) -> dict:
    """Measured phase attribution of the flash-vs-matmul gap (round-4
    verdict #3): time the instrumented variants at the tuned shape and
    decompose one block-pair's cost into MXU matmul time vs softmax/VPU
    time vs everything else. TPU only; returns ``{"ok": False}`` off-TPU.

    The causal FLOPs accounting is per-variant (qk_only performs half
    the matmul work), so each variant's ``tflops`` is honest against the
    work IT does; ``per_pair_us`` (microseconds per processed q×k block
    pair) is the comparable cost unit across variants."""
    out = {"ok": False, "seq": seq, "heads": heads,
           "block_q": block_q, "block_k": block_k}
    try:
        import jax
        import jax.numpy as jnp

        dev = jax.devices()[0]
        if dev.platform != "tpu":
            out["error"] = "breakdown requires the TPU"
            return out
        from tpu_operator.workloads.timing import chain_per_iter_seconds

        key = jax.random.PRNGKey(13)
        kq, kk, kv = jax.random.split(key, 3)
        shape = (heads, seq, head_dim)
        q = jax.random.normal(kq, shape, jnp.bfloat16)
        k = jax.random.normal(kk, shape, jnp.bfloat16)
        v = jax.random.normal(kv, shape, jnp.bfloat16)

        n_q = seq // block_q
        pairs = heads * sum(diag_stop(i, block_q, block_k) for i in range(n_q))
        flops_full = causal_flops(seq, heads, head_dim, block_q, block_k)

        from tpu_operator.workloads.matmul import device_generation
        from tpu_operator.workloads.topology import PEAK_BF16_TFLOPS

        gen = device_generation(dev.device_kind)
        peak = PEAK_BF16_TFLOPS.get(gen) if gen else None

        variants = {}
        for name in ("full", "pipelined", "softmax_stub", "qk_only"):
            fn = make_flash_fn(
                seq, heads, head_dim, block_q, block_k,
                causal=True, interpret=False, variant=name,
            )

            def step(x, fn=fn):
                return fn(x, k, v)

            def force(x):
                return float(jnp.sum(x[0, 0, :8]))

            flops = flops_full if name != "qk_only" else flops_full / 2

            def plausible(per_iter):
                # every variant's MXU work is bounded by the chip peak;
                # a super-peak reading is a tunnel timing-sync artifact,
                # not a fast kernel (same policy as the probe's gate)
                return peak is None or flops / per_iter / 1e12 <= peak * 1.05

            # best-of-2 with up to 2 plausibility retries: single runs
            # swing with tunnel state and can read impossibly fast
            readings = [
                chain_per_iter_seconds(step, q, force, iters)
                for _ in range(2)
            ]
            while True:
                sane = [r for r in readings if plausible(r)]
                if sane or len(readings) >= 4:
                    break
                readings.append(chain_per_iter_seconds(step, q, force, iters))
            entry_implausible = not sane
            # all-implausible fallback: the SLOWEST reading — the fastest
            # one is the most corrupted (super-peak sync artifact), and
            # the attribution math must not ride it
            per_iter = min(sane) if sane else max(readings)
            variants[name] = {
                "tflops": round(flops / per_iter / 1e12, 1),
                "per_pair_us": round(per_iter / pairs * 1e6, 3),
                "per_iter_ms": round(per_iter * 1e3, 3),
                **({"implausible": True} if entry_implausible else {}),
            }
        out["variants"] = variants

        t_full = variants["full"]["per_pair_us"]
        t_pipe = variants["pipelined"]["per_pair_us"]
        t_stub = variants["softmax_stub"]["per_pair_us"]
        t_qk = variants["qk_only"]["per_pair_us"]
        out["attribution"] = {
            # both matmuls at full rate, no softmax: the MXU+streaming floor
            "matmuls_us": t_stub,
            # what the online softmax ADDS on top of the matmuls when
            # serialized (the shipped kernel's structure)
            "softmax_added_us": round(t_full - t_stub, 3),
            "softmax_fraction_of_full": round(
                max(0.0, (t_full - t_stub)) / t_full, 4
            ),
            # second matmul's marginal cost over QKᵀ alone
            "pv_added_us": round(t_stub - t_qk, 3),
            # what software-pipelining recovers of the softmax cost
            "pipeline_recovered_us": round(t_full - t_pipe, 3),
        }
        out["measurement_clean"] = not any(
            v.get("implausible") for v in variants.values()
        )
        out["ok"] = True
        return out
    except Exception as e:
        out["error"] = f"{type(e).__name__}: {e}"
        return out


def run_flashattn_probe(
    seq: int = 2048,
    heads: int = 8,
    head_dim: int = LANES,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    causal: bool = True,
    iters: int = 64,
    expect_tpu: bool = False,
    tol: float = 2e-2,
    variant: str = "full",
) -> FlashAttnResult:
    """Correctness vs the f32 oracle, then throughput (fixed-overhead-
    cancelling chain timing, like the matmul/membw probes; ``iters``
    defaults high because one flash pass is only a few ms and tunnel
    round-trips must be amortized). A reading above the chip's rated
    matmul peak is a broken measurement and fails the probe, same policy
    as bench's plausibility gates."""
    try:
        import jax
        import jax.numpy as jnp
    except Exception as e:  # pragma: no cover
        return FlashAttnResult(False, error=str(e))
    try:
        dev = jax.devices()[0]
        on_tpu = dev.platform == "tpu"
        if expect_tpu and not on_tpu:
            raise RuntimeError(f"expected TPU, found platform={dev.platform}")
        interpret = not on_tpu
        # measured optimum on v5e at seq 8192 (round-5 drift-cancelled
        # sweep, scripts/fa_walltune.py): 256/1024 beats the round-3
        # 512/2048 by 13-16% WALL TIME (tighter diagonal tracking does
        # 10% less masked compute) and ~4% per performed FLOP. The
        # round-3 sweep that picked 512/2048 predated the 64 MiB
        # scoped-vmem raise and was not drift-cancelled; larger blocks
        # (512/4096) only look faster per-FLOP because causal tiling
        # with coarse k-blocks performs MORE flops for the same task.
        def _default_block(cap: int) -> int:
            # largest sublane-aligned divisor of seq <= cap: a bare
            # min(cap, seq) breaks seqs the old 512/2048 defaults
            # handled (1536 % 1024 != 0). Alignment floor of 8 rejects
            # degenerate tilings (prime seq would otherwise "succeed"
            # with 1-row blocks and a meaningless rate) — those fall
            # through to min(cap, seq) so make_flash_fn raises its
            # clear must-tile error instead.
            return next(
                (
                    d
                    for d in range(min(cap, seq), 7, -1)
                    if seq % d == 0 and d % 8 == 0
                ),
                min(cap, seq),
            )

        bq = block_q if block_q is not None else _default_block(256)
        bk = block_k if block_k is not None else _default_block(1024)

        key = jax.random.PRNGKey(11)
        kq, kk, kv = jax.random.split(key, 3)
        shape = (heads, seq, head_dim)
        q = jax.random.normal(kq, shape, jnp.bfloat16)
        k = jax.random.normal(kk, shape, jnp.bfloat16)
        v = jax.random.normal(kv, shape, jnp.bfloat16)

        flash = make_flash_fn(
            seq, heads, head_dim, bq, bk, causal, interpret, variant=variant
        )
        out = flash(q, k, v)
        ref = reference_attention(q, k, v, causal)
        max_err = float(
            jnp.max(jnp.abs(out.astype(jnp.float32) - ref))
        )
        if not max_err < tol:
            raise RuntimeError(
                f"flash attention diverged from the oracle: max_err={max_err}"
            )

        flops = (
            causal_flops(seq, heads, head_dim, bq, bk)
            if causal
            else 4.0 * heads * seq * seq * head_dim
        )
        # tiling-INDEPENDENT useful work: the exact causal triangle
        # (each query attends to q+1 keys), no credit for masked-region
        # compute a coarse tiling performs. ``tflops`` rewards tilings
        # that do more redundant work; ``tflops_effective`` is the
        # task-level number two tilings can be honestly compared on.
        flops_effective = (
            4.0 * heads * head_dim * seq * (seq + 1) / 2.0
            if causal
            else 4.0 * heads * seq * seq * head_dim
        )
        if on_tpu:
            from tpu_operator.workloads.timing import chain_per_iter_seconds

            # chain through q so iterations can't overlap on-device
            def step(x):
                return flash(x, k, v)

            def force(x):
                return float(jnp.sum(x[0, 0, :8]))

            per_iter = chain_per_iter_seconds(step, q, force, iters)
            tflops = flops / per_iter / 1e12
            tflops_effective = flops_effective / per_iter / 1e12
            elapsed = per_iter * iters
            from tpu_operator.workloads.matmul import device_generation
            from tpu_operator.workloads.topology import PEAK_BF16_TFLOPS

            gen = device_generation(dev.device_kind)
            peak = PEAK_BF16_TFLOPS.get(gen) if gen else None
            if peak and tflops > peak * 1.05:
                raise RuntimeError(
                    f"implausible flash-attention rate ({tflops:.0f} TFLOPS "
                    f"vs peak {peak}); timing sync failure — rerun"
                )
        else:
            tflops = 0.0  # interpret mode: numerics only
            tflops_effective = 0.0
            elapsed = 0.0
        return FlashAttnResult(
            ok=True,
            platform=dev.platform,
            device_kind=dev.device_kind,
            seq=seq,
            heads=heads,
            head_dim=head_dim,
            causal=causal,
            max_err=max_err,
            tflops=tflops,
            tflops_effective=tflops_effective,
            elapsed_s=elapsed,
        )
    except Exception as e:
        return FlashAttnResult(False, error=str(e))
