"""Flash-attention forward as a pallas TPU kernel — the hot-op depth
probe.

The reference's validation workloads stop at CUDA ``vectorAdd``; the
TPU-native validator already proves the MXU (matmul), HBM (pallas DMA
memcpy) and ICI (ring/collective probes). This kernel proves the
``pallas`` path XLA cannot fuse on its own: blockwise attention with
ONLINE softmax — running max + denominator carried in f32 across K/V
blocks while the MXU consumes bf16 tiles — the memory-bound pattern that
dominates long-context serving (same math the ring-attention probe runs
ACROSS chips via ppermute, here tiled WITHIN one chip's VMEM).

Numerics are validated against naive full attention in f32; throughput
is reported as achieved TFLOPS over the exact FLOPs the causal tiling
performs (skipped upper-triangle blocks are not counted).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Optional

LANES = 128  # TPU lane width; head_dim is kept lane-aligned


def diag_stop(i, block_q: int, block_k: int):
    """K-blocks a causal q-block ``i`` must process: through the block
    containing its last row. The single source for both the kernel's loop
    bound and the FLOPs accounting — they must never drift, or reported
    TFLOPS is computed against the wrong work. ``seq % block_k == 0``
    (enforced at build) keeps this <= n_k_blocks. Works on python ints
    and traced values alike."""
    return ((i + 1) * block_q + block_k - 1) // block_k


@dataclass
class FlashAttnResult:
    ok: bool
    platform: str = ""
    device_kind: str = ""
    seq: int = 0
    heads: int = 0
    head_dim: int = 0
    causal: bool = True
    max_err: float = 0.0
    tflops: float = 0.0
    elapsed_s: float = 0.0
    error: str = ""

    def to_dict(self):
        return {
            "ok": self.ok,
            "platform": self.platform,
            "device_kind": self.device_kind,
            "seq": self.seq,
            "heads": self.heads,
            "head_dim": self.head_dim,
            "causal": self.causal,
            "max_err": round(self.max_err, 6),
            "tflops": round(self.tflops, 2),
            "elapsed_s": round(self.elapsed_s, 4),
        }


def make_flash_fn(
    seq: int,
    heads: int,
    head_dim: int = LANES,
    block_q: int = 256,
    block_k: int = 1024,
    causal: bool = True,
    interpret: bool = False,
):
    """Build the jitted flash-attention forward over ``(H, S, D)`` bf16
    Q/K/V. Grid is (head, q-block); each kernel instance streams K/V
    blocks for its head with a running-max/denominator carry (the flash
    recurrence), masking nothing it can skip: causal q-blocks stop at
    their diagonal block."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.experimental import pallas as pl

    if seq % block_q or seq % block_k:
        raise ValueError(f"seq={seq} must tile by {block_q}/{block_k}")
    scale = 1.0 / (head_dim**0.5)
    n_k_blocks = seq // block_k

    def kernel(q_ref, k_ref, v_ref, o_ref):
        i = pl.program_id(1)
        q = q_ref[0]  # (block_q, D) bf16 — stays bf16 for the MXU

        if causal:
            # blocks fully above the diagonal contribute nothing
            hi = diag_stop(i, block_q, block_k)
            # blocks fully BELOW the diagonal need no mask at all: every
            # kpos <= every qpos when (j+1)*block_k - 1 <= i*block_q.
            # Masking them anyway costs two iotas + compare + select on
            # (block_q, block_k) per block — pure VPU overhead on the
            # vast majority of blocks at long seq (the MXU sits idle
            # while the VPU grinds); splitting the loop removes it
            n_full = (i * block_q) // block_k
        else:
            hi = n_k_blocks
            n_full = n_k_blocks

        def make_body(masked: bool):
            def body(j, carry):
                m, l, acc = carry
                k = k_ref[0, pl.ds(j * block_k, block_k), :]
                s = (
                    lax.dot_general(
                        q, k, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32,
                    )
                    * scale
                )
                if masked:
                    qpos = i * block_q + lax.broadcasted_iota(
                        jnp.int32, (block_q, block_k), 0
                    )
                    kpos = j * block_k + lax.broadcasted_iota(
                        jnp.int32, (block_q, block_k), 1
                    )
                    s = jnp.where(qpos >= kpos, s, -jnp.inf)
                m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
                alpha = jnp.exp(m - m_new)
                p = jnp.exp(s - m_new)
                l_new = alpha * l + p.sum(axis=-1, keepdims=True)
                v = v_ref[0, pl.ds(j * block_k, block_k), :]
                acc_new = acc * alpha + lax.dot_general(
                    p.astype(jnp.bfloat16), v, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                return m_new, l_new, acc_new

            return body

        m0 = jnp.full((block_q, 1), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((block_q, 1), jnp.float32)
        acc0 = jnp.zeros((block_q, head_dim), jnp.float32)
        carry = lax.fori_loop(0, n_full, make_body(False), (m0, l0, acc0))
        if causal:
            # only the diagonal-straddling tail pays for masking
            carry = lax.fori_loop(n_full, hi, make_body(True), carry)
        m, l, acc = carry
        o_ref[0] = (acc / l).astype(o_ref.dtype)

    kwargs = {}
    if not interpret:
        # every grid step is independent (the flash carry lives INSIDE
        # one kernel instance): telling Mosaic both dims are parallel
        # frees its scheduler to reorder/partition grid steps. The API
        # moved across jax versions (TPUCompilerParams + strings before
        # CompilerParams + GridDimensionSemantics); a jax without either
        # still runs the kernel, just without the scheduling hint —
        # never fail the probe over an optional optimization.
        try:
            from jax.experimental.pallas import tpu as pltpu

            params_cls = getattr(pltpu, "CompilerParams", None) or getattr(
                pltpu, "TPUCompilerParams", None
            )
            sem = getattr(pltpu, "GridDimensionSemantics", None)
            parallel = sem.PARALLEL if sem is not None else "parallel"
            if params_cls is not None:
                kwargs["compiler_params"] = params_cls(
                    dimension_semantics=(parallel, parallel)
                )
        except Exception:  # pragma: no cover - version-dependent
            pass

    def flash(q, k, v):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((heads, seq, head_dim), q.dtype),
            grid=(heads, seq // block_q),
            in_specs=[
                pl.BlockSpec((1, block_q, head_dim), lambda h, i: (h, i, 0)),
                pl.BlockSpec((1, seq, head_dim), lambda h, i: (h, 0, 0)),
                pl.BlockSpec((1, seq, head_dim), lambda h, i: (h, 0, 0)),
            ],
            out_specs=pl.BlockSpec(
                (1, block_q, head_dim), lambda h, i: (h, i, 0)
            ),
            interpret=interpret,
            **kwargs,
        )(q, k, v)

    return jax.jit(flash)


def reference_attention(q, k, v, causal: bool = True):
    """Naive full attention in f32 — the numerics oracle."""
    import jax.numpy as jnp

    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("hqd,hkd->hqk", qf, kf) * scale
    if causal:
        seq = q.shape[1]
        mask = jnp.tril(jnp.ones((seq, seq), bool))
        s = jnp.where(mask[None], s, -jnp.inf)
    p = jnp.exp(s - s.max(axis=-1, keepdims=True))
    p = p / p.sum(axis=-1, keepdims=True)
    return jnp.einsum("hqk,hkd->hqd", p, vf)


def causal_flops(seq: int, heads: int, head_dim: int, block_q: int, block_k: int) -> float:
    """Exact FLOPs the causal tiling performs: two bf16 matmuls per
    processed (q-block, k-block) pair, skipped blocks not counted."""
    n_q = seq // block_q
    total_blocks = sum(diag_stop(i, block_q, block_k) for i in range(n_q))
    return 4.0 * heads * total_blocks * block_q * block_k * head_dim


def run_flashattn_probe(
    seq: int = 2048,
    heads: int = 8,
    head_dim: int = LANES,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    causal: bool = True,
    iters: int = 64,
    expect_tpu: bool = False,
    tol: float = 2e-2,
) -> FlashAttnResult:
    """Correctness vs the f32 oracle, then throughput (fixed-overhead-
    cancelling chain timing, like the matmul/membw probes; ``iters``
    defaults high because one flash pass is only a few ms and tunnel
    round-trips must be amortized). A reading above the chip's rated
    matmul peak is a broken measurement and fails the probe, same policy
    as bench's plausibility gates."""
    try:
        import jax
        import jax.numpy as jnp
    except Exception as e:  # pragma: no cover
        return FlashAttnResult(False, error=str(e))
    try:
        dev = jax.devices()[0]
        on_tpu = dev.platform == "tpu"
        if expect_tpu and not on_tpu:
            raise RuntimeError(f"expected TPU, found platform={dev.platform}")
        interpret = not on_tpu
        # measured optimum on v5e at seq 8192 (block sweep, round 3):
        # 512/2048 beats the round-2 256/1024 by ~40% — fewer
        # softmax/carry rounds per FLOP; 512/4096 exceeds VMEM
        bq = block_q if block_q is not None else min(512, seq)
        bk = block_k if block_k is not None else min(2048, seq)

        key = jax.random.PRNGKey(11)
        kq, kk, kv = jax.random.split(key, 3)
        shape = (heads, seq, head_dim)
        q = jax.random.normal(kq, shape, jnp.bfloat16)
        k = jax.random.normal(kk, shape, jnp.bfloat16)
        v = jax.random.normal(kv, shape, jnp.bfloat16)

        flash = make_flash_fn(
            seq, heads, head_dim, bq, bk, causal, interpret
        )
        out = flash(q, k, v)
        ref = reference_attention(q, k, v, causal)
        max_err = float(
            jnp.max(jnp.abs(out.astype(jnp.float32) - ref))
        )
        if not max_err < tol:
            raise RuntimeError(
                f"flash attention diverged from the oracle: max_err={max_err}"
            )

        flops = (
            causal_flops(seq, heads, head_dim, bq, bk)
            if causal
            else 4.0 * heads * seq * seq * head_dim
        )
        if on_tpu:
            from tpu_operator.workloads.timing import chain_per_iter_seconds

            # chain through q so iterations can't overlap on-device
            def step(x):
                return flash(x, k, v)

            def force(x):
                return float(jnp.sum(x[0, 0, :8]))

            per_iter = chain_per_iter_seconds(step, q, force, iters)
            tflops = flops / per_iter / 1e12
            elapsed = per_iter * iters
            from tpu_operator.workloads.matmul import device_generation
            from tpu_operator.workloads.topology import PEAK_BF16_TFLOPS

            gen = device_generation(dev.device_kind)
            peak = PEAK_BF16_TFLOPS.get(gen) if gen else None
            if peak and tflops > peak * 1.05:
                raise RuntimeError(
                    f"implausible flash-attention rate ({tflops:.0f} TFLOPS "
                    f"vs peak {peak}); timing sync failure — rerun"
                )
        else:
            tflops = 0.0  # interpret mode: numerics only
            elapsed = 0.0
        return FlashAttnResult(
            ok=True,
            platform=dev.platform,
            device_kind=dev.device_kind,
            seq=seq,
            heads=heads,
            head_dim=head_dim,
            causal=causal,
            max_err=max_err,
            tflops=tflops,
            elapsed_s=elapsed,
        )
    except Exception as e:
        return FlashAttnResult(False, error=str(e))
