"""HBM bandwidth probe — a Pallas streaming-copy kernel.

The reference's deep hardware diagnostics live in DCGM (``dcgmi diag`` run
levels include a memory-bandwidth test; the operator wires DCGM in
``assets/state-dcgm/`` and ``controllers/object_controls.go:1441-1495``).
The TPU analogue measures achieved HBM streaming bandwidth and compares it
against the chip generation's spec sheet — a sick HBM stack shows up as a
bandwidth cliff long before it corrupts training.

TPU-first design notes:
* the kernel is a grid-pipelined identity copy: each grid step Pallas
  DMAs one ``(block_rows, LANES)`` tile HBM→VMEM and writes it back
  VMEM→HBM, double-buffering automatically, so the measured time is pure
  HBM streaming (read + write) with compute fully hidden;
* blocks are f32 ``(32, 16384)`` = 2 MiB — long sequential DMAs that
  saturate the HBM controller while the pipeline's working set (in + out,
  double-buffered = 4 blocks = 8 MiB) stays inside the ~16 MiB/core VMEM
  budget;
* everything is statically shaped; iterations chain serially under jit
  dispatches and synchronize with one scalar fetch, the same
  fixed-overhead-cancelling delta timing as the matmul validation
  (``workloads/matmul.py``).

Off-TPU the kernel runs in Pallas interpreter mode on tiny shapes — tests
validate kernel semantics anywhere; the bandwidth number only means
something on real hardware.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from tpu_operator.workloads.matmul import device_generation
from tpu_operator.workloads.topology import PEAK_HBM_GBPS

LANES = 16384  # 128 lanes × 128: wide rows so each DMA is long and sequential


@dataclass
class MemBwResult:
    ok: bool
    device_kind: str
    platform: str
    size_mb: float
    iters: int
    elapsed_s: float
    gbps: float  # best achieved HBM throughput (max of the two probes)
    copy_gbps: float = 0.0  # pallas DMA-engine memcpy
    stream_gbps: float = 0.0  # XLA fused elementwise stream
    peak_gbps: Optional[float] = None
    utilization: Optional[float] = None
    integrity: bool = False
    error: str = ""

    def to_dict(self):
        return {
            "ok": self.ok,
            "device_kind": self.device_kind,
            "platform": self.platform,
            "size_mb": round(self.size_mb, 1),
            "iters": self.iters,
            "elapsed_s": round(self.elapsed_s, 6),
            "gbps": round(self.gbps, 1),
            "copy_gbps": round(self.copy_gbps, 1),
            "stream_gbps": round(self.stream_gbps, 1),
            "peak_gbps": self.peak_gbps,
            "utilization": round(self.utilization, 4)
            if self.utilization is not None
            else None,
            "integrity": self.integrity,
            "error": self.error,
        }


def make_copy_fn(rows: int, block_rows: int, interpret: bool = False):
    """Build the jitted streaming copy: ``(rows, LANES)`` f32 moved through
    VMEM one ``(block_rows, LANES)`` tile per grid step."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    if rows % block_rows:
        raise ValueError(f"rows={rows} not a multiple of block_rows={block_rows}")

    def kernel(in_ref, out_ref):
        out_ref[...] = in_ref[...]

    def copy(x):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((rows, LANES), jnp.float32),
            grid=(rows // block_rows,),
            in_specs=[pl.BlockSpec((block_rows, LANES), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((block_rows, LANES), lambda i: (i, 0)),
            interpret=interpret,
        )(x)

    return jax.jit(copy)


def make_dma_copy_fn(rows: int, n_chunks: int = 8):
    """Build the jitted raw-DMA copy: the whole ``(rows, LANES)`` buffer is
    moved HBM→HBM by ``n_chunks`` concurrently-outstanding DMAs (one per
    chunk, per-chunk semaphores), bypassing VMEM entirely — this measures
    the DMA engines, the closest thing the chip has to ``memcpy``."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if rows % n_chunks:
        raise ValueError(f"rows={rows} not a multiple of n_chunks={n_chunks}")
    chunk = rows // n_chunks

    def kernel(in_ref, out_ref):
        def body(sems):
            for i in range(n_chunks):  # static unroll: all DMAs in flight
                pltpu.make_async_copy(
                    in_ref.at[pl.ds(i * chunk, chunk), :],
                    out_ref.at[pl.ds(i * chunk, chunk), :],
                    sems.at[i],
                ).start()
            for i in range(n_chunks):
                pltpu.make_async_copy(
                    in_ref.at[pl.ds(i * chunk, chunk), :],
                    out_ref.at[pl.ds(i * chunk, chunk), :],
                    sems.at[i],
                ).wait()

        pl.run_scoped(body, sems=pltpu.SemaphoreType.DMA((n_chunks,)))

    def copy(x):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((rows, LANES), jnp.float32),
            in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
            out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
            compiler_params=pltpu.CompilerParams(has_side_effects=True),
        )(x)

    return jax.jit(copy)


# a bandwidth reading above hardware peak is a broken measurement (a
# timing-sync failure on the tunneled PJRT path), not a fast chip; small
# tolerance for spec rounding
PLAUSIBILITY_MARGIN = 1.05


def best_plausible_gbps(copy_gbps: float, stream_gbps: float, peak) -> float:
    """The better of the two paths among PHYSICALLY POSSIBLE readings.
    With a known peak, any path measuring above peak*margin is discarded;
    if both are implausible the measurement is invalid and raises — a
    bogus number must never be recorded as a healthy rate."""
    candidates = [g for g in (copy_gbps, stream_gbps) if g > 0]
    if peak:
        candidates = [g for g in candidates if g <= peak * PLAUSIBILITY_MARGIN]
    if not candidates:
        raise RuntimeError(
            f"implausible bandwidth measurement (copy={copy_gbps:.0f}, "
            f"stream={stream_gbps:.0f} GB/s vs peak {peak}); timing sync "
            "failure — rerun"
        )
    return max(candidates)


def run_membw_probe(
    size_mb: int = 2048,
    block_rows: int = 32,
    iters: int = 16,
    expect_tpu: bool = False,
) -> MemBwResult:
    """Measure achieved HBM bandwidth on one chip, two ways:

    * ``copy_gbps`` — the pallas raw-DMA memcpy (DMA engines, HBM→HBM);
    * ``stream_gbps`` — an XLA fused elementwise pass (read + write through
      the VPU, the pattern every activation/optimizer op hits).

    ``gbps``/``utilization`` report the better of the two: a healthy stack
    must sustain near-spec on at least one path, and which one degrades
    tells you where the sickness is.
    """
    try:
        import jax
        import jax.numpy as jnp
    except Exception as e:  # pragma: no cover
        return MemBwResult(False, "", "", size_mb, iters, 0.0, 0.0, error=str(e))

    try:
        devices = jax.devices()
        if not devices:
            raise RuntimeError("jax.devices() is empty")
        dev = devices[0]
        platform = dev.platform
        if expect_tpu and platform != "tpu":
            raise RuntimeError(f"expected TPU, found platform={platform}")
        on_tpu = platform == "tpu"

        bytes_per_row = LANES * 4
        align = 8 * block_rows  # keep rows divisible by block_rows and DMA chunks
        rows = max(align, (size_mb * (1 << 20)) // bytes_per_row)
        rows -= rows % align
        buf_bytes = rows * bytes_per_row

        copy_fn = (
            make_dma_copy_fn(rows, n_chunks=8)
            if on_tpu
            else make_copy_fn(rows, block_rows, interpret=True)
        )
        stream_fn = jax.jit(lambda v: v + 1.0)
        key = jax.random.PRNGKey(7)
        x = jax.random.normal(key, (rows, LANES), dtype=jnp.float32)

        # integrity: the copy must be bit-exact over the WHOLE buffer — a
        # corner probe would miss corruption in 7 of the 8 DMA chunks; the
        # comparison runs on-device and fetches one boolean
        y = copy_fn(x)
        integrity = bool(jax.device_get(jnp.array_equal(x, y)))
        if not integrity:
            raise RuntimeError("copy integrity check failed: HBM readback mismatch")

        def force(v):
            return float(jnp.sum(v[0, :128]))

        from tpu_operator.workloads.timing import chain_per_iter_seconds

        moved = 2.0 * buf_bytes  # each pass reads + writes the buffer once
        copy_per_iter = chain_per_iter_seconds(copy_fn, x, force, iters)
        copy_gbps = moved / copy_per_iter / 1e9
        stream_per_iter = chain_per_iter_seconds(stream_fn, x, force, iters)
        stream_gbps = moved / stream_per_iter / 1e9

        gen = device_generation(dev.device_kind)
        peak = PEAK_HBM_GBPS.get(gen) if gen else None
        gbps = best_plausible_gbps(copy_gbps, stream_gbps, peak)
        util = gbps / peak if peak else None
        return MemBwResult(
            ok=True,
            device_kind=dev.device_kind,
            platform=platform,
            size_mb=buf_bytes / (1 << 20),
            iters=iters,
            elapsed_s=(copy_per_iter + stream_per_iter) * iters,
            gbps=gbps,
            copy_gbps=copy_gbps,
            stream_gbps=stream_gbps,
            peak_gbps=peak,
            utilization=util,
            integrity=integrity,
        )
    except Exception as e:
        return MemBwResult(
            False, "", "", size_mb, iters, 0.0, 0.0, error=str(e)
        )
