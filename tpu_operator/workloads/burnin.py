"""Multi-chip slice burn-in: a sharded training step over a device Mesh.

The multi-host validation workload (SURVEY.md §7 "readiness semantics on
multi-host slices"): a pod-slice is only healthy if every chip computes AND
every ICI link carries collectives. A plain per-chip matmul proves the
former; this burn-in proves the latter by jitting a real train step whose
gradient sync (``psum`` over ``dp``) and tensor-parallel matmuls
(``all_gather``/``reduce_scatter`` over ``tp``) ride every mesh axis.

TPU-first: the model is sharded with ``jax.sharding.NamedSharding`` +
``jit`` so XLA inserts the collectives; no hand-written per-device code.
The same function runs on a virtual CPU mesh (tests, dryrun) and a real
multi-chip slice (the validator's ``--component slice`` burn-in).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple


@dataclass
class BurninResult:
    ok: bool
    n_devices: int
    mesh_shape: Tuple[int, int]
    steps: int
    final_loss: float
    loss_decreased: bool
    error: str = ""

    def to_dict(self):
        return {
            "ok": self.ok,
            "n_devices": self.n_devices,
            "mesh_shape": list(self.mesh_shape),
            "steps": self.steps,
            "final_loss": round(self.final_loss, 6),
            "loss_decreased": self.loss_decreased,
            "error": self.error,
        }


def _mesh_shape(n: int) -> Tuple[int, int]:
    """Factor n into (dp, tp), as square as possible with tp a power of two."""
    tp = 1
    while tp * 2 <= n and n % (tp * 2) == 0 and tp * 2 <= int(n**0.5) + 1:
        tp *= 2
    return n // tp, tp


def build_burnin(
    n_devices: Optional[int] = None,
    batch: int = 32,
    d_model: int = 256,
    d_hidden: int = 512,
):
    """Construct (mesh, jitted train step, params, opt_state, data).

    Layout: batch sharded over ``dp``; the two MLP weight matrices sharded
    over ``tp`` on their contracting/output dims, forcing XLA to insert
    all-gather/reduce-scatter on ``tp`` and psum on ``dp`` for the gradient
    mean — every ICI axis carries traffic each step.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise RuntimeError(
                f"need {n_devices} devices, have {len(devices)} "
                f"(platform={devices[0].platform})"
            )
        devices = devices[:n_devices]
    n = len(devices)
    dp, tp = _mesh_shape(n)
    import numpy as np

    mesh = Mesh(np.asarray(devices).reshape(dp, tp), axis_names=("dp", "tp"))

    key = jax.random.PRNGKey(42)
    k1, k2, k3, k4 = jax.random.split(key, 4)
    params = {
        "w1": jax.random.normal(k1, (d_model, d_hidden), jnp.float32)
        * (1.0 / d_model**0.5),
        "w2": jax.random.normal(k2, (d_hidden, d_model), jnp.float32)
        * (1.0 / d_hidden**0.5),
    }
    x = jax.random.normal(k3, (batch, d_model), jnp.float32)
    # a fixed random target makes the loss strictly decreasing under SGD
    y = jax.random.normal(k4, (batch, d_model), jnp.float32)

    param_sharding = {
        "w1": NamedSharding(mesh, P(None, "tp")),  # column-parallel
        "w2": NamedSharding(mesh, P("tp", None)),  # row-parallel
    }
    data_sharding = NamedSharding(mesh, P("dp", None))
    params = jax.device_put(params, param_sharding)
    x = jax.device_put(x, data_sharding)
    y = jax.device_put(y, data_sharding)

    def loss_fn(p, xb, yb):
        h = jnp.dot(xb, p["w1"], preferred_element_type=jnp.float32)
        h = jax.nn.gelu(h)
        out = jnp.dot(h, p["w2"], preferred_element_type=jnp.float32)
        return jnp.mean((out - yb) ** 2)

    @jax.jit
    def train_step(p, xb, yb):
        loss, grads = jax.value_and_grad(loss_fn)(p, xb, yb)
        # SGD; XLA emits the dp psum for the grad mean and tp collectives
        # for the sharded matmuls
        new_p = jax.tree_util.tree_map(lambda w, g: w - 0.05 * g, p, grads)
        return new_p, loss

    return mesh, train_step, params, (x, y)


def run_burnin(
    n_devices: Optional[int] = None, steps: int = 20, **kw
) -> BurninResult:
    try:
        mesh, train_step, params, (x, y) = build_burnin(n_devices=n_devices, **kw)
        losses = []
        for _ in range(steps):
            params, loss = train_step(params, x, y)
            losses.append(float(loss))
        dp, tp = mesh.devices.shape
        return BurninResult(
            ok=losses[-1] < losses[0],
            n_devices=mesh.devices.size,
            mesh_shape=(dp, tp),
            steps=steps,
            final_loss=losses[-1],
            loss_decreased=losses[-1] < losses[0],
        )
    except Exception as e:
        return BurninResult(
            ok=False,
            n_devices=0,
            mesh_shape=(0, 0),
            steps=steps,
            final_loss=float("nan"),
            loss_decreased=False,
            error=str(e),
        )
