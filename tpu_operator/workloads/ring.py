"""ICI ring probe: per-link health + bandwidth via ``ppermute``.

The burn-in (``workloads/burnin.py``) proves collectives work in aggregate;
this probe isolates *individual* ICI links: a payload is rotated around a
1-D ring of all devices with ``jax.lax.ppermute`` (the primitive ring
collectives — and ring attention — are built from). After ``world_size``
hops every shard must arrive back at its origin bit-exact, and the hop time
gives an aggregate link-bandwidth estimate.

TPU-first notes: ``shard_map`` over a 1-D mesh gives per-device code whose
neighbor sends XLA lowers onto physical ICI; payload is a static-shaped
bf16 buffer; hops run under one jit as a ``lax.fori_loop`` so the ring is
device-side, not host-stepped.

Used by ``tpu-validator --component ici`` and runnable on the virtual CPU
mesh (collectives compile and run; bandwidth numbers are then only
indicative).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple


@dataclass
class RingResult:
    ok: bool
    n_devices: int
    hops: int
    payload_mb: float
    elapsed_s: float
    gbps_per_hop: float
    integrity: bool
    error: str = ""

    def to_dict(self):
        return {
            "ok": self.ok,
            "n_devices": self.n_devices,
            "hops": self.hops,
            "payload_mb": self.payload_mb,
            "elapsed_s": round(self.elapsed_s, 6),
            "gbps_per_hop": round(self.gbps_per_hop, 3),
            "integrity": self.integrity,
            "error": self.error,
        }


def build_ring(n_devices: Optional[int] = None, payload_mb: float = 4.0):
    """Returns (mesh, jitted full-ring rotation fn, sharded payload)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    try:
        from jax import shard_map  # jax >= 0.8
    except ImportError:  # pragma: no cover
        from jax.experimental.shard_map import shard_map

    devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise RuntimeError(
                f"need {n_devices} devices, have {len(devices)}"
            )
        devices = devices[:n_devices]
    n = len(devices)
    mesh = Mesh(np.asarray(devices), axis_names=("ring",))

    # per-device payload: payload_mb of bf16, 128-lane aligned
    cols = 512
    rows = max(1, int(payload_mb * 2**20 / 2 / cols))
    # each device's shard is filled with its own ordinal
    host = np.broadcast_to(
        np.arange(n, dtype=np.float32).reshape(n, 1, 1), (n, rows, cols)
    ).reshape(n * rows, cols)
    x = jax.device_put(
        jnp.asarray(host, jnp.bfloat16), NamedSharding(mesh, P("ring", None))
    )

    def rotate_full_ring(xs):
        def body(_, val):
            return jax.lax.ppermute(
                val,
                axis_name="ring",
                perm=[(i, (i + 1) % n) for i in range(n)],
            )

        return jax.lax.fori_loop(0, n, body, xs)

    fn = jax.jit(
        shard_map(
            rotate_full_ring,
            mesh=mesh,
            in_specs=P("ring", None),
            out_specs=P("ring", None),
        )
    )
    return mesh, fn, x


def run_ring_probe(
    n_devices: Optional[int] = None,
    payload_mb: float = 4.0,
    iters: int = 4,
) -> RingResult:
    import time

    import numpy as np

    try:
        import jax

        mesh, fn, x = build_ring(n_devices=n_devices, payload_mb=payload_mb)
        n = mesh.devices.size
        if n < 2:
            # a 1-chip "ring" is vacuously healthy
            return RingResult(True, n, 0, payload_mb, 0.0, 0.0, True)
        out = fn(x)
        out.block_until_ready()  # compile + integrity round
        integrity = bool(np.array_equal(np.asarray(out), np.asarray(x)))
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(out)
        out.block_until_ready()
        elapsed = time.perf_counter() - t0
        # each link carries one shard per hop; the timed region runs
        # n*iters hops, so per-link bytes = shard_bytes * n * iters
        shard_bytes = x.nbytes / n
        per_link_bytes = shard_bytes * n * iters
        per_hop_gbps = (per_link_bytes / elapsed) * 8 / 1e9
        return RingResult(
            ok=integrity,
            n_devices=n,
            hops=n * iters,
            payload_mb=payload_mb,
            elapsed_s=elapsed,
            gbps_per_hop=per_hop_gbps,
            integrity=integrity,
        )
    except Exception as e:
        return RingResult(False, 0, 0, payload_mb, 0.0, 0.0, False, error=str(e))
