"""JAX matmul validation workload — the CUDA ``vectorAdd`` slot.

The reference proves end-to-end GPU access by running a tiny CUDA binary in
a pod (``validator/cuda-workload-validation.yaml:20``,
``validator/main.go:1217-1293``). The TPU equivalent both *proves* chip
access (``jax.devices()`` + a correctness-checked matmul) and *measures* it:
the validation emits achieved bf16 TFLOPS/chip, which is the operator's
headline benchmark (BASELINE.md).

TPU-first design notes:
* bf16 inputs, f32 accumulation (``preferred_element_type``) — the MXU's
  native contract;
* sizes are multiples of 256 so XLA tiles cleanly onto the 128×128 MXU;
* a K-chained matmul loop under one ``jit`` keeps the benchmark
  compute-bound instead of HBM-bound, measuring the systolic array rather
  than input streaming;
* everything is statically shaped; timing feeds each dispatch's output into
  the next (serial dependency chain) and synchronizes with ONE tiny scalar
  host fetch at the end — robust on remote/tunneled PJRT platforms where
  ``block_until_ready`` can return before execution finishes, and it
  amortizes the fetch latency over the whole chain.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from tpu_operator.workloads.topology import PEAK_BF16_TFLOPS


@dataclass
class MatmulResult:
    ok: bool
    device_kind: str
    platform: str
    n_devices: int
    size: int
    iters: int
    elapsed_s: float
    tflops: float
    peak_tflops: Optional[float]
    utilization: Optional[float]
    error: str = ""

    def to_dict(self):
        return {
            "ok": self.ok,
            "device_kind": self.device_kind,
            "platform": self.platform,
            "n_devices": self.n_devices,
            "size": self.size,
            "iters": self.iters,
            "elapsed_s": round(self.elapsed_s, 6),
            "tflops": round(self.tflops, 3),
            "peak_tflops": self.peak_tflops,
            "utilization": round(self.utilization, 4)
            if self.utilization is not None
            else None,
            "error": self.error,
        }


def device_generation(device_kind: str) -> Optional[str]:
    """Map ``jax.devices()[0].device_kind`` to a TPU generation tag."""
    kind = device_kind.lower()
    if "v6" in kind:
        return "v6e"
    if "v5p" in kind or ("v5" in kind and "lite" not in kind and "e" not in kind):
        return "v5p"
    if "v5" in kind:
        return "v5e"
    if "v4" in kind:
        return "v4"
    return None


def make_matmul_step(size: int = 4096, depth: int = 8, dtype=None):
    """Build the jitted validation step: a chain of ``depth`` matmuls with a
    cheap nonlinearity, so one dispatch amortizes launch overhead and the
    MXU stays hot. Returns ``(fn, example_args)``.
    """
    import jax
    import jax.numpy as jnp

    dtype = dtype or jnp.bfloat16

    def step(a, b):
        x = a
        for _ in range(depth):
            x = jnp.dot(x, b, preferred_element_type=jnp.float32)
            # cheap VPU op fused by XLA into the matmul epilogue; keeps
            # magnitudes bounded without extra HBM traffic
            x = (x * (1.0 / size)).astype(dtype)
        return x

    fn = jax.jit(step)
    key = jax.random.PRNGKey(0)
    k1, k2 = jax.random.split(key)
    a = jax.random.normal(k1, (size, size), dtype=dtype)
    b = jax.random.normal(k2, (size, size), dtype=dtype)
    return fn, (a, b)


def run_matmul_validation(
    size: int = 4096,
    depth: int = 8,
    iters: int = 10,
    expect_tpu: bool = False,
) -> MatmulResult:
    """Validate chip access and measure achieved TFLOPS on one device."""
    try:
        import jax
        import jax.numpy as jnp
        import numpy as np
    except Exception as e:  # pragma: no cover
        return MatmulResult(
            False, "", "", 0, size, iters, 0.0, 0.0, None, None, error=str(e)
        )

    try:
        devices = jax.devices()
        if not devices:
            raise RuntimeError("jax.devices() is empty")
        dev = devices[0]
        platform = dev.platform
        if expect_tpu and platform != "tpu":
            raise RuntimeError(f"expected TPU, found platform={platform}")

        fn, (a, b) = make_matmul_step(size=size, depth=depth)
        # correctness probe on a small slice (f32 reference)
        small = 256
        sa = a[:small, :small].astype(jnp.float32)
        sb = b[:small, :small].astype(jnp.float32)
        want = np.asarray(jnp.dot(sa, sb))
        got = np.asarray(
            jnp.dot(
                a[:small, :small], b[:small, :small],
                preferred_element_type=jnp.float32,
            )
        )
        rel = np.abs(got - want) / (np.abs(want) + 1.0)
        if float(rel.mean()) > 0.02:
            raise RuntimeError(f"matmul numerics off: mean rel err {rel.mean():.4f}")

        def force(x):
            # scalar fetch: the only reliable completion barrier on remote
            # PJRT platforms (block_until_ready can no-op over a tunnel)
            return float(jnp.sum(x.astype(jnp.float32)))

        # serial chain (each dispatch depends on the last), fixed
        # sync/fetch overhead cancelled — see workloads/timing.py
        from tpu_operator.workloads.timing import chain_per_iter_seconds

        per_iter = chain_per_iter_seconds(lambda v: fn(v, b), a, force, iters)
        elapsed = per_iter * iters

        flops = 2.0 * size * size * size * depth * iters
        tflops = flops / elapsed / 1e12
        gen = device_generation(dev.device_kind)
        peak = PEAK_BF16_TFLOPS.get(gen) if gen else None
        util = tflops / peak if peak else None
        return MatmulResult(
            ok=True,
            device_kind=dev.device_kind,
            platform=platform,
            n_devices=len(devices),
            size=size,
            iters=iters,
            elapsed_s=elapsed,
            tflops=tflops,
            peak_tflops=peak,
            utilization=util,
        )
    except Exception as e:
        return MatmulResult(
            False, "", "", 0, size, iters, 0.0, 0.0, None, None, error=str(e)
        )
