"""Expert-parallel (ep) probe: MoE token dispatch/combine via ``all_to_all``.

The last mesh axis the slice validation suite must prove: expert
parallelism, where each device hosts one expert and tokens are routed
between devices. This is the only standard parallelism whose collective is
``all_to_all`` — the burn-in (psum/all-gather), ring probes (ppermute) and
pipeline probe (chained ppermute) never exercise it, yet it is the
all-to-all ICI traffic pattern that stresses every link pair at once
rather than neighbors only.

The probe runs a top-1-gated mixture-of-experts layer: a deterministic
router picks an expert per token; tokens are packed into per-expert
capacity slots, exchanged with ``jax.lax.all_to_all``, transformed by the
resident expert MLP, exchanged back, and unpacked. Validation is exact
against the dense reference (every token pushed through its chosen expert
on one device). Routing bugs, slot-packing bugs, or a link corrupting
payloads all surface as divergence; overflowing tokens are counted and
must be zero at the probe's default drop-free capacity.

TPU-first notes: one jitted program; fixed capacity ⇒ static shapes (the
XLA-friendly MoE formulation — no dynamic token counts); dispatch/combine
are one-hot matmuls that land on the MXU; ``shard_map`` gives the
per-device view so the two ``all_to_all`` calls are explicit.

Used by ``tpu-validator --component moe`` and the multi-chip dryrun.
Reference parity: none (SURVEY.md §2.4 — fabric validation is TPU-native
surplus).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


@dataclass
class MoEResult:
    ok: bool
    n_experts: int
    tokens: int
    capacity: int
    dropped: int
    max_abs_err: float
    elapsed_s: float
    error: str = ""

    def to_dict(self):
        return {
            "ok": self.ok,
            "n_experts": self.n_experts,
            "tokens": self.tokens,
            "capacity": self.capacity,
            "dropped": self.dropped,
            "max_abs_err": round(self.max_abs_err, 8),
            "elapsed_s": round(self.elapsed_s, 6),
            "error": self.error,
        }


def _expert_mlp(x, w):
    import jax
    import jax.numpy as jnp

    # HIGHEST precision: on TPU, f32 dots otherwise run as bf16 MXU passes,
    # and probe-vs-reference rounding at different shapes would swamp the
    # tolerance — this is a correctness probe, not a throughput one
    return jax.nn.gelu(
        jnp.dot(
            x,
            w,
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
    )


def build_moe(
    n_devices: Optional[int] = None,
    tokens_per_device: int = 64,
    d_model: int = 64,
    capacity_factor: Optional[float] = None,
):
    """Returns (mesh, jitted MoE layer fn, (x, wg, we), capacity).

    ``x``: [n_tokens, d_model] tokens sharded over ``ep``.
    ``wg``: [d_model, n_experts] router weights, replicated.
    ``we``: [n_experts, d_model, d_model] expert weights sharded over ``ep``.
    fn returns (y sharded like x, keep mask, dropped-token count).

    ``capacity_factor=None`` (the default) sizes each per-(source, expert)
    slot budget at ``tokens_per_device`` — drop-free for ANY routing, since
    a source can never send more tokens than it holds. A health probe must
    not fail on healthy hardware, and mean-based budgets (factor ×
    tokens/n) deterministically overflow the binomial routing tail once
    tokens_per_device/n is small. Pass a numeric factor only to exercise
    the overflow-detection path.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    try:
        from jax import shard_map  # jax >= 0.8
    except ImportError:  # pragma: no cover
        from jax.experimental.shard_map import shard_map

    devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise RuntimeError(f"need {n_devices} devices, have {len(devices)}")
        devices = devices[:n_devices]
    n = len(devices)
    mesh = Mesh(np.asarray(devices), axis_names=("ep",))

    # per-(device, destination-expert) slot budget; ×n devices sending
    # means each expert can receive up to n*capacity tokens per step
    if capacity_factor is None:
        capacity = tokens_per_device
    else:
        capacity = max(4, int(capacity_factor * tokens_per_device / n))
    capacity = min(capacity, tokens_per_device)

    key = jax.random.PRNGKey(11)
    kx, kg, ke = jax.random.split(key, 3)
    x = jax.random.normal(kx, (n * tokens_per_device, d_model), jnp.float32)
    wg = jax.random.normal(kg, (d_model, n), jnp.float32)
    we = jax.random.normal(ke, (n, d_model, d_model), jnp.float32) * (
        1.0 / d_model**0.5
    )
    x = jax.device_put(x, NamedSharding(mesh, P("ep", None)))
    wg = jax.device_put(wg, NamedSharding(mesh, P(None, None)))
    we = jax.device_put(we, NamedSharding(mesh, P("ep", None, None)))

    def moe(xs, wgr, wes):
        # xs: [t, d] local tokens; wes: [1, d, d] resident expert weights
        t = xs.shape[0]
        logits = jnp.dot(
            xs,
            wgr,
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        choice = jnp.argmax(logits, axis=-1)  # [t] expert id per token
        # position of each token within its expert's slot budget
        onehot = jax.nn.one_hot(choice, n, dtype=jnp.int32)  # [t, e]
        # slot = how many earlier tokens (inclusive) chose the same expert,
        # minus one; zero in the non-chosen columns so the row-sum is the
        # chosen expert's slot id
        pos = (jnp.cumsum(onehot, axis=0) - 1) * onehot  # [t, e]
        slot = jnp.sum(pos, axis=-1)  # [t] slot id within chosen expert
        keep = slot < capacity
        dropped = jnp.sum(~keep)
        # dispatch tensor: [e, capacity, d] — token payloads packed into
        # (destination expert, slot); one-hot matmul keeps it MXU-shaped
        disp = jnp.zeros((n, capacity, xs.shape[1]), jnp.float32)
        e_idx = jnp.where(keep, choice, 0)
        s_idx = jnp.where(keep, slot, 0)
        payload = jnp.where(keep[:, None], xs, 0.0)
        disp = disp.at[e_idx, s_idx].add(payload)
        # exchange: after all_to_all over ep, device e holds the slots every
        # peer packed for expert e → [n_sources, capacity, d]
        recv = jax.lax.all_to_all(disp, "ep", split_axis=0, concat_axis=0, tiled=True)
        y = _expert_mlp(recv.reshape(n * capacity, -1), wes[0])
        y = y.reshape(n, capacity, -1)
        # return trip: send each source its transformed slots back
        back = jax.lax.all_to_all(y, "ep", split_axis=0, concat_axis=0, tiled=True)
        # unpack: token i reads (choice i, slot i) from its own view
        out = back[e_idx, s_idx]
        out = jnp.where(keep[:, None], out, 0.0)  # dropped tokens: zeros
        return out, keep, jax.lax.psum(dropped, "ep")

    fn = jax.jit(
        shard_map(
            moe,
            mesh=mesh,
            in_specs=(P("ep", None), P(None, None), P("ep", None, None)),
            out_specs=(P("ep", None), P("ep"), P()),
        )
    )
    return mesh, fn, (x, wg, we), capacity


def run_moe(
    n_devices: Optional[int] = None,
    tokens_per_device: int = 64,
    d_model: int = 64,
    capacity_factor: Optional[float] = None,
    tol: float = 1e-4,
) -> MoEResult:
    import time

    try:
        import jax.numpy as jnp
        import numpy as np

        mesh, fn, (x, wg, we), capacity = build_moe(
            n_devices=n_devices,
            tokens_per_device=tokens_per_device,
            d_model=d_model,
            capacity_factor=capacity_factor,
        )
        n = mesh.devices.size
        t0 = time.perf_counter()
        out, keep, dropped = fn(x, wg, we)
        out.block_until_ready()
        elapsed = time.perf_counter() - t0
        dropped = int(dropped)
        keep = np.asarray(keep)
        # dense reference: each token through its argmax expert; dropped
        # tokens (zeroed in the probe output) are excluded so the numerical
        # check stays orthogonal to the capacity check
        xn = np.asarray(x)
        choice = np.argmax(xn @ np.asarray(wg), axis=-1)
        wen = np.asarray(we)
        # grouped by expert: n batched MXU-shaped calls instead of one
        # un-jitted per-token dispatch each
        ref = np.zeros_like(xn)
        for e in range(mesh.devices.size):
            sel = choice == e
            if sel.any():
                ref[sel] = np.asarray(
                    _expert_mlp(jnp.asarray(xn[sel]), jnp.asarray(wen[e]))
                )
        diff = np.abs(np.asarray(out) - ref)[keep]
        max_err = float(np.max(diff)) if diff.size else 0.0
        errors = []
        if dropped:
            errors.append(f"{dropped} tokens dropped (capacity too low)")
        if max_err > tol:
            errors.append(f"divergence {max_err:.6f} > {tol}")
        return MoEResult(
            ok=not errors,
            n_experts=n,
            tokens=xn.shape[0],
            capacity=capacity,
            dropped=dropped,
            max_abs_err=max_err,
            elapsed_s=elapsed,
            error="; ".join(errors),
        )
    except Exception as e:
        return MoEResult(
            ok=False,
            n_experts=0,
            tokens=0,
            capacity=0,
            dropped=0,
            max_abs_err=float("nan"),
            elapsed_s=0.0,
            error=str(e),
        )
