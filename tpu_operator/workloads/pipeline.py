"""Pipeline-parallel (pp) probe: GPipe-style microbatch pipeline over ICI.

Completes the mesh-axis coverage of the slice validation workloads: the
burn-in proves dp/tp, the ring/ringattn probes prove the sp ring — this
probe proves the *pipeline* pattern, where the model's layers are sharded
across devices and activations stream stage-to-stage over ICI. Each device
holds one MLP block ("stage"); microbatches enter at stage 0, and every
tick each stage processes its resident microbatch and hands the activation
to its successor with ``jax.lax.ppermute``. After ``n_micro + n_stages - 1``
ticks every microbatch has traversed every stage — the classic GPipe
schedule with bubbles at head and tail.

Validation is exact: the pipelined output must match applying all stages
sequentially on one device (f32, tight tolerance).

TPU-first notes: the whole schedule is ONE jitted program — the tick loop
is a device-side ``lax.scan``; stage weights live sharded over the ``pp``
axis (each device's shard_map block sees only its own stage's weights);
activations are static-shaped so each ``ppermute`` lowers onto a physical
ICI hop; outputs are collected with a stage-masked ``psum`` rather than a
gather, keeping the program collective-only.

Used by ``tpu-validator --component pipeline`` and the multi-chip dryrun.
Reference parity: none (the NVIDIA operator validates with vectorAdd
only); mandated by the slice/topology story (SURVEY.md §2.4).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


@dataclass
class PipelineResult:
    ok: bool
    n_stages: int
    n_micro: int
    ticks: int
    max_abs_err: float
    elapsed_s: float
    error: str = ""

    def to_dict(self):
        return {
            "ok": self.ok,
            "n_stages": self.n_stages,
            "n_micro": self.n_micro,
            "ticks": self.ticks,
            "max_abs_err": round(self.max_abs_err, 8),
            "elapsed_s": round(self.elapsed_s, 6),
            "error": self.error,
        }


def _stage_block(x, w):
    """One pipeline stage: gelu MLP block (matmul → MXU). HIGHEST precision
    so the probe-vs-sequential-reference comparison is not dominated by the
    TPU's default bf16 f32-matmul passes."""
    import jax
    import jax.numpy as jnp

    return jax.nn.gelu(
        jnp.dot(
            x,
            w,
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
    )


def build_pipeline(
    n_devices: Optional[int] = None,
    n_micro: int = 8,
    micro_batch: int = 4,
    d_model: int = 128,
):
    """Returns (mesh, jitted pipeline fn, (x, w)).

    ``x``: [n_micro, micro_batch, d_model] replicated inputs.
    ``w``: [n_stages, d_model, d_model] stage weights sharded over ``pp``.
    The fn returns [n_micro, micro_batch, d_model] outputs (replicated).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    try:
        from jax import shard_map  # jax >= 0.8
    except ImportError:  # pragma: no cover
        from jax.experimental.shard_map import shard_map

    devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise RuntimeError(f"need {n_devices} devices, have {len(devices)}")
        devices = devices[:n_devices]
    n = len(devices)
    mesh = Mesh(np.asarray(devices), axis_names=("pp",))

    key = jax.random.PRNGKey(3)
    kx, kw = jax.random.split(key)
    x = jax.random.normal(kx, (n_micro, micro_batch, d_model), jnp.float32)
    # orthogonal-ish small weights keep activations O(1) through n stages
    w = jax.random.normal(kw, (n, d_model, d_model), jnp.float32) * (
        1.0 / d_model**0.5
    )
    x = jax.device_put(x, NamedSharding(mesh, P(None, None, None)))
    w = jax.device_put(w, NamedSharding(mesh, P("pp", None, None)))

    ticks = n_micro + n - 1
    fwd_perm = [(i, i + 1) for i in range(n - 1)]  # no wraparound: a chain

    def pipe(xs, ws):
        # xs: [n_micro, mb, d] (replicated into each shard);
        # ws: [1, d, d] — this device's stage weights
        stage = jax.lax.axis_index("pp")
        w_mine = ws[0]

        def vary(v):
            try:
                return jax.lax.pcast(v, ("pp",), to="varying")
            except (AttributeError, TypeError):  # pragma: no cover
                return jax.lax.pvary(v, ("pp",))

        out0 = vary(jnp.zeros_like(xs))
        recv0 = vary(jnp.zeros(xs.shape[1:], xs.dtype))

        def tick(carry, t):
            recv, outs = carry
            # stage 0 injects microbatch t (clamped; bubble ticks masked out
            # downstream by the write-index guard)
            inj = jax.lax.dynamic_index_in_dim(
                xs, jnp.clip(t, 0, xs.shape[0] - 1), keepdims=False
            )
            inp = jnp.where(stage == 0, inj, recv)
            act = _stage_block(inp, w_mine)
            # microbatch id resident at this stage this tick; valid only in
            # the diagonal window of the schedule
            mb_id = t - stage
            is_last = stage == n - 1
            valid = is_last & (mb_id >= 0) & (mb_id < xs.shape[0])
            outs = jax.lax.dynamic_update_index_in_dim(
                outs,
                jnp.where(valid, act, jax.lax.dynamic_index_in_dim(
                    outs, jnp.clip(mb_id, 0, xs.shape[0] - 1), keepdims=False
                )),
                jnp.clip(mb_id, 0, xs.shape[0] - 1),
                axis=0,
            )
            nxt = jax.lax.ppermute(act, axis_name="pp", perm=fwd_perm)
            return (nxt, outs), None

        (_, outs), _ = jax.lax.scan(
            tick, (recv0, out0), jnp.arange(ticks)
        )
        # only the last stage holds real outputs; psum over the chain
        # replicates them (all other stages contribute zeros)
        outs = jnp.where(stage == n - 1, outs, jnp.zeros_like(outs))
        return jax.lax.psum(outs, "pp")

    fn = jax.jit(
        shard_map(
            pipe,
            mesh=mesh,
            in_specs=(P(None, None, None), P("pp", None, None)),
            out_specs=P(None, None, None),
        )
    )
    return mesh, fn, (x, w)


def run_pipeline(
    n_devices: Optional[int] = None,
    n_micro: int = 8,
    micro_batch: int = 4,
    d_model: int = 128,
    tol: float = 1e-4,
) -> PipelineResult:
    import time

    try:
        import jax.numpy as jnp
        import numpy as np

        mesh, fn, (x, w) = build_pipeline(
            n_devices=n_devices,
            n_micro=n_micro,
            micro_batch=micro_batch,
            d_model=d_model,
        )
        n = mesh.devices.size
        t0 = time.perf_counter()
        out = fn(x, w)
        out.block_until_ready()
        elapsed = time.perf_counter() - t0
        # sequential reference: all stages applied in order on one device
        ref = np.asarray(x)
        wn = np.asarray(w)
        for s in range(n):
            ref = np.asarray(_stage_block(jnp.asarray(ref), jnp.asarray(wn[s])))
        max_err = float(np.max(np.abs(np.asarray(out) - ref)))
        return PipelineResult(
            ok=max_err <= tol,
            n_stages=n,
            n_micro=n_micro,
            ticks=n_micro + n - 1,
            max_abs_err=max_err,
            elapsed_s=elapsed,
            error="" if max_err <= tol else f"divergence {max_err:.6f} > {tol}",
        )
    except Exception as e:
        return PipelineResult(
            ok=False,
            n_stages=0,
            n_micro=n_micro,
            ticks=0,
            max_abs_err=float("nan"),
            elapsed_s=0.0,
            error=str(e),
        )
