"""Project-native concurrency & contract analyzer.

The operator is a deeply concurrent system (~35 locks across the write
pipeline, batch lanes, gang coordinator, breaker, informer caches) whose
structural contracts — layering ("obs/ imports nothing", "kube/ never
imports upward"), the frozen-view read discipline, guarded-by locking,
lock ordering — were previously enforced only by docs and hammer tests.
This package is the machine check, in two halves:

* **static** (``python -m tpu_operator.analysis`` / ``make lint``): a
  dependency-free AST rule engine (``engine.py``) running the rule
  catalog under ``rules/`` over ``tpu_operator/`` + ``tests/scripts/``,
  with deterministic ``path:line: [rule] message`` findings, per-line
  suppression comments (``# lint: ignore[rule-id]``), and a committed
  baseline (``analysis-baseline.json``) so the gate bites only on NEW
  findings;
* **dynamic** (``lockwatch.py``): an opt-in runtime watchdog that wraps
  ``threading.Lock``/``RLock`` creation, records the per-thread lock
  acquisition-order graph plus held-across-blocking events, detects
  order cycles that static nesting cannot see (acquisitions that nest
  across call boundaries and threads), and flight-records violations
  through ``obs/flight.py``. The chaos suites run under it
  (``TPU_LOCKWATCH=1``) and fail on any cycle.

Rule catalog, suppression/baseline syntax and the contract each rule
encodes: ``docs/analysis.md``. Configuration: ``[tool.tpu_analysis]``
in ``pyproject.toml``.

Layering note: this package sits OUTSIDE the runtime stack — nothing in
``tpu_operator`` imports it; the static half imports only the stdlib,
and ``lockwatch`` additionally uses ``obs/`` (which imports nothing).
"""

from tpu_operator.analysis.engine import (  # noqa: F401
    Finding,
    load_baseline,
    run_analysis,
    write_baseline,
)
