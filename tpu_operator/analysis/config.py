"""Analyzer configuration: the ``[tool.tpu_analysis]`` pyproject block.

Python 3.10 has no ``tomllib`` and the analyzer must stay
dependency-free, so this is a deliberately tiny TOML-subset reader:
one section, ``key = value`` pairs where value is a string, bool, int,
or a (possibly multiline) array of strings. That covers every knob the
analyzer has; anything fancier in the block is a configuration error
worth failing loudly on.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field
from typing import Dict, List

SECTION = "tool.tpu_analysis"


@dataclass
class AnalysisConfig:
    # roots the default invocation scans (repo-relative)
    paths: List[str] = field(default_factory=lambda: ["tpu_operator", "tests/scripts"])
    baseline: str = "analysis-baseline.json"
    # rule ids disabled outright
    disable: List[str] = field(default_factory=list)
    # guarded-by: also flag UNLOCKED READS of guarded attributes (off by
    # default: GIL-atomic scalar reads of counters/flags are idiomatic
    # here — docs/analysis.md#guarded-by)
    guarded_by_strict_reads: bool = False
    # methods with this suffix follow the repo's caller-holds-lock
    # convention (``_begin_pass_locked``, ``_commit_main_locked``):
    # guarded-by treats their bodies as lock-held, and lock-blocking
    # still flags blocking calls inside them
    locked_method_suffix: str = "_locked"
    # lock-blocking: method names that block the calling thread
    blocking_methods: List[str] = field(
        default_factory=lambda: ["result", "drain", "join_all", "urlopen", "getresponse"]
    )
    # lock-blocking: dotted call paths that block
    blocking_functions: List[str] = field(
        default_factory=lambda: ["time.sleep"]
    )
    # frozen-view: regex a receiver name must match to count as an
    # informer-backed read surface
    frozen_receivers: str = r"(client|cache|informer|store)"
    # metrics-fed: attribute assignments in this module register metrics
    metrics_module: str = "tpu_operator/controllers/operator_metrics.py"
    repo_root: str = "."

    def is_enabled(self, rule_id: str) -> bool:
        return rule_id not in self.disable


_STR = re.compile(r'^"((?:[^"\\]|\\.)*)"$')


def _parse_scalar(text: str):
    text = text.strip()
    m = _STR.match(text)
    if m:
        return m.group(1).replace('\\"', '"').replace("\\\\", "\\")
    if text in ("true", "false"):
        return text == "true"
    try:
        return int(text)
    except ValueError:
        raise ValueError(f"unsupported TOML value: {text!r}")


def _strip_comment(line: str) -> str:
    """Drop a trailing comment, respecting double-quoted strings."""
    out, in_str = [], False
    i = 0
    while i < len(line):
        ch = line[i]
        if ch == '"' and (i == 0 or line[i - 1] != "\\"):
            in_str = not in_str
        elif ch == "#" and not in_str:
            break
        out.append(ch)
        i += 1
    return "".join(out)


def parse_tool_section(text: str, section: str = SECTION) -> Dict[str, object]:
    """Extract ``[section]`` key/values from pyproject-style TOML text."""
    values: Dict[str, object] = {}
    in_section = False
    pending_key = None
    pending_items: List[str] = []
    for raw in text.splitlines():
        line = _strip_comment(raw).strip()
        if not line:
            continue
        if line.startswith("["):
            in_section = line == f"[{section}]"
            continue
        if not in_section:
            continue
        if pending_key is not None:
            # accumulating a multiline array
            closed = line.endswith("]")
            body = line[:-1] if closed else line
            pending_items.extend(
                p.strip() for p in body.split(",") if p.strip()
            )
            if closed:
                values[pending_key] = [_parse_scalar(p) for p in pending_items]
                pending_key, pending_items = None, []
            continue
        if "=" not in line:
            raise ValueError(f"unparseable [{section}] line: {raw!r}")
        key, _, val = line.partition("=")
        key, val = key.strip(), val.strip()
        if val.startswith("["):
            if val.endswith("]"):
                body = val[1:-1]
                items = [p.strip() for p in body.split(",") if p.strip()]
                values[key] = [_parse_scalar(p) for p in items]
            else:
                pending_key = key
                pending_items = [
                    p.strip() for p in val[1:].split(",") if p.strip()
                ]
        else:
            values[key] = _parse_scalar(val)
    return values


def load_config(repo_root: str = ".") -> AnalysisConfig:
    cfg = AnalysisConfig(repo_root=repo_root)
    pyproject = os.path.join(repo_root, "pyproject.toml")
    if not os.path.exists(pyproject):
        return cfg
    with open(pyproject, encoding="utf-8") as f:
        values = parse_tool_section(f.read())
    for key, val in values.items():
        if not hasattr(cfg, key):
            raise ValueError(f"unknown [{SECTION}] key: {key}")
        setattr(cfg, key, val)
    return cfg
