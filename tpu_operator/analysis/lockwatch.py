"""Runtime lock-order watchdog — the dynamic half of the analyzer.

The static ``lock-order`` rule only sees acquisitions that nest
TEXTUALLY; the real system nests across call boundaries (a controller
method holding its own lock calls into the write pipeline, which takes
its lock, which calls a batch lane's flush...). This module watches the
real thing: while enabled, ``threading.Lock``/``RLock`` construction is
wrapped so every acquisition records into a per-thread held set, every
"acquire B while holding A" adds an ``A → B`` edge to a process-wide
acquisition-order graph keyed by lock CREATION SITE, and a cycle in
that graph — two threads that ever acquired the same pair of lock
sites in opposite orders — is a potential deadlock even if this run
never interleaved into one.

Also recorded: **held-across-blocking** events — ``time.sleep``,
``WriteFuture.result()`` and ``WritePipeline.drain()`` entered while
any watched lock is held (the runtime twin of the static
``lock-blocking`` rule).

Violations flight-record through ``obs/flight.py`` (``lockwatch.cycle``
/ ``lockwatch.blocking`` events; a cycle also triggers a post-mortem
dump), so a chaos soak that trips the watchdog leaves a causal
timeline next to the invariant dumps.

Usage (the chaos suites run this via the ``TPU_LOCKWATCH=1`` session
fixture in ``tests/conftest.py``; ``make chaos-fast`` /
``chaos-soak-fast`` set it)::

    from tpu_operator.analysis import lockwatch
    lockwatch.enable()
    ...  # run the system under load
    assert lockwatch.cycles() == []
    lockwatch.disable()

Only locks CREATED while enabled are watched — enable before building
the controllers under test. Edges between two instances of the same
creation site are ignored (a site cannot order against itself without
instance identity, and Python would already deadlock on a true
re-acquire). Overhead is one dict touch per acquire; fine for tests,
not meant for production.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from tpu_operator.obs import flight

# real factories captured at import, BEFORE any patching: the watch's
# own bookkeeping lock must never be a watched lock
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock

_MAX_VIOLATIONS = 256


_SKIP_BASENAMES = ("lockwatch.py", "threading.py")


def _caller_site() -> str:
    """file:line of the nearest frame outside this module and
    threading.py, shortened to the last two path components. Exact
    basename matching: a file merely NAMED like us (test_lockwatch.py)
    must still resolve to its own sites."""
    import sys

    frame = sys._getframe(1)
    while frame is not None:
        fname = frame.f_code.co_filename
        if os.path.basename(fname) not in _SKIP_BASENAMES:
            parts = fname.replace(os.sep, "/").split("/")
            return f"{'/'.join(parts[-2:])}:{frame.f_lineno}"
        frame = frame.f_back
    return "?"


class _WatchedLock:
    """Delegating wrapper around a real lock. Supports ``with``,
    explicit acquire/release, ``threading.Condition`` construction, and
    anything else via ``__getattr__`` delegation."""

    __slots__ = ("_real", "site", "_watch")

    def __init__(self, real: Any, site: str, watch: "LockWatch"):
        self._real = real
        self.site = site
        self._watch = watch

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._real.acquire(blocking, timeout)
        if ok:
            self._watch._on_acquire(self)
        return ok

    def release(self) -> None:
        self._watch._on_release(self)
        self._real.release()

    def locked(self) -> bool:
        return self._real.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def _at_fork_reinit(self):  # threading internals call this on fork
        return self._real._at_fork_reinit()

    def __getattr__(self, name: str):
        return getattr(self._real, name)

    def __repr__(self):
        return f"<watched {self._real!r} from {self.site}>"


class _WatchedRLock(_WatchedLock):
    """RLock wrapper. ``threading.Condition`` probes ``_release_save``/
    ``_acquire_restore``/``_is_owned`` — defining them here (with
    bookkeeping) keeps the held-set consistent across ``cond.wait()``
    on an RLock-backed condition; the plain-Lock wrapper deliberately
    does NOT define them so Condition falls back to acquire/release,
    which are instrumented anyway."""

    __slots__ = ()

    def _release_save(self):
        self._watch._on_release_all(self)
        return self._real._release_save()

    def _acquire_restore(self, state):
        self._real._acquire_restore(state)
        # state is (count, owner); restore the recursion count
        count = state[0] if isinstance(state, tuple) else 1
        self._watch._on_acquire(self, count=count)

    def _is_owned(self):
        return self._real._is_owned()


class LockWatch:
    def __init__(self) -> None:
        self._mu = _REAL_LOCK()
        self._tls = threading.local()
        self._enabled = False
        # (site_a, site_b) -> witness dict (first observation wins)
        self._edges: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self._violations: List[Dict[str, Any]] = []
        self._cycles_seen: set = set()
        self.locks_created = 0
        self.acquires = 0
        self.blocking_events = 0
        self._saved: Dict[str, Any] = {}

    # -- held-set bookkeeping (per thread) ------------------------------
    def _held(self) -> List[List[Any]]:
        """This thread's held list: [[lock, count], ...] in acquisition
        order."""
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def _on_acquire(self, lock: _WatchedLock, count: int = 1) -> None:
        if not self._enabled:
            return
        self.acquires += 1
        held = self._held()
        for entry in held:
            if entry[0] is lock:
                entry[1] += count  # reentrant (RLock)
                return
        new_edges = []
        for entry in held:
            a = entry[0].site
            if a != lock.site and (a, lock.site) not in self._edges:
                new_edges.append((a, lock.site))
        held.append([lock, count])
        if new_edges:
            self._add_edges(new_edges)

    def _on_release(self, lock: _WatchedLock) -> None:
        held = getattr(self._tls, "held", None)
        if not held:
            return
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is lock:
                held[i][1] -= 1
                if held[i][1] <= 0:
                    del held[i]
                return
        # released by a thread that never acquired it (legal for plain
        # locks used as signals); nothing to unwind

    def _on_release_all(self, lock: _WatchedLock) -> None:
        """Full release regardless of recursion count (Condition.wait
        on an RLock)."""
        held = getattr(self._tls, "held", None)
        if not held:
            return
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is lock:
                del held[i]
                return

    # -- graph ----------------------------------------------------------
    def _add_edges(self, new_edges: List[Tuple[str, str]]) -> None:
        caller = _caller_site()
        thread = threading.current_thread().name
        found_cycles = []
        with self._mu:
            for a, b in new_edges:
                if (a, b) in self._edges:
                    continue
                self._edges[(a, b)] = {"thread": thread, "at": caller}
                cycle = self._path_locked(b, a)
                if cycle is not None:
                    found_cycles.append([a] + cycle)
        for cyc in found_cycles:
            self._record_cycle(cyc)

    def _path_locked(self, src: str, dst: str) -> Optional[List[str]]:
        """DFS path src → dst over current edges (caller holds _mu)."""
        adj: Dict[str, List[str]] = {}
        for (a, b) in self._edges:
            adj.setdefault(a, []).append(b)
        stack = [(src, [src])]
        seen = set()
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            if node in seen:
                continue
            seen.add(node)
            for nxt in sorted(adj.get(node, ())):
                stack.append((nxt, path + [nxt]))
        return None

    def _record_cycle(self, cycle: List[str]) -> None:
        key = tuple(sorted(set(cycle)))
        with self._mu:
            if key in self._cycles_seen:
                return
            self._cycles_seen.add(key)
            edges = {
                f"{a}->{b}": w
                for (a, b), w in self._edges.items()
                if a in key and b in key
            }
            violation = {
                "type": "lock-order-cycle",
                "cycle": cycle,
                "edges": edges,
                "thread": threading.current_thread().name,
            }
            if len(self._violations) < _MAX_VIOLATIONS:
                self._violations.append(violation)
        flight.record(
            "lockwatch.cycle",
            cycle=" -> ".join(cycle),
            thread=violation["thread"],
        )
        flight.dump(
            "lockwatch-cycle",
            detail=" -> ".join(cycle),
            extra={"edges": edges},
        )

    # -- blocking -------------------------------------------------------
    def _note_blocking(self, what: str) -> None:
        held = getattr(self._tls, "held", None)
        if not held:
            return
        self.blocking_events += 1
        sites = [entry[0].site for entry in held]
        caller = _caller_site()
        violation = {
            "type": "held-across-blocking",
            "call": what,
            "locks": sites,
            "at": caller,
            "thread": threading.current_thread().name,
        }
        with self._mu:
            if len(self._violations) < _MAX_VIOLATIONS:
                self._violations.append(violation)
        flight.record(
            "lockwatch.blocking", call=what, locks=sites, at=caller
        )

    # -- enable/disable -------------------------------------------------
    def enable(self) -> None:
        with self._mu:
            if self._enabled:
                return
            self._enabled = True

        watch = self

        def make_lock():
            watch.locks_created += 1
            return _WatchedLock(_REAL_LOCK(), _caller_site(), watch)

        def make_rlock():
            watch.locks_created += 1
            return _WatchedRLock(_REAL_RLOCK(), _caller_site(), watch)

        self._saved = {"Lock": threading.Lock, "RLock": threading.RLock}
        threading.Lock = make_lock
        threading.RLock = make_rlock

        real_sleep = time.sleep
        self._saved["sleep"] = real_sleep

        def watched_sleep(seconds):
            watch._note_blocking(f"time.sleep({seconds})")
            return real_sleep(seconds)

        time.sleep = watched_sleep

        # the write pipeline's two blocking surfaces (best-effort: the
        # module is part of this repo, but keep enable() usable even if
        # an embedder runs without it)
        try:
            from tpu_operator.kube import write_pipeline as wp

            real_result = wp.WriteFuture.result
            real_drain = wp.WritePipeline.drain
            self._saved["result"] = real_result
            self._saved["drain"] = real_drain

            def watched_result(fut, timeout=None):
                watch._note_blocking("WriteFuture.result()")
                return real_result(fut, timeout)

            def watched_drain(pipe, timeout=None, raise_errors=False):
                watch._note_blocking("WritePipeline.drain()")
                return real_drain(pipe, timeout, raise_errors)

            wp.WriteFuture.result = watched_result
            wp.WritePipeline.drain = watched_drain
        except Exception:  # pragma: no cover - import-environment dependent
            pass

    def disable(self) -> None:
        with self._mu:
            if not self._enabled:
                return
            self._enabled = False
        threading.Lock = self._saved.pop("Lock", _REAL_LOCK)
        threading.RLock = self._saved.pop("RLock", _REAL_RLOCK)
        if "sleep" in self._saved:
            time.sleep = self._saved.pop("sleep")
        if "result" in self._saved or "drain" in self._saved:
            try:
                from tpu_operator.kube import write_pipeline as wp

                if "result" in self._saved:
                    wp.WriteFuture.result = self._saved.pop("result")
                if "drain" in self._saved:
                    wp.WritePipeline.drain = self._saved.pop("drain")
            except Exception:  # pragma: no cover
                pass

    @property
    def enabled(self) -> bool:
        return self._enabled

    # -- reporting ------------------------------------------------------
    def cycles(self) -> List[Dict[str, Any]]:
        with self._mu:
            return [
                v for v in self._violations if v["type"] == "lock-order-cycle"
            ]

    def violations(self) -> List[Dict[str, Any]]:
        with self._mu:
            return list(self._violations)

    def edges(self) -> Dict[str, Dict[str, Any]]:
        with self._mu:
            return {f"{a}->{b}": dict(w) for (a, b), w in self._edges.items()}

    def reset(self) -> None:
        """Clear the graph + violations (keep patching state)."""
        with self._mu:
            self._edges.clear()
            self._violations.clear()
            self._cycles_seen.clear()
            self.acquires = 0
            self.blocking_events = 0

    def stats(self) -> Dict[str, Any]:
        with self._mu:
            return {
                "enabled": self._enabled,
                "locks_created": self.locks_created,
                "acquires": self.acquires,
                "edges": len(self._edges),
                "cycles": sum(
                    1
                    for v in self._violations
                    if v["type"] == "lock-order-cycle"
                ),
                "blocking_events": self.blocking_events,
            }


WATCH = LockWatch()


def enable() -> None:
    WATCH.enable()


def disable() -> None:
    WATCH.disable()


def enabled() -> bool:
    return WATCH.enabled


def cycles() -> List[Dict[str, Any]]:
    return WATCH.cycles()


def violations() -> List[Dict[str, Any]]:
    return WATCH.violations()


def reset() -> None:
    WATCH.reset()


def stats() -> Dict[str, Any]:
    return WATCH.stats()
