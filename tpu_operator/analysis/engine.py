"""AST rule engine: file discovery, parsing, suppression, baseline,
deterministic reporting.

Determinism is a hard contract (tests byte-compare two runs): files are
walked sorted, findings are sorted by (path, line, rule, message), and
nothing in the report carries a timestamp, pid, or absolute path.

Suppression syntax (same line as the finding)::

    self._executor = make()  # lint: ignore[guarded-by] caller holds _lock

``# lint: ignore`` without a bracket suppresses every rule on the line;
``# lint: ignore-file[rule-id]`` anywhere in a file's first 20 lines
suppresses that rule for the whole file (the sim/test scaffolding
escape: kube/testing.py is ALLOWED to import upward, and says so at the
top where a reviewer sees it).

The baseline (``analysis-baseline.json``) maps finding fingerprints —
``sha1(rule|path|scope|message)``, line-number-free so unrelated edits
do not churn it — to accepted counts. ``make lint`` fails only on
findings beyond the baselined count; an empty baseline means the gate
bites on everything.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from tpu_operator.analysis.config import AnalysisConfig

SUPPRESS_RE = re.compile(r"#\s*lint:\s*ignore(?:\[([a-zA-Z0-9_,\- ]+)\])?")
SUPPRESS_FILE_RE = re.compile(r"#\s*lint:\s*ignore-file\[([a-zA-Z0-9_,\- ]+)\]")
FILE_SUPPRESS_SCAN_LINES = 20


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative, posix separators
    line: int
    message: str
    # enclosing class/function, part of the fingerprint so baselines
    # survive line drift without colliding across scopes
    scope: str = ""

    def fingerprint(self) -> str:
        key = f"{self.rule}|{self.path}|{self.scope}|{self.message}"
        return hashlib.sha1(key.encode()).hexdigest()[:16]

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class ParsedModule:
    path: str  # absolute
    relpath: str  # repo-relative posix
    source: str
    lines: List[str]
    tree: ast.Module
    # dotted module name when under the package root, else ""
    modname: str


@dataclass
class Report:
    findings: List[Finding] = field(default_factory=list)  # post-suppression
    new: List[Finding] = field(default_factory=list)  # beyond baseline
    suppressed: int = 0
    baselined: int = 0
    files_scanned: int = 0
    parse_errors: List[Finding] = field(default_factory=list)

    def render_text(self) -> str:
        out = []
        for f in self.new:
            out.append(f.render())
        out.append(
            f"{len(self.new)} finding(s) "
            f"({len(self.findings)} total, {self.baselined} baselined, "
            f"{self.suppressed} suppressed) in {self.files_scanned} file(s)"
        )
        return "\n".join(out)

    def render_json(self) -> str:
        return json.dumps(
            {
                "new": [f.__dict__ for f in self.new],
                "total": len(self.findings),
                "baselined": self.baselined,
                "suppressed": self.suppressed,
                "files_scanned": self.files_scanned,
            },
            indent=2,
            sort_keys=True,
        )


def _modname_for(relpath: str) -> str:
    if not relpath.endswith(".py"):
        return ""
    parts = relpath[: -len(".py")].split("/")
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def collect_files(repo_root: str, paths: List[str]) -> List[str]:
    """Sorted absolute paths of every .py under the given roots (a root
    may itself be a file)."""
    found = []
    for root in paths:
        abs_root = os.path.join(repo_root, root)
        if os.path.isfile(abs_root):
            found.append(abs_root)
            continue
        for dirpath, dirnames, filenames in os.walk(abs_root):
            dirnames[:] = sorted(
                d for d in dirnames if d != "__pycache__" and not d.startswith(".")
            )
            for name in sorted(filenames):
                if name.endswith(".py"):
                    found.append(os.path.join(dirpath, name))
    return sorted(set(found))


def parse_modules(
    repo_root: str, files: List[str]
) -> Tuple[List[ParsedModule], List[Finding]]:
    modules, errors = [], []
    for path in files:
        relpath = os.path.relpath(path, repo_root).replace(os.sep, "/")
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source, filename=relpath)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            line = getattr(e, "lineno", 1) or 1
            errors.append(
                Finding("parse", relpath, line, f"cannot parse: {e.__class__.__name__}")
            )
            continue
        modules.append(
            ParsedModule(
                path=path,
                relpath=relpath,
                source=source,
                lines=source.splitlines(),
                tree=tree,
                modname=_modname_for(relpath),
            )
        )
    return modules, errors


# ---------------------------------------------------------------------------
# suppression
# ---------------------------------------------------------------------------


def _line_suppressions(lines: List[str]) -> Dict[int, Optional[set]]:
    """line number -> set of suppressed rule ids (None = all rules)."""
    out: Dict[int, Optional[set]] = {}
    for i, line in enumerate(lines, start=1):
        m = SUPPRESS_RE.search(line)
        if not m:
            continue
        if m.group(1) is None:
            out[i] = None
        else:
            out[i] = {r.strip() for r in m.group(1).split(",") if r.strip()}
    return out


def _file_suppressions(lines: List[str]) -> set:
    out = set()
    for line in lines[:FILE_SUPPRESS_SCAN_LINES]:
        m = SUPPRESS_FILE_RE.search(line)
        if m:
            out.update(r.strip() for r in m.group(1).split(",") if r.strip())
    return out


def apply_suppressions(
    findings: List[Finding], modules: List[ParsedModule]
) -> Tuple[List[Finding], int]:
    # precompute per module: rescanning every line per FINDING would be
    # O(findings × file lines) on a regression-heavy run
    by_path = {
        m.relpath: (_file_suppressions(m.lines), _line_suppressions(m.lines))
        for m in modules
    }
    kept, dropped = [], 0
    for f in findings:
        entry = by_path.get(f.path)
        if entry is None:
            kept.append(f)
            continue
        file_rules, line_rules = entry
        if f.rule in file_rules:
            dropped += 1
            continue
        rules = line_rules.get(f.line, ())
        if rules is None or f.rule in rules:
            dropped += 1
            continue
        kept.append(f)
    return kept, dropped


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------


def load_baseline(path: str) -> Dict[str, int]:
    if not path or not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if data.get("version") != 1:
        raise ValueError(f"unsupported baseline version in {path}")
    return {str(k): int(v) for k, v in data.get("fingerprints", {}).items()}


def write_baseline(path: str, findings: List[Finding]) -> None:
    counts: Dict[str, int] = {}
    notes: Dict[str, str] = {}
    for f in findings:
        fp = f.fingerprint()
        counts[fp] = counts.get(fp, 0) + 1
        notes.setdefault(fp, f.render())
    payload = {
        "version": 1,
        # human-readable context only; the gate reads fingerprints
        "notes": {k: notes[k] for k in sorted(notes)},
        "fingerprints": {k: counts[k] for k in sorted(counts)},
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")


def split_baselined(
    findings: List[Finding], baseline: Dict[str, int]
) -> Tuple[List[Finding], int]:
    budget = dict(baseline)
    new = []
    baselined = 0
    for f in findings:
        fp = f.fingerprint()
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
            baselined += 1
        else:
            new.append(f)
    return new, baselined


# ---------------------------------------------------------------------------
# run
# ---------------------------------------------------------------------------


def run_analysis(
    config: AnalysisConfig,
    paths: Optional[List[str]] = None,
    baseline_path: Optional[str] = None,
    use_baseline: bool = True,
) -> Report:
    from tpu_operator.analysis.rules import build_rules

    files = collect_files(config.repo_root, paths or config.paths)
    modules, parse_errors = parse_modules(config.repo_root, files)
    rules = [r for r in build_rules(config) if config.is_enabled(r.id)]

    findings: List[Finding] = list(parse_errors)
    for rule in rules:
        for mod in modules:
            findings.extend(rule.visit_module(mod, config))
        findings.extend(rule.finalize(config))

    findings, suppressed = apply_suppressions(findings, modules)
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))

    if use_baseline:
        bl_path = baseline_path or os.path.join(config.repo_root, config.baseline)
        baseline = load_baseline(bl_path)
    else:
        baseline = {}
    new, baselined = split_baselined(findings, baseline)

    return Report(
        findings=findings,
        new=new,
        suppressed=suppressed,
        baselined=baselined,
        files_scanned=len(modules),
        parse_errors=parse_errors,
    )
