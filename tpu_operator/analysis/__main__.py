"""CLI: ``python -m tpu_operator.analysis`` — the ``make lint`` engine.

Exit status: 0 when every finding is baselined or suppressed, 1 when
any NEW finding exists (the gate bites), 2 on usage/config errors.
Output is deterministic (two runs on the same tree are byte-identical).
"""

from __future__ import annotations

import argparse
import os
import sys

from tpu_operator.analysis.config import load_config
from tpu_operator.analysis.engine import run_analysis, write_baseline


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tpu_operator.analysis",
        description="Project-native concurrency & contract analyzer "
        "(rule catalog: docs/analysis.md)",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        help="files/dirs to scan (default: [tool.tpu_analysis] paths)",
    )
    parser.add_argument(
        "--repo-root", default=".", help="repository root (pyproject.toml location)"
    )
    parser.add_argument("--baseline", help="baseline file (default from config)")
    parser.add_argument(
        "--no-baseline",
        action="store_true",
        help="report every finding, ignoring the baseline",
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help="accept the current findings as the new baseline",
    )
    parser.add_argument(
        "--disable", action="append", default=[], metavar="RULE",
        help="disable a rule id (repeatable)",
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text"
    )
    args = parser.parse_args(argv)

    try:
        config = load_config(args.repo_root)
    except ValueError as e:
        print(f"config error: {e}", file=sys.stderr)
        return 2
    config.disable = sorted(set(config.disable) | set(args.disable))

    try:
        report = run_analysis(
            config,
            paths=args.paths or None,
            baseline_path=args.baseline,
            use_baseline=not (args.no_baseline or args.write_baseline),
        )
    except ValueError as e:
        print(f"analysis error: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        path = args.baseline or os.path.join(config.repo_root, config.baseline)
        write_baseline(path, report.findings)
        print(f"baseline written: {path} ({len(report.findings)} finding(s))")
        return 0

    print(
        report.render_text() if args.format == "text" else report.render_json()
    )
    return 1 if report.new else 0


if __name__ == "__main__":
    sys.exit(main())
