"""frozen-view — mutation of zero-copy informer read results.

Contract encoded: PR 1's read discipline (docs/cache.md) — informer
``get``/``list`` return SHARED frozen views (``kube/frozen.py``);
writers opt in explicitly via ``copy=True`` or ``thaw()``. Mutating a
view raises ``FrozenObjectError`` at runtime *if* the code path runs
against the cached client — but paths exercised only against FakeClient
or live reads hide the bug until production. This rule finds the shape
statically.

Per-function taint tracking, deliberately simple and in-order:

* ``x = <recv>.get/list/list_scoped/get_or_none(...)`` taints ``x``
  when the receiver looks informer-backed (``frozen_receivers`` regex,
  default ``client|cache|informer|store``) and the call does not pass
  ``copy=True``;
* taint propagates through subscripts/attributes of tainted names,
  ``.get/.items/.values/.keys`` calls on them, and ``for`` loop
  variables iterating a tainted expression (elements of a frozen list
  are frozen);
* ``thaw(x)``, ``deepcopy(x)``, ``dict(x)``, ``list(x)`` launder the
  taint; any other reassignment clears it;
* flagged: assignment/augmented-assignment/``del`` into a subscript or
  attribute rooted at a tainted name, and in-place container mutators
  (``.update``, ``.append``, ``.pop``, ``.setdefault``, ...) called on
  one — plus the same rooted directly at an unassigned frozen call.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Tuple

from tpu_operator.analysis.config import AnalysisConfig
from tpu_operator.analysis.engine import Finding, ParsedModule
from tpu_operator.analysis.rules import MUTATOR_METHODS, Rule, dotted, root_name

FROZEN_CALLS = {"get", "list", "list_scoped", "get_or_none"}
PROPAGATING_CALLS = {"get", "items", "values", "keys"}
LAUNDERING_CALLS = {"thaw", "deepcopy", "dict", "list", "sorted", "copy"}


class _FnChecker:
    def __init__(self, rule_id: str, mod: ParsedModule, config: AnalysisConfig, scope: str):
        self.rule_id = rule_id
        self.mod = mod
        self.scope = scope
        self.recv_re = re.compile(config.frozen_receivers, re.IGNORECASE)
        self.config = config
        # var name -> origin description
        self.tainted: Dict[str, str] = {}
        self.findings: List[Finding] = []

    # -- taint sources -------------------------------------------------
    def _frozen_call_origin(self, node: ast.AST) -> Optional[str]:
        """Origin text when ``node`` is an informer read without
        copy=True, else None."""
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in FROZEN_CALLS
        ):
            return None
        recv = dotted(node.func.value) or ""
        if not self.recv_re.search(recv):
            return None
        for kw in node.keywords:
            if kw.arg == "copy" and (
                isinstance(kw.value, ast.Constant) and kw.value.value
            ):
                return None
        return f"{recv}.{node.func.attr}() at line {node.lineno}"

    def _taint_of(self, node: ast.AST) -> Optional[str]:
        """Origin if evaluating ``node`` yields a frozen view."""
        origin = self._frozen_call_origin(node)
        if origin is not None:
            return origin
        if isinstance(node, ast.Name):
            return self.tainted.get(node.id)
        if isinstance(node, (ast.Subscript, ast.Attribute)):
            base = root_name(node)
            if isinstance(base, ast.Name):
                return self.tainted.get(base.id)
            if isinstance(base, ast.Call):
                return self._frozen_call_origin(base)
            return None
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            func = node.func
            if func.attr in LAUNDERING_CALLS:
                return None
            if func.attr in PROPAGATING_CALLS:
                return self._taint_of(func.value)
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
            if node.func.id in LAUNDERING_CALLS:
                return None
        return None

    # -- mutation checks -----------------------------------------------
    def _check_mutation_target(self, target: ast.AST, line: int) -> None:
        """A store/delete INTO a subscript/attribute of a frozen view."""
        if not isinstance(target, (ast.Subscript, ast.Attribute)):
            return
        base = root_name(target)
        origin = None
        if isinstance(base, ast.Name):
            origin = self.tainted.get(base.id)
            what = base.id
        elif isinstance(base, ast.Call):
            origin = self._frozen_call_origin(base)
            what = "<informer read>"
        else:
            return
        if origin is not None:
            self.findings.append(
                Finding(
                    self.rule_id,
                    self.mod.relpath,
                    line,
                    f"mutates zero-copy informer view '{what}' "
                    f"(from {origin}) — read with copy=True or thaw() first",
                    scope=self.scope,
                )
            )

    def _check_expr(self, node: Optional[ast.AST]) -> None:
        """Find mutator-method calls on tainted roots anywhere in an
        expression tree."""
        if node is None:
            return
        for sub in ast.walk(node):
            if not (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr in MUTATOR_METHODS
            ):
                continue
            base = root_name(sub.func.value)
            origin = None
            if isinstance(base, ast.Name):
                origin = self.tainted.get(base.id)
                what = base.id
            elif isinstance(base, ast.Call):
                origin = self._frozen_call_origin(base)
                what = "<informer read>"
            else:
                continue
            # .pop() on a dict/list mutates; but .get/.items on the same
            # object do not — MUTATOR_METHODS already encodes that split
            if origin is not None:
                self.findings.append(
                    Finding(
                        self.rule_id,
                        self.mod.relpath,
                        sub.lineno,
                        f"calls .{sub.func.attr}() on zero-copy informer "
                        f"view '{what}' (from {origin}) — read with "
                        f"copy=True or thaw() first",
                        scope=self.scope,
                    )
                )

    # -- taint updates -------------------------------------------------
    def _assign_names(self, target: ast.AST, origin: Optional[str]) -> None:
        if isinstance(target, ast.Name):
            if origin is not None:
                self.tainted[target.id] = origin
            else:
                self.tainted.pop(target.id, None)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._assign_names(elt, origin)

    # -- statement walk ------------------------------------------------
    def run(self, body: List[ast.stmt]) -> None:
        for stmt in body:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            inner = _FnChecker(
                self.rule_id, self.mod, self.config,
                f"{self.scope}.{stmt.name}",
            )
            inner.run(stmt.body)
            self.findings.extend(inner.findings)
            return
        if isinstance(stmt, ast.ClassDef):
            for sub in stmt.body:
                self._stmt(sub)
            return
        if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            value = stmt.value
            self._check_expr(value)
            targets = (
                stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
            )
            for target in targets:
                self._check_mutation_target(target, stmt.lineno)
            origin = self._taint_of(value) if value is not None else None
            for target in targets:
                self._assign_names(target, origin)
            return
        if isinstance(stmt, ast.AugAssign):
            self._check_expr(stmt.value)
            self._check_mutation_target(stmt.target, stmt.lineno)
            return
        if isinstance(stmt, ast.Delete):
            for target in stmt.targets:
                self._check_mutation_target(target, stmt.lineno)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._check_expr(stmt.iter)
            self._assign_names(stmt.target, self._taint_of(stmt.iter))
            self.run(stmt.body)
            self.run(stmt.orelse)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._check_expr(stmt.test)
            self.run(stmt.body)
            self.run(stmt.orelse)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._check_expr(item.context_expr)
            self.run(stmt.body)
            return
        if isinstance(stmt, ast.Try):
            self.run(stmt.body)
            for handler in stmt.handlers:
                self.run(handler.body)
            self.run(stmt.orelse)
            self.run(stmt.finalbody)
            return
        # Expr, Return, Raise, Assert, ...
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._check_expr(child)


class FrozenViewRule(Rule):
    id = "frozen-view"

    def visit_module(
        self, mod: ParsedModule, config: AnalysisConfig
    ) -> List[Finding]:
        checker = _FnChecker(self.id, mod, config, mod.modname or mod.relpath)
        checker.run(mod.tree.body)
        return checker.findings
