"""lock-blocking — blocking calls made while a lock is held.

Contract encoded: locks in this codebase bound CRITICAL SECTIONS, not
I/O. A thread that sleeps, blocks on a ``WriteFuture.result()``, drains
a pipeline, or performs a client/gRPC round-trip while holding a lock
convoys every other thread needing that lock behind an unbounded wait —
the shape behind both the PR 5 stall-watchdog trips and classic
holding-the-informer-lock-across-a-LIST bugs.

Flagged under a held lock:

* calls whose dotted path is in ``blocking_functions`` (default
  ``time.sleep``; a bare ``sleep`` counts when the module does
  ``from time import sleep``);
* method calls named in ``blocking_methods`` (default ``result``,
  ``drain``, ``join_all``, ``urlopen``, ``getresponse``) plus ``wait``
  / ``wait_for`` — EXCEPT on the held lock's own condition, which is
  the one correct lock-releasing wait
  (``with self._cond: self._cond.wait()``).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Tuple

from tpu_operator.analysis.config import AnalysisConfig
from tpu_operator.analysis.engine import Finding, ParsedModule
from tpu_operator.analysis.rules import (
    Rule,
    collect_class_locks,
    collect_module_locks,
    dotted,
)
from tpu_operator.analysis.rules.heldwalk import HeldWalker

COND_WAITS = {"wait", "wait_for"}


class _BlockingCollector(HeldWalker):
    def __init__(self, resolve, config: AnalysisConfig, bare_sleep: bool):
        super().__init__(resolve)
        self.config = config
        self.bare_sleep = bare_sleep
        # (line, description, held)
        self.hits: List[Tuple[int, str, Tuple[str, ...]]] = []

    def on_node(self, node: ast.AST, held) -> None:
        if not held or not isinstance(node, ast.Call):
            return
        path = dotted(node.func)
        if path in self.config.blocking_functions or (
            self.bare_sleep and path == "sleep"
        ):
            self.hits.append((node.lineno, f"{path}()", held))
            return
        if isinstance(node.func, ast.Attribute):
            name = node.func.attr
            if name in COND_WAITS:
                # the held lock's own condition-wait releases the lock —
                # that is the idiom, not a violation
                if self.resolve(node.func.value) in held:
                    return
                self.hits.append((node.lineno, f".{name}()", held))
            elif name in self.config.blocking_methods:
                self.hits.append((node.lineno, f".{name}()", held))


class LockBlockingRule(Rule):
    id = "lock-blocking"

    def visit_module(
        self, mod: ParsedModule, config: AnalysisConfig
    ) -> List[Finding]:
        prefix = mod.modname.rsplit(".", 1)[-1] if mod.modname else mod.relpath
        module_locks = collect_module_locks(mod.tree)
        bare_sleep = any(
            isinstance(n, ast.ImportFrom)
            and n.module == "time"
            and any(a.name == "sleep" for a in n.names)
            for n in ast.walk(mod.tree)
        )

        def module_resolve(expr: ast.AST) -> Optional[str]:
            path = dotted(expr)
            if path in module_locks:
                return f"{prefix}.{path}"
            return None

        findings: List[Finding] = []
        class_nodes = set()
        for cls in [n for n in ast.walk(mod.tree) if isinstance(n, ast.ClassDef)]:
            for child in ast.walk(cls):
                class_nodes.add(id(child))
            locks = collect_class_locks(cls)

            def resolve(expr: ast.AST, _locks=locks, _cls=cls):
                path = dotted(expr)
                if path and path.startswith("self."):
                    attr = _locks.resolve(path[len("self.") :])
                    if attr is not None:
                        return f"{prefix}.{_cls.name}.{attr}"
                return module_resolve(expr)

            for fn in [n for n in cls.body if isinstance(n, ast.FunctionDef)]:
                findings.extend(
                    self._collect(
                        fn, resolve, mod, config, bare_sleep,
                        f"{cls.name}.{fn.name}",
                    )
                )
        for fn in [
            n
            for n in ast.walk(mod.tree)
            if isinstance(n, ast.FunctionDef) and id(n) not in class_nodes
        ]:
            findings.extend(
                self._collect(fn, module_resolve, mod, config, bare_sleep, fn.name)
            )
        return findings

    def _collect(
        self, fn, resolve, mod, config, bare_sleep, scope
    ) -> List[Finding]:
        collector = _BlockingCollector(resolve, config, bare_sleep)
        suffix = config.locked_method_suffix
        initial = (
            ("<caller>",)
            if suffix and getattr(fn, "name", "").endswith(suffix)
            else ()
        )
        collector.walk_function(fn, initial)
        return [
            Finding(
                self.id,
                mod.relpath,
                line,
                f"blocking call {desc} while holding "
                f"{'/'.join(sorted(set(held)))}",
                scope=scope,
            )
            for line, desc, held in collector.hits
        ]
