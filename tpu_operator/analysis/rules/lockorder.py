"""lock-order — static acquisition-order graph over nested ``with``.

Contract encoded: the write pipeline / batch lanes / gang coordinator /
breaker stack is deadlock-free because lock acquisition follows a
consistent partial order. Every textually nested acquisition
(``with self._a: ... with self._b:``) contributes a directed edge
``a → b``; a cycle in the package-wide graph means two code paths
acquire the same pair of locks in opposite orders — a potential
deadlock even if the chaos suites never happened to interleave it.

Nodes are canonicalized per lock DECLARATION (``Class._attr`` /
``module._global``), not per instance: two instances of one class
acquired in inconsistent orders is exactly the hazard worth flagging.
Acquisitions that nest across call boundaries are invisible statically
— the runtime half (``analysis/lockwatch.py``) covers those inside the
chaos suites.

A nested re-acquisition of the SAME non-reentrant ``threading.Lock``
is flagged immediately (guaranteed self-deadlock, no graph needed).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tpu_operator.analysis.config import AnalysisConfig
from tpu_operator.analysis.engine import Finding, ParsedModule
from tpu_operator.analysis.rules import (
    Rule,
    collect_class_locks,
    collect_module_locks,
    dotted,
)
from tpu_operator.analysis.rules.heldwalk import HeldWalker

# edge -> first witness (path, line)
_Edges = Dict[Tuple[str, str], Tuple[str, int]]


class _EdgeCollector(HeldWalker):
    def __init__(self, resolve, relpath: str, rlocks: Set[str]):
        super().__init__(resolve)
        self.relpath = relpath
        self.rlocks = rlocks
        self.edges: _Edges = {}
        self.self_deadlocks: List[Tuple[str, int]] = []

    def on_acquire(self, with_node, held_before, acquired) -> None:
        # a multi-item `with self._a, self._b:` acquires left-to-right —
        # earlier items order before later ones exactly like nesting
        for i, (lock, expr) in enumerate(acquired):
            outers = list(held_before) + [a for a, _ in acquired[:i]]
            for outer in outers:
                if outer == lock:
                    if lock not in self.rlocks:
                        self.self_deadlocks.append((lock, with_node.lineno))
                    continue
                self.edges.setdefault(
                    (outer, lock), (self.relpath, with_node.lineno)
                )


class LockOrderRule(Rule):
    id = "lock-order"

    def __init__(self) -> None:
        self.edges: _Edges = {}

    def visit_module(
        self, mod: ParsedModule, config: AnalysisConfig
    ) -> List[Finding]:
        prefix = mod.modname.rsplit(".", 1)[-1] if mod.modname else mod.relpath
        module_locks = collect_module_locks(mod.tree)
        rlock_nodes: Set[str] = set()

        # module-level code outside classes
        def module_resolve(expr: ast.AST) -> Optional[str]:
            path = dotted(expr)
            if path in module_locks:
                return f"{prefix}.{path}"
            return None

        findings: List[Finding] = []
        classes = [
            n for n in ast.walk(mod.tree) if isinstance(n, ast.ClassDef)
        ]
        class_nodes: Set[int] = set()
        for cls in classes:
            for child in ast.walk(cls):
                class_nodes.add(id(child))
            locks = collect_class_locks(cls)
            for attr in locks.rlocks:
                rlock_nodes.add(f"{prefix}.{cls.name}.{attr}")

            def resolve(expr: ast.AST, _locks=locks, _cls=cls):
                path = dotted(expr)
                if path and path.startswith("self."):
                    attr = _locks.resolve(path[len("self.") :])
                    if attr is not None:
                        return f"{prefix}.{_cls.name}.{attr}"
                return module_resolve(expr)

            for fn in [n for n in cls.body if isinstance(n, ast.FunctionDef)]:
                findings.extend(
                    self._collect(fn, resolve, mod, rlock_nodes, f"{cls.name}.{fn.name}")
                )

        # module-level functions (not inside any class)
        for fn in [
            n
            for n in ast.walk(mod.tree)
            if isinstance(n, ast.FunctionDef) and id(n) not in class_nodes
        ]:
            findings.extend(
                self._collect(fn, module_resolve, mod, rlock_nodes, fn.name)
            )
        return findings

    def _collect(
        self, fn, resolve, mod: ParsedModule, rlock_nodes: Set[str], scope: str
    ) -> List[Finding]:
        collector = _EdgeCollector(resolve, mod.relpath, rlock_nodes)
        collector.walk_function(fn)
        for edge, witness in collector.edges.items():
            self.edges.setdefault(edge, witness)
        out = []
        for lock, line in collector.self_deadlocks:
            out.append(
                Finding(
                    self.id,
                    mod.relpath,
                    line,
                    f"nested re-acquisition of non-reentrant lock "
                    f"'{lock}' — guaranteed self-deadlock",
                    scope=scope,
                )
            )
        return out

    def finalize(self, config: AnalysisConfig) -> List[Finding]:
        adj: Dict[str, Set[str]] = {}
        for (a, b) in self.edges:
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())
        findings: List[Finding] = []
        for scc in _sccs(adj):
            if len(scc) < 2:
                continue
            cycle = sorted(scc)
            witness_edges = sorted(
                (a, b, *self.edges[(a, b)])
                for (a, b) in self.edges
                if a in scc and b in scc
            )
            where = ", ".join(
                f"{a}->{b} at {path}:{line}" for a, b, path, line in witness_edges
            )
            path, line = witness_edges[0][2], witness_edges[0][3]
            findings.append(
                Finding(
                    self.id,
                    path,
                    line,
                    f"potential deadlock: lock-order cycle "
                    f"[{' <-> '.join(cycle)}] ({where})",
                    scope="lock-graph",
                )
            )
        # reset for potential re-runs within one process
        self.edges = {}
        return findings


def _sccs(adj: Dict[str, Set[str]]) -> List[Set[str]]:
    """Iterative Tarjan."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    out: List[Set[str]] = []
    counter = [0]

    for root in sorted(adj):
        if root in index:
            continue
        work = [(root, iter(sorted(adj[root])))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(adj[nxt]))))
                    advanced = True
                    break
                elif nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.add(w)
                    if w == node:
                        break
                out.append(scc)
    return out
