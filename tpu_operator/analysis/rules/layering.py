"""layering — the package's import-direction contracts.

Contracts encoded (docs/architecture.md, docs/observability.md, the
obs/ and kube/ module docstrings):

* ``obs/`` imports NOTHING from ``tpu_operator`` — it is the
  always-importable instrumentation layer every other module may use;
* ``kube/`` never imports upward (``controllers/``, ``schedsim/``,
  ``upgrade/``, ...): the module-hook pattern
  (``write_pipeline.on_queue_wait_ms``, ``client.on_conflict_retry``)
  is the only allowed inversion, and it is an assignment made BY the
  upper layer, not an import made by kube/;
* nothing in the runtime package imports ``tpu_operator.analysis`` —
  the analyzer stands outside the stack it checks.

Deliberate inversions in simulation/test scaffolding (the kubelet sim
IS the kubelet side of the device-plugin wire) carry file-level
``# lint: ignore-file[layering]`` headers where a reviewer sees them.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Tuple

from tpu_operator.analysis.config import AnalysisConfig
from tpu_operator.analysis.engine import Finding, ParsedModule
from tpu_operator.analysis.rules import Rule

PKG = "tpu_operator"
# what kube/ may reach: itself, the dependency-free obs layer, shared
# constants, and the API types
KUBE_ALLOWED = {
    f"{PKG}.kube",
    f"{PKG}.obs",
    f"{PKG}.consts",
    f"{PKG}.api",
}


def _resolve_relative(modname: str, level: int, module: Optional[str]) -> str:
    parts = modname.split(".")
    base = parts[: len(parts) - level] if level <= len(parts) else []
    if module:
        base = base + [module]
    return ".".join(base)


def _imports_of(mod: ParsedModule) -> List[Tuple[str, int]]:
    """Every (dotted-target, line) the module imports, relative imports
    resolved against the module's own dotted name."""
    out: List[Tuple[str, int]] = []
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                out.append((alias.name, node.lineno))
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                pkg_name = mod.modname
                # a module's relative base is its package
                if not mod.relpath.endswith("/__init__.py"):
                    pkg_name = ".".join(pkg_name.split(".")[:-1]) if pkg_name else ""
                    level = node.level - 1
                else:
                    level = node.level - 1
                target = _resolve_relative(pkg_name, level, node.module)
            else:
                target = node.module or ""
            # `from tpu_operator import consts` imports tpu_operator.consts
            if target:
                for alias in node.names:
                    out.append((f"{target}.{alias.name}", node.lineno))
            else:
                for alias in node.names:
                    out.append((alias.name, node.lineno))
    return out


def _allowed(target: str, allowed_prefixes) -> bool:
    if target == PKG:  # bare "import tpu_operator" (namespace only)
        return True
    return any(
        target == p or target.startswith(p + ".") for p in allowed_prefixes
    )


class LayeringRule(Rule):
    id = "layering"

    def visit_module(
        self, mod: ParsedModule, config: AnalysisConfig
    ) -> List[Finding]:
        findings: List[Finding] = []
        if not mod.modname.startswith(PKG):
            return findings
        in_obs = mod.modname.startswith(f"{PKG}.obs")
        in_kube = mod.modname.startswith(f"{PKG}.kube")
        in_analysis = mod.modname.startswith(f"{PKG}.analysis")
        for target, line in _imports_of(mod):
            if not target.startswith(PKG):
                continue
            if in_obs and not _allowed(target, {f"{PKG}.obs"}):
                findings.append(
                    Finding(
                        self.id,
                        mod.relpath,
                        line,
                        f"obs/ must import nothing from the package "
                        f"(imports {target})",
                        scope=mod.modname,
                    )
                )
            elif in_kube and not _allowed(target, KUBE_ALLOWED):
                findings.append(
                    Finding(
                        self.id,
                        mod.relpath,
                        line,
                        f"kube/ must not import upward (imports {target}; "
                        f"use a module hook like on_queue_wait_ms for "
                        f"inversions)",
                        scope=mod.modname,
                    )
                )
            elif (
                not in_analysis
                and _allowed(target, {f"{PKG}.analysis"})
            ):
                findings.append(
                    Finding(
                        self.id,
                        mod.relpath,
                        line,
                        f"runtime code must not import the analyzer "
                        f"(imports {target})",
                        scope=mod.modname,
                    )
                )
        return findings
