"""Rule registry + shared AST helpers.

Every rule encodes one contract the repo already states in docs or
enforces by hand-written tests; the catalog with the contract each rule
comes from is ``docs/analysis.md``. Rules are pure AST walkers: no
imports of the code under analysis, no execution.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tpu_operator.analysis.config import AnalysisConfig
from tpu_operator.analysis.engine import Finding, ParsedModule

LOCK_FACTORIES = {
    "threading.Lock",
    "threading.RLock",
    "Lock",
    "RLock",
}
CONDITION_FACTORIES = {"threading.Condition", "Condition"}

# method names that mutate the common stdlib containers in place
MUTATOR_METHODS = {
    "append",
    "appendleft",
    "extend",
    "extendleft",
    "insert",
    "pop",
    "popitem",
    "popleft",
    "remove",
    "clear",
    "add",
    "discard",
    "update",
    "setdefault",
    "sort",
    "reverse",
}


class Rule:
    id = ""

    def visit_module(
        self, mod: ParsedModule, config: AnalysisConfig
    ) -> List[Finding]:
        return []

    def finalize(self, config: AnalysisConfig) -> List[Finding]:
        return []


def dotted(node: ast.AST) -> Optional[str]:
    """``self._lock`` / ``threading.Lock`` / ``time.sleep`` for pure
    Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def root_self_attr(node: ast.AST) -> Optional[str]:
    """First attribute hanging off ``self`` at the base of an
    Attribute/Subscript chain: ``self._chains[key].append`` → ``_chains``."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            return node.attr
        node = node.value
    return None


def root_name(node: ast.AST) -> Optional[ast.AST]:
    """Base Name/Call of an Attribute/Subscript chain."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node


class ClassLocks:
    """Lock-typed attributes a class owns, plus Condition aliases.

    ``self._idle = threading.Condition(self._lock)`` means ``with
    self._idle`` acquires ``_lock`` — the alias map folds the condition
    attribute onto the lock it wraps. A bare ``threading.Condition()``
    owns an internal lock, so the condition attribute is itself a lock
    node.
    """

    def __init__(self) -> None:
        self.locks: Dict[str, int] = {}  # attr -> decl line
        self.rlocks: Set[str] = set()
        self.alias: Dict[str, str] = {}  # cond attr -> lock attr

    def resolve(self, attr: str) -> Optional[str]:
        if attr in self.locks:
            return attr
        return self.alias.get(attr)

    @property
    def all_attrs(self) -> Set[str]:
        return set(self.locks) | set(self.alias)


def collect_class_locks(cls: ast.ClassDef) -> ClassLocks:
    out = ClassLocks()
    pending_conds: List[Tuple[str, Optional[str], int]] = []
    for fn in [n for n in cls.body if isinstance(n, ast.FunctionDef)]:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign) or not isinstance(
                node.value, ast.Call
            ):
                continue
            call_path = dotted(node.value.func)
            for target in node.targets:
                if not (
                    isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"
                ):
                    continue
                if call_path in LOCK_FACTORIES:
                    out.locks[target.attr] = node.lineno
                    if call_path and call_path.endswith("RLock"):
                        out.rlocks.add(target.attr)
                elif call_path in CONDITION_FACTORIES:
                    arg_attr = None
                    if node.value.args:
                        a = dotted(node.value.args[0])
                        if a and a.startswith("self."):
                            arg_attr = a[len("self.") :]
                    pending_conds.append((target.attr, arg_attr, node.lineno))
    for cond_attr, wrapped, line in pending_conds:
        if wrapped is not None and wrapped in out.locks:
            out.alias[cond_attr] = wrapped
        else:
            # a Condition over its own (or an unresolvable) lock is a
            # lock node in its own right
            out.locks[cond_attr] = line
            out.rlocks.add(cond_attr)  # Condition's default lock is an RLock
    return out


def collect_module_locks(tree: ast.Module) -> Dict[str, int]:
    """Module-level ``_corr_lock = threading.Lock()`` style globals."""
    out: Dict[str, int] = {}
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and isinstance(node.value, ast.Call)
            and dotted(node.value.func) in LOCK_FACTORIES
        ):
            for target in node.targets:
                if isinstance(target, ast.Name):
                    out[target.id] = node.lineno
    return out


def iter_class_functions(cls: ast.ClassDef):
    for node in cls.body:
        if isinstance(node, ast.FunctionDef):
            yield node


def build_rules(config: AnalysisConfig) -> List[Rule]:
    from tpu_operator.analysis.rules.blocking import LockBlockingRule
    from tpu_operator.analysis.rules.frozenview import FrozenViewRule
    from tpu_operator.analysis.rules.guards import GuardedByRule
    from tpu_operator.analysis.rules.layering import LayeringRule
    from tpu_operator.analysis.rules.lockorder import LockOrderRule
    from tpu_operator.analysis.rules.metricsfed import MetricsFedRule

    return [
        LayeringRule(),
        GuardedByRule(),
        LockOrderRule(),
        LockBlockingRule(),
        FrozenViewRule(),
        MetricsFedRule(),
    ]
