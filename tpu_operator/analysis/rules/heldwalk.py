"""Shared lock-scope AST walker.

Walks a function body tracking which locks are statically held at each
node: ``with self._lock:`` pushes, leaving the block pops, and entering
a nested ``def``/``lambda`` RESETS the held set (a closure defined
under a lock does not execute under it — the manager's watchdog monitor
is exactly that shape). ``guards``, ``lockorder`` and ``blocking`` are
all views over this one traversal.
"""

from __future__ import annotations

import ast
from typing import Callable, List, Optional, Tuple

# resolve(context_expr) -> canonical lock name or None
Resolver = Callable[[ast.AST], Optional[str]]


class HeldWalker:
    """Subclass and override ``on_node`` / ``on_acquire``."""

    def __init__(self, resolve: Resolver):
        self.resolve = resolve

    # hooks ------------------------------------------------------------
    def on_node(self, node: ast.AST, held: Tuple[str, ...]) -> None:
        pass

    def on_acquire(
        self,
        with_node: ast.With,
        held_before: Tuple[str, ...],
        acquired: List[Tuple[str, ast.expr]],
    ) -> None:
        """Called once per ``with`` that acquires at least one known
        lock, BEFORE its body is walked."""

    # traversal --------------------------------------------------------
    def walk_function(
        self, fn: ast.AST, initial: Tuple[str, ...] = ()
    ) -> None:
        """``initial`` seeds the held set — the caller-holds-lock
        (``*_locked``) convention passes a pseudo-lock here."""
        body = getattr(fn, "body", [])
        for stmt in body:
            self._walk(stmt, initial)

    def _walk(self, node: ast.AST, held: Tuple[str, ...]) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired: List[Tuple[str, ast.expr]] = []
            for item in node.items:
                self._walk(item.context_expr, held)
                lock = self.resolve(item.context_expr)
                if lock is not None:
                    acquired.append((lock, item.context_expr))
                if item.optional_vars is not None:
                    self._walk(item.optional_vars, held)
            if acquired and isinstance(node, ast.With):
                self.on_acquire(node, held, acquired)
            inner = held + tuple(lock for lock, _ in acquired)
            self.on_node(node, held)
            for stmt in node.body:
                self._walk(stmt, inner)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.on_node(node, held)
            for dec in node.decorator_list:
                self._walk(dec, held)
            for stmt in node.body:
                self._walk(stmt, ())
            return
        if isinstance(node, ast.Lambda):
            self.on_node(node, held)
            self._walk(node.body, ())
            return
        self.on_node(node, held)
        for child in ast.iter_child_nodes(node):
            self._walk(child, held)
