"""guarded-by — inferred lock/attribute consistency within a class.

Contract encoded: PR 5's breaker/pipeline thread-safety discipline —
when a class owns a ``threading.Lock``/``RLock``, the mutable state it
protects is whatever the class itself mutates under ``with self._lock``.
Any OTHER mutation of those same attributes outside a lock block in the
same class is a latent race: two threads interleaving a guarded and an
unguarded write.

Inference, per class owning at least one lock:

1. collect every attribute the class WRITES (assignment, augmented
   assignment, ``del``, or an in-place container mutator like
   ``.append``/``.pop``/``.update``) under a held lock, outside
   ``__init__``/``__new__`` — that is the guarded set, tagged with the
   lock(s) it was seen under;
2. flag writes to guarded attributes with no lock held. ``__init__`` is
   exempt (the object is not yet shared); closures reset the held set
   (they run on other threads).

Unlocked READS of guarded attributes are only flagged with
``guarded_by_strict_reads = true``: single-word reads of counters and
flags are GIL-atomic and idiomatic here (the breaker's lock-free fast
path is deliberate and documented) — flagging them would bury the
write findings that matter.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tpu_operator.analysis.config import AnalysisConfig
from tpu_operator.analysis.engine import Finding, ParsedModule
from tpu_operator.analysis.rules import (
    MUTATOR_METHODS,
    ClassLocks,
    Rule,
    collect_class_locks,
    dotted,
    root_self_attr,
)
from tpu_operator.analysis.rules.heldwalk import HeldWalker

INIT_METHODS = {"__init__", "__new__", "__init_subclass__"}

# (attr, line, held, method)
_Access = Tuple[str, int, Tuple[str, ...], str]


class _AccessCollector(HeldWalker):
    def __init__(self, resolve, lock_attrs: Set[str], method: str):
        super().__init__(resolve)
        self.lock_attrs = lock_attrs
        self.method = method
        self.writes: List[_Access] = []
        self.reads: List[_Access] = []

    def _note_write(self, attr: Optional[str], node: ast.AST, held):
        if attr is not None and attr not in self.lock_attrs:
            self.writes.append((attr, node.lineno, held, self.method))

    def on_node(self, node: ast.AST, held) -> None:
        if isinstance(node, ast.Assign):
            for target in node.targets:
                self._note_targets(target, node, held)
        elif isinstance(node, ast.AugAssign):
            self._note_targets(node.target, node, held)
        elif isinstance(node, ast.Delete):
            for target in node.targets:
                self._note_write(root_self_attr(target), node, held)
        elif isinstance(node, ast.Call):
            func = node.func
            if (
                isinstance(func, ast.Attribute)
                and func.attr in MUTATOR_METHODS
            ):
                self._note_write(root_self_attr(func.value), node, held)
        elif isinstance(node, ast.Attribute) and isinstance(
            node.ctx, ast.Load
        ):
            if (
                isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and node.attr not in self.lock_attrs
            ):
                self.reads.append((node.attr, node.lineno, held, self.method))

    def _note_targets(self, target: ast.AST, node: ast.AST, held) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._note_targets(elt, node, held)
            return
        self._note_write(root_self_attr(target), node, held)


class GuardedByRule(Rule):
    id = "guarded-by"

    def visit_module(
        self, mod: ParsedModule, config: AnalysisConfig
    ) -> List[Finding]:
        findings: List[Finding] = []
        for cls in [
            n for n in ast.walk(mod.tree) if isinstance(n, ast.ClassDef)
        ]:
            findings.extend(self._check_class(cls, mod, config))
        return findings

    def _check_class(
        self, cls: ast.ClassDef, mod: ParsedModule, config: AnalysisConfig
    ) -> List[Finding]:
        locks = collect_class_locks(cls)
        if not locks.locks:
            return []

        def resolve(expr: ast.AST) -> Optional[str]:
            path = dotted(expr)
            if path and path.startswith("self."):
                return locks.resolve(path[len("self.") :])
            return None

        writes: List[_Access] = []
        reads: List[_Access] = []
        suffix = config.locked_method_suffix
        for fn in [n for n in cls.body if isinstance(n, ast.FunctionDef)]:
            if fn.name in INIT_METHODS:
                continue
            collector = _AccessCollector(resolve, locks.all_attrs, fn.name)
            # caller-holds-lock convention: a *_locked method runs with
            # the owning lock already held
            initial = ("<caller>",) if suffix and fn.name.endswith(suffix) else ()
            collector.walk_function(fn, initial)
            writes.extend(collector.writes)
            reads.extend(collector.reads)

        guarded: Dict[str, Set[str]] = {}
        for attr, _line, held, _m in writes:
            if held:
                guarded.setdefault(attr, set()).update(held)

        findings: List[Finding] = []
        for attr, line, held, method in writes:
            if held or attr not in guarded:
                continue
            under = "/".join(sorted(guarded[attr]))
            findings.append(
                Finding(
                    self.id,
                    mod.relpath,
                    line,
                    f"'{attr}' is written under '{under}' elsewhere in "
                    f"{cls.name} but written here with no lock held",
                    scope=f"{cls.name}.{method}",
                )
            )
        if config.guarded_by_strict_reads:
            for attr, line, held, method in reads:
                if held or attr not in guarded:
                    continue
                under = "/".join(sorted(guarded[attr]))
                findings.append(
                    Finding(
                        self.id,
                        mod.relpath,
                        line,
                        f"'{attr}' is guarded by '{under}' in {cls.name} "
                        f"but read here with no lock held",
                        scope=f"{cls.name}.{method}",
                    )
                )
        return findings
