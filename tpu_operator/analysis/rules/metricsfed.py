"""metrics-fed — every registered metric must have a feeding call site.

Contract encoded: the obs surface (docs/observability.md) is only
trustworthy if every series it exports moves. A gauge registered in
``operator_metrics.py`` that no code ever ``.set()``s is worse than
missing — dashboards read a permanent 0 and alerts silently never fire.
As the surface grows (21+ series and counting), dead registrations are
exactly the drift this rule catches.

Mechanics: collect ``self.NAME = g(...)/c(...)/h(...)`` (or direct
``Gauge``/``Counter``/``Histogram``) registrations from the configured
metrics module, then every attribute LOAD named ``NAME`` anywhere in
the scanned tree — ``metrics.slices_ready.set(...)``, a bound-method
hook wire like ``_wp.on_queue_wait_ms = hist.observe`` reading the
attribute, or a convenience feeder inside the metrics class itself all
count. Registrations with zero loads are findings at their
registration line.
"""

from __future__ import annotations

import ast
from collections import Counter
from typing import Dict, List, Tuple

from tpu_operator.analysis.config import AnalysisConfig
from tpu_operator.analysis.engine import Finding, ParsedModule
from tpu_operator.analysis.rules import Rule, dotted

REGISTER_FUNCS = {"g", "c", "h", "Gauge", "Counter", "Histogram", "Summary"}


class MetricsFedRule(Rule):
    id = "metrics-fed"

    def __init__(self) -> None:
        # attr -> (relpath, line)
        self.registered: Dict[str, Tuple[str, int]] = {}
        self.loads: Counter = Counter()

    def visit_module(
        self, mod: ParsedModule, config: AnalysisConfig
    ) -> List[Finding]:
        if mod.relpath == config.metrics_module:
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Assign):
                    continue
                if not isinstance(node.value, ast.Call):
                    continue
                func = node.value.func
                fname = (dotted(func) or "").split(".")[-1]
                if fname not in REGISTER_FUNCS:
                    continue
                for target in node.targets:
                    if (
                        isinstance(target, ast.Attribute)
                        and isinstance(target.value, ast.Name)
                        and target.value.id == "self"
                    ):
                        self.registered[target.attr] = (
                            mod.relpath,
                            node.lineno,
                        )
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Attribute) and isinstance(
                node.ctx, ast.Load
            ):
                self.loads[node.attr] += 1
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "getattr"
                and len(node.args) >= 2
                and isinstance(node.args[1], ast.Constant)
                and isinstance(node.args[1].value, str)
            ):
                # getattr(metrics, "name", None) feeders count as loads
                self.loads[node.args[1].value] += 1
        return []

    def finalize(self, config: AnalysisConfig) -> List[Finding]:
        findings = []
        for attr, (relpath, line) in sorted(self.registered.items()):
            if self.loads[attr] == 0:
                findings.append(
                    Finding(
                        self.id,
                        relpath,
                        line,
                        f"metric '{attr}' is registered but never fed "
                        f"(no attribute load anywhere in the scanned tree)",
                        scope="OperatorMetrics",
                    )
                )
        self.registered = {}
        self.loads = Counter()
        return findings
