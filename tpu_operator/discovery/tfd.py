"""TPU feature discovery (TFD) — the GFD slot.

The reference's gpu-feature-discovery (external Go+NVML image) publishes
``nvidia.com/gpu.product``/memory/CUDA labels. TFD publishes the TPU facts
that drive scheduling and the operator's fan-out:

* chip type (generation) and per-host chip count,
* HBM per chip,
* ICI topology string + wraparound flag (the fabric facts, SURVEY.md §2.4),
* slice host count and this host's worker id (multi-host coordination),
* installed libtpu version.

Facts come from (in priority order) native libtpuinfo, GKE-provided node
labels, and the environment; they are applied as ``tpu.k8s.io/tpu.*`` node
labels and optionally as an NFD feature file.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Dict, Optional

from tpu_operator import consts
from tpu_operator.native import tpuinfo
from tpu_operator.workloads import topology as topo

log = logging.getLogger("tpu-feature-discovery")


def gather_features(
    node: dict,
    dev_root: str = "/dev",
    libtpu_dir: str = consts.LIBTPU_HOST_DIR,
    env: Optional[Dict[str, str]] = None,
) -> Dict[str, str]:
    """Compute the label set for a node (pure; no API writes)."""
    env = env if env is not None else dict(os.environ)
    labels = node.get("metadata", {}).get("labels", {}) or {}
    features: Dict[str, str] = {}

    accelerator = labels.get(consts.GKE_TPU_ACCELERATOR_LABEL, "")
    generation = consts.GKE_ACCELERATOR_TO_GENERATION.get(accelerator, "")
    if not generation:
        generation = env.get("TPU_GENERATION", "")
    if generation:
        features[consts.TFD_CHIP_TYPE_LABEL] = generation

    chips = tpuinfo.chip_count(dev_root)
    if chips:
        features[consts.TFD_CHIP_COUNT_LABEL] = str(chips)

    if generation in topo.HBM_GB:
        features[consts.TFD_HBM_GB_LABEL] = str(topo.HBM_GB[generation])

    topology = labels.get(consts.GKE_TPU_TOPOLOGY_LABEL, "") or env.get(
        "TPU_TOPOLOGY", ""
    )
    if topology:
        features[consts.TFD_TOPOLOGY_LABEL] = topology
        if generation:
            wraps = topo.wraparound_dims(topology, generation)
            features[consts.TFD_ICI_WRAP_LABEL] = (
                "true" if any(wraps) else "false"
            )
            features[consts.TFD_SLICE_HOSTS_LABEL] = str(
                topo.host_count(topology, generation)
            )

    worker_id = env.get("TPU_WORKER_ID", "")
    if worker_id != "":
        features[consts.TFD_WORKER_ID_LABEL] = worker_id

    # slice identity for the operator's slice-scoped readiness aggregate:
    # explicit env wins; multi-host slices fall back to the GKE node pool
    # (all hosts of one multi-host slice live in one pool)
    slice_id = env.get("TPU_SLICE_ID", "") or env.get("TPU_SLICE_NAME", "")
    if not slice_id:
        hosts = features.get(consts.TFD_SLICE_HOSTS_LABEL, "1")
        if hosts.isdigit() and int(hosts) > 1:
            slice_id = labels.get(consts.GKE_NODEPOOL_LABEL, "")
    if slice_id:
        features[consts.TFD_SLICE_ID_LABEL] = slice_id

    libtpu_version = _libtpu_version(libtpu_dir)
    if libtpu_version:
        features[consts.TFD_LIBTPU_VERSION_LABEL] = libtpu_version

    return features


def _libtpu_version(libtpu_dir: str) -> str:
    """Version from the installer's marker file or a versioned .so name."""
    marker = os.path.join(libtpu_dir, "VERSION")
    try:
        with open(marker) as f:
            return f.read().strip()
    except OSError:
        pass
    import glob
    import re

    for so in glob.glob(os.path.join(libtpu_dir, "libtpu-*.so")):
        m = re.search(r"libtpu-(.+)\.so$", os.path.basename(so))
        if m:
            return m.group(1)
    return ""


def apply_features(client, node_name: str, features: Dict[str, str]) -> bool:
    """Write labels to the node; prunes stale ``tpu.k8s.io/tpu.*`` TFD labels
    we no longer assert. Conflict-retried — the Node is shared with the
    deploy-label bus, the upgrade FSM and the slice/maintenance operands.
    Returns True when anything changed."""
    from tpu_operator.kube.client import mutate_with_retry

    managed_prefixes = (
        consts.TFD_CHIP_TYPE_LABEL,
        consts.TFD_CHIP_COUNT_LABEL,
        consts.TFD_HBM_GB_LABEL,
        consts.TFD_TOPOLOGY_LABEL,
        consts.TFD_SLICE_HOSTS_LABEL,
        consts.TFD_WORKER_ID_LABEL,
        consts.TFD_ICI_WRAP_LABEL,
        consts.TFD_LIBTPU_VERSION_LABEL,
        consts.TFD_SLICE_ID_LABEL,
    )
    result = {"changed": False}

    def mutate(node):
        labels = node["metadata"].setdefault("labels", {})
        changed = False
        for key in managed_prefixes:
            want = features.get(key)
            if want is None and key in labels:
                del labels[key]
                changed = True
            elif want is not None and labels.get(key) != want:
                labels[key] = want
                changed = True
        result["changed"] = changed
        return changed

    mutate_with_retry(client, "v1", "Node", node_name, mutate=mutate)
    return result["changed"]


def write_nfd_feature_file(
    features: Dict[str, str],
    path: str = "/etc/kubernetes/node-feature-discovery/features.d/tpu",
) -> None:
    """NFD sidecar-style feature file (label=value lines)."""
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        for k, v in sorted(features.items()):
            f.write(f"{k}={v}\n")


def run_loop(
    client,
    node_name: str,
    interval_s: float = 60.0,
    once: bool = False,
    dev_root: str = "/dev",
    libtpu_dir: str = consts.LIBTPU_HOST_DIR,
) -> None:
    while True:
        try:
            node = client.get("v1", "Node", node_name)
            features = gather_features(
                node, dev_root=dev_root, libtpu_dir=libtpu_dir
            )
            if apply_features(client, node_name, features):
                log.info("updated %d TFD labels on %s", len(features), node_name)
        except Exception:
            log.exception("feature discovery pass failed")
        if once:
            return
        time.sleep(interval_s)


def main(argv=None) -> int:
    import argparse

    logging.basicConfig(level="INFO")
    p = argparse.ArgumentParser("tpu-feature-discovery")
    p.add_argument("--node-name", default=os.environ.get("NODE_NAME", ""))
    p.add_argument("--interval", type=float, default=60.0)
    p.add_argument("--once", action="store_true")
    p.add_argument("--dev-root", default="/dev")
    args = p.parse_args(argv)
    if not args.node_name:
        log.error("NODE_NAME required")
        return 1
    from tpu_operator.kube.rest import RestClient

    run_loop(
        RestClient(), args.node_name, interval_s=args.interval, once=args.once,
        dev_root=args.dev_root,
    )
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
