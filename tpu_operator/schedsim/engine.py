"""The scheduling-churn engine — sustained allocation traffic through the
real device-plugin admission path.

One :class:`HostAgent` per simulated host wraps a REAL
``TPUDevicePluginServicer`` (synthetic chip discovery, production RPC
handlers) behind the kubelet admission sequence
(``kubelet_sim.admit_and_allocate``: options → GetPreferredAllocation
with fail-closed preference checks → Allocate). The engine's workers
create short-lived pods against the cluster (kubesim or FakeClient),
pick hosts with ICI-topology-aware scoring, admit through the shared
:class:`~tpu_operator.schedsim.gang.GangCoordinator` gate (single jobs
are gangs of one — holds only protect anything if every admission path
honors them), record allocation latency, and a reaper terminates pods at
end-of-life and releases their chips from the
:class:`~tpu_operator.schedsim.registry.AllocationRegistry`.

The engine is simultaneously a load generator and a correctness harness:
double allocations raise at the ledger, gang placement is asserted
all-or-nothing after every admission and rollback, and ``drain()`` ends
with a zero-held-chips steady-state check. See ``docs/allocation.md``.
"""

from __future__ import annotations

import heapq
import itertools
import logging
import random
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from tpu_operator import consts
from tpu_operator.obs import flight, trace
from tpu_operator.kube.kubelet_sim import (
    InProcessPluginStub,
    PodGoneError,
    admit_and_allocate,
)
from tpu_operator.plugin.server import HEALTHY, TPUDevicePluginServicer
from tpu_operator.schedsim.gang import GangCoordinator
from tpu_operator.schedsim.registry import (
    AllocationRegistry,
    DoubleAllocationError,
    fragmentation_pct,
    largest_contiguous_block,
)

log = logging.getLogger("tpu-schedsim")


class InsufficientChipsError(RuntimeError):
    """The host cannot serve the request right now (free healthy chips <
    requested) — a load condition, not a bug."""


class SyntheticChipServicer(TPUDevicePluginServicer):
    """The production servicer over synthetic chip discovery — no devfs,
    no poller, real GetPreferredAllocation/Allocate. A 1000-host fleet
    needs a thousand of these; stat-ing eight thousand stub device files
    per refresh would measure the filesystem."""

    def __init__(self, chips: int = 8, **kw):
        self._n_chips = chips
        kw.setdefault("dev_root", "/nonexistent-schedsim-devfs")
        super().__init__(**kw)

    def discover(self):
        return [
            {"index": i, "path": f"/dev/accel{i}"}
            for i in range(self._n_chips)
        ]


class LatencyRecorder:
    """Bounded latency sample sink with percentile readout. An
    optional ``observer`` (the alloc-latency Prometheus histogram's
    ``observe``) sees every sample as it lands."""

    def __init__(self, cap: int = 200_000, observer=None):
        self.cap = cap
        self._lock = threading.Lock()
        self._samples: List[float] = []
        self.count = 0
        self.observer = observer

    def add(self, ms: float) -> None:
        with self._lock:
            self.count += 1  # under self._lock
            if len(self._samples) < self.cap:
                self._samples.append(ms)
        obs = self.observer
        if obs is not None:
            try:
                obs(ms)
            except Exception:
                pass

    @staticmethod
    def _at(ordered: List[float], p: float) -> float:
        idx = min(
            len(ordered) - 1,
            max(0, int(round(p / 100.0 * (len(ordered) - 1)))),
        )
        return round(ordered[idx], 3)

    def percentile(self, p: float) -> Optional[float]:
        # copy under the lock, sort OUTSIDE it: add() sits on the timed
        # allocation hot path and must never wait behind an O(n log n)
        # sort of a six-figure sample buffer
        with self._lock:
            if not self._samples:
                return None
            samples = list(self._samples)
        return self._at(sorted(samples), p)

    def summary(self) -> dict:
        with self._lock:
            count = self.count
            samples = list(self._samples)
        if not samples:
            return {"count": count, "p50_ms": None, "p99_ms": None}
        ordered = sorted(samples)  # one sort serves both percentiles
        return {
            "count": count,
            "p50_ms": self._at(ordered, 50),
            "p99_ms": self._at(ordered, 99),
        }


class HostAgent:
    """One simulated host: the real plugin servicer driven through the
    kubelet admission sequence in-process, chips accounted in the shared
    registry."""

    def __init__(
        self,
        node: str,
        servicer: TPUDevicePluginServicer,
        registry: AllocationRegistry,
        resource: str = consts.TPU_RESOURCE,
        pod_gone: Optional[Callable[[dict], bool]] = None,
    ):
        self.node = node
        self.servicer = servicer
        self.registry = registry
        self.resource = resource
        self.stub = InProcessPluginStub(servicer)
        self._pod_gone = pod_gone
        # the kubelet serializes pod admission per node; two concurrent
        # admissions would otherwise be offered the same free chips
        self._lock = threading.Lock()

    def free_ids(self) -> Set[str]:
        healthy = {
            i for i, h in self.servicer.snapshot().items() if h == HEALTHY
        }
        return healthy - self.registry.held_ids(self.node, self.resource)

    def allocate(
        self,
        count: int,
        pod: dict,
        must_include: Sequence[str] = (),
        gang_id: Optional[str] = None,
    ) -> List[str]:
        """Admit ``count`` chips for ``pod`` through the real plugin
        path; returns the chip ids held. Raises
        :class:`InsufficientChipsError` when the host can't serve it,
        :class:`PodGoneError` (chips released) when the pod was deleted
        mid-allocation."""
        with self._lock:
            available = sorted(self.free_ids(), key=str)
            must = [str(m) for m in must_include]
            if len(available) < count or any(
                m not in available for m in must
            ):
                raise InsufficientChipsError(
                    f"{self.node}: want {count} (must={must}), "
                    f"free {available}"
                )
            chosen, _resp = admit_and_allocate(
                self.stub, self.resource, available, count, must
            )
            self.registry.hold(
                self.node, self.resource, pod["uid"], chosen, gang_id=gang_id
            )
        # outside the admission lock: the existence probe is I/O. A
        # FAILED probe reads as "still alive" — the hold stands and the
        # normal reap path releases it; treating a transient probe error
        # as gone would release chips under a live pod
        gone = False
        if self._pod_gone is not None:
            try:
                gone = self._pod_gone(pod)
            except Exception:
                log.debug("pod-gone probe failed", exc_info=True)
        if gone:
            freed = self.registry.release_pod(pod["uid"])
            raise PodGoneError(
                f"pod {pod.get('namespace', '')}/{pod.get('name', '')} "
                f"deleted mid-allocation; released {freed} chip(s)"
            )
        return chosen


class ChurnEngine:
    """The load generator + correctness harness."""

    def __init__(
        self,
        client,
        node_names: Sequence[str],
        *,
        namespace: str = "alloc-churn",
        chips_per_host: int = 8,
        host_topology: str = "2x4",
        generation: str = "v5e",
        workers: int = 8,
        rate_per_min: float = 0.0,
        gang_fraction: float = 0.15,
        gang_hosts: int = 2,
        sizes: Sequence[int] = (1, 2, 4, 8),
        lifetime_s: Tuple[float, float] = (0.3, 1.2),
        cancel_prob: float = 0.02,
        sample_k: int = 16,
        seed: int = 0,
        registry: Optional[AllocationRegistry] = None,
        coordinator: Optional[GangCoordinator] = None,
    ):
        self.client = client
        self.node_names = list(node_names)
        self.namespace = namespace
        self.chips_per_host = chips_per_host
        self.host_topology = host_topology
        self.generation = generation
        self.workers = workers
        self.rate_per_min = rate_per_min
        self.gang_fraction = gang_fraction
        self.gang_hosts = gang_hosts
        self.sizes = tuple(sizes)
        self.lifetime_s = lifetime_s
        self.cancel_prob = cancel_prob
        self.sample_k = sample_k
        self.seed = seed
        self.registry = registry or AllocationRegistry()
        self.coordinator = coordinator or GangCoordinator()
        self.resource = consts.TPU_RESOURCE

        def pod_gone(pod: dict) -> bool:
            return (
                self.client.get_or_none(
                    "v1", "Pod", pod["name"], pod["namespace"]
                )
                is None
            )

        self._pod_gone = pod_gone
        # guards fleet membership (node_names + agents) against the
        # lifecycle hooks: joins/preemptions mutate the fleet while the
        # workers place against it
        self._fleet_lock = threading.Lock()
        self.agents: Dict[str, HostAgent] = {
            node: self._make_agent(node) for node in self.node_names
        }

        # shared counters: updated via _bump() only — a plain `+=` from
        # 8 worker threads is LOAD/ADD/STORE and loses increments under
        # preemption, and a lost invariant_violations increment would
        # turn a detected violation into a false-green round
        self._count_lock = threading.Lock()
        self.allocations_total = 0
        self.failures_total = 0
        self.failures_no_host = 0
        self.failures_insufficient = 0
        self.failures_hold_contention = 0
        self.cancelled_total = 0
        self.errors_total = 0
        self.invariant_violations = 0
        # the gang-specific slice of invariant_violations: a red gate
        # must point its reader at the right admission path
        self.partial_gang_violations = 0
        self.gangs_admitted = 0
        self.gangs_failed = 0
        self.gangs_timed_out = 0
        self.pods_created = 0
        self.pods_reaped = 0
        # fleet lifecycle (joins/preemptions/layout shifts mid-churn)
        self.hosts_attached = 0
        self.hosts_detached = 0
        self.pods_evicted_lifecycle = 0
        self.gangs_rescheduled = 0
        self.fragmentation_last_pct = 0.0
        self.fragmentation_max_pct = 0.0

        self.alloc_latency = LatencyRecorder(
            observer=self._alloc_hist_observer()
        )
        self.gang_ready_latency = LatencyRecorder()

        self._seq = itertools.count()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._reap_lock = threading.Lock()
        self._reap_cond = threading.Condition(self._reap_lock)
        self._reap_heap: List[Tuple[float, int, dict]] = []
        self._tokens_lock = threading.Lock()
        self._tokens = float(workers)
        self._tokens_at = time.monotonic()
        self._started_at: Optional[float] = None
        self._stopped_at: Optional[float] = None

    def _bump(self, attr: str, n: int = 1) -> None:
        with self._count_lock:
            setattr(self, attr, getattr(self, attr) + n)

    def _make_agent(self, node: str) -> HostAgent:
        return HostAgent(
            node,
            SyntheticChipServicer(
                chips=self.chips_per_host,
                generation=self.generation,
                host_topology=self.host_topology,
                cdi_enabled=True,
            ),
            self.registry,
            pod_gone=self._pod_gone,
        )

    # -- fleet lifecycle --------------------------------------------------
    def attach_host(self, node: str) -> None:
        """Autoscale join: a fresh host (real servicer, empty ledger)
        enters the placement pool. Idempotent."""
        with self._fleet_lock:
            if node in self.agents:
                return
            self.agents[node] = self._make_agent(node)
            self.node_names.append(node)
        self._bump("hosts_attached")

    def detach_host(self, node: str) -> int:
        """Spot preemption / scale-down: the host leaves the placement
        pool, every gang with a member on it is terminated whole (a
        slice job without one member host is dead, not degraded), and
        the registry drops the node's chips — a hold on vanished
        hardware is a zombie. Returns chips freed. Idempotent."""
        with self._fleet_lock:
            if self.agents.pop(node, None) is None:
                return 0
            try:
                self.node_names.remove(node)
            except ValueError:
                pass
        self._evict_holders(node, reschedule=False)
        freed = self.registry.release_node(node)
        self._bump("hosts_detached")
        return freed

    def evict_host(self, node: str) -> int:
        """Layout shift (live re-partition): the host stays in the
        fleet, but every job holding chips on it is terminated — gangs
        whole — so the churn workers re-admit the demand against the new
        layout (gang rescheduling). Returns pods evicted."""
        evicted = self._evict_holders(node, reschedule=True)
        return evicted

    def _evict_holders(self, node: str, reschedule: bool) -> int:
        evicted = 0
        gangs: Set[str] = set()
        for pod_key in self.registry.pods_on_node(node):
            gang = self.registry.gang_of(pod_key)
            if gang is not None:
                gangs.add(gang)
                continue
            ns, _, name = pod_key.partition("/")
            self._terminate({"uid": pod_key, "namespace": ns, "name": name})
            evicted += 1
        for gang in gangs:
            for pod_key in self.registry.pods_of_gang(gang):
                ns, _, name = pod_key.partition("/")
                self._terminate(
                    {"uid": pod_key, "namespace": ns, "name": name}
                )
                evicted += 1
            if reschedule:
                self._bump("gangs_rescheduled")
        if evicted:
            self._bump("pods_evicted_lifecycle", evicted)
        return evicted

    # -- lifecycle --------------------------------------------------------
    def ensure_namespace(self) -> None:
        try:
            self.client.create(
                {
                    "apiVersion": "v1",
                    "kind": "Namespace",
                    "metadata": {"name": self.namespace},
                }
            )
        except Exception:
            pass  # exists (or FakeClient without namespace admission)

    def start(self) -> None:
        self.ensure_namespace()
        self._started_at = time.monotonic()
        self._stop.clear()
        reaper = threading.Thread(
            target=self._reaper, daemon=True, name="churn-reaper"
        )
        reaper.start()
        self._threads = [reaper]
        for w in range(self.workers):
            t = threading.Thread(
                target=self._worker,
                args=(w,),
                daemon=True,
                name=f"churn-worker-{w}",
            )
            t.start()
            self._threads.append(t)

    def stop(self, drain_timeout_s: float = 60.0) -> None:
        """Halt intake, terminate every live pod, release every chip.

        The drain must survive a straggler worker: under a loaded box a
        worker can sit in one slow client call past any join timeout and
        schedule its last job's reap AFTER a one-shot heap drain — so
        the drain loops until the heap is empty AND every worker exited,
        then sweeps the ledger for pods that still exist but were never
        scheduled. Holds whose pod is ALREADY GONE are genuine leaks and
        deliberately survive to ``drain_check``."""
        self._stop.set()
        with self._reap_cond:
            self._reap_cond.notify_all()
        workers = [t for t in self._threads if t.name != "churn-reaper"]
        # ONE shared deadline across every join: sequential per-thread
        # timeouts would let N wedged threads stretch the "bounded"
        # drain to N × timeout
        join_deadline = time.monotonic() + drain_timeout_s / 2
        for t in self._threads:
            t.join(timeout=max(0.0, join_deadline - time.monotonic()))
        self._stopped_at = time.monotonic()
        deadline = time.monotonic() + drain_timeout_s
        while True:
            with self._reap_lock:
                leftovers = [pod for _, _, pod in self._reap_heap]
                self._reap_heap = []
            for pod in leftovers:
                self._terminate(pod)
            workers_alive = any(t.is_alive() for t in workers)
            with self._reap_lock:
                heap_empty = not self._reap_heap
            if (not workers_alive and heap_empty) or (
                time.monotonic() >= deadline
            ):
                if workers_alive:
                    log.warning(
                        "churn drain: %d worker(s) still alive at the "
                        "drain deadline",
                        sum(1 for t in workers if t.is_alive()),
                    )
                break
            time.sleep(0.05)
        # final ledger sweep: a pod that still EXISTS but holds chips was
        # admitted in the shutdown race and never scheduled for reaping —
        # terminate it like the reaper would have
        for pod_key in self.registry.holding_pod_keys():
            ns, _, name = pod_key.partition("/")
            if not name:
                continue
            try:
                if (
                    self.client.get_or_none("v1", "Pod", name, ns)
                    is not None
                ):
                    self._terminate(
                        {"uid": pod_key, "namespace": ns, "name": name}
                    )
            except Exception:
                log.debug("drain sweep probe failed", exc_info=True)

    def drain_check(self) -> dict:
        """Post-stop steady-state verdict: zero held chips, zero holding
        pods — the no-leaked-reservations invariant."""
        return {
            "chips_held": self.registry.total_held(),
            "pods_holding": self.registry.pods_holding(),
            "double_allocations": self.registry.double_allocation_attempts,
            "invariant_violations": self.invariant_violations,
        }

    def wire_lifecycle(self, sim) -> None:
        """Attach to a kubesim's fleet-lifecycle hooks: node ADDED joins
        the placement pool, node DELETED detaches (gangs terminated
        whole, chips released) — the plugin/kubelet half of a lifecycle
        event the apiserver half already emitted watch events for."""

        def hook(event: str, name: str) -> None:
            if event == "ADDED":
                self.attach_host(name)
            elif event == "DELETED":
                self.detach_host(name)

        sim.add_lifecycle_hook(hook)

    # -- rate control -----------------------------------------------------
    def _take_token(self) -> bool:
        """Token bucket at ``rate_per_min`` (0 = unlimited); False when
        stopping."""
        if self.rate_per_min <= 0:
            return not self._stop.is_set()
        rate_s = self.rate_per_min / 60.0
        while not self._stop.is_set():
            with self._tokens_lock:
                now = time.monotonic()
                self._tokens = min(
                    float(self.workers),
                    self._tokens + (now - self._tokens_at) * rate_s,
                )
                self._tokens_at = now
                if self._tokens >= 1.0:
                    self._tokens -= 1.0
                    return True
            self._stop.wait(min(0.05, 1.0 / rate_s))
        return False

    # -- pod plumbing -----------------------------------------------------
    def _make_pod(self, node: str, size: int, job_id: str) -> Optional[dict]:
        name = f"churn-{next(self._seq)}"
        body = {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": name,
                "namespace": self.namespace,
                "labels": {"app": "alloc-churn", "schedsim/job": job_id},
            },
            "spec": {
                "nodeName": node,
                "containers": [
                    {
                        "name": "w",
                        "image": "jax-workload",
                        "resources": {
                            "requests": {self.resource: str(size)},
                            "limits": {self.resource: str(size)},
                        },
                    }
                ],
            },
        }
        try:
            self.client.create(body)
        except Exception:
            return None
        self._bump("pods_created")
        return {
            "uid": f"{self.namespace}/{name}",
            "namespace": self.namespace,
            "name": name,
            "node": node,
            "size": size,
        }

    def _terminate(self, pod: dict) -> None:
        try:
            self.client.delete_if_exists(
                "v1", "Pod", pod["name"], pod["namespace"]
            )
        except Exception:
            log.debug("churn pod delete failed", exc_info=True)
        self.registry.release_pod(pod["uid"])
        self._bump("pods_reaped")

    def _schedule_reap(self, pods: Sequence[dict], rng: random.Random) -> None:
        lo, hi = self.lifetime_s
        expiry = time.monotonic() + rng.uniform(lo, hi)
        with self._reap_cond:
            for pod in pods:
                heapq.heappush(
                    self._reap_heap, (expiry, next(self._seq), pod)
                )
            self._reap_cond.notify()

    def _reaper(self) -> None:
        last_sample = 0.0
        while not self._stop.is_set():
            due: List[dict] = []
            with self._reap_cond:
                now = time.monotonic()
                while self._reap_heap and self._reap_heap[0][0] <= now:
                    _, _, pod = heapq.heappop(self._reap_heap)
                    due.append(pod)
                if not due:
                    timeout = (
                        min(0.2, max(0.0, self._reap_heap[0][0] - now))
                        if self._reap_heap
                        else 0.2
                    )
                    self._reap_cond.wait(timeout)
            # terminate OUTSIDE the scheduling lock: deletes are I/O and
            # workers must keep scheduling reaps meanwhile
            for pod in due:
                self._terminate(pod)
            now = time.monotonic()
            if now - last_sample >= 0.5:
                last_sample = now
                try:
                    self.sample_fragmentation()
                    self.publish_metrics()
                except Exception:
                    log.debug("fragmentation sample failed", exc_info=True)

    # -- placement --------------------------------------------------------
    def _score(self, node: str, size: int) -> Optional[Tuple[int, int]]:
        """ICI-aware best-fit score (lower is better): prefer hosts whose
        free chips still hold a contiguous block covering the request,
        then the tightest fit — churn packs instead of shredding."""
        agent = self.agents.get(node)
        if agent is None:
            return None  # detached between snapshot and scoring
        free = agent.free_ids()
        if len(free) < size:
            return None
        fits = (
            largest_contiguous_block(
                free, self.host_topology, self.generation
            )
            >= size
        )
        return (0 if fits else 1, len(free) - size)

    def _pick_hosts(
        self, size: int, count: int, rng: random.Random
    ) -> List[str]:
        """Up to ``count`` distinct hosts by score, sampled
        power-of-k-choices first (O(sample) per job at any fleet size),
        full scan only when the sample comes up short."""
        with self._fleet_lock:
            fleet = list(self.node_names)
        if not fleet:
            return []
        sample_n = min(max(self.sample_k, count * 4), len(fleet))
        candidates = rng.sample(fleet, sample_n)
        scored = []
        for node in candidates:
            s = self._score(node, size)
            if s is not None:
                scored.append((s, node))
        if len(scored) < count and sample_n < len(fleet):
            scored = []
            for node in fleet:
                s = self._score(node, size)
                if s is not None:
                    scored.append((s, node))
        scored.sort()
        return [node for _, node in scored[:count]]

    # -- job bodies -------------------------------------------------------
    def _worker(self, widx: int) -> None:
        rng = random.Random((self.seed << 8) ^ widx)
        while self._take_token():
            try:
                if rng.random() < self.gang_fraction:
                    self._run_gang(rng)
                else:
                    self._run_single(rng)
            except DoubleAllocationError:
                self._bump("invariant_violations")
                log.exception("INVARIANT VIOLATION: double allocation")
            except Exception:
                self._bump("errors_total")
                log.exception("churn job failed unexpectedly")

    def _run_single(self, rng: random.Random) -> None:
        size = rng.choice(self.sizes)
        for _attempt in range(3):
            hosts = self._pick_hosts(size, 1, rng)
            if not hosts:
                self._bump("failures_total")
                self._bump("failures_no_host")
                return
            node = hosts[0]
            job_id = f"job-{next(self._seq)}"
            if not self.coordinator.acquire(job_id, [node], timeout_s=0.25):
                continue  # a gang holds this host; re-pick
            try:
                if self._stop.is_set():
                    return  # shutting down: don't admit into the drain
                agent = self.agents.get(node)
                if agent is None:
                    # host preempted between pick and admission: a load
                    # condition of a churning fleet, not an error
                    self._bump("failures_total")
                    self._bump("failures_no_host")
                    return
                pod = self._make_pod(node, size, job_id)
                if pod is None:
                    self._bump("failures_total")
                    return
                if rng.random() < self.cancel_prob:
                    # deletion racing allocation: the admission path must
                    # release the reservation it just took
                    try:
                        self.client.delete_if_exists(
                            "v1", "Pod", pod["name"], pod["namespace"]
                        )
                    except Exception:
                        pass
                t0 = time.perf_counter()
                try:
                    with trace.span(
                        "alloc.allocate", node=node, size=size
                    ):
                        agent.allocate(size, pod)
                except PodGoneError:
                    self._bump("cancelled_total")
                    return
                except InsufficientChipsError:
                    self._bump("failures_total")
                    self._bump("failures_insufficient")
                    self._terminate(pod)
                    return
                self.alloc_latency.add((time.perf_counter() - t0) * 1000.0)
                self._bump("allocations_total")
                self._schedule_reap([pod], rng)
                return
            finally:
                self.coordinator.release(job_id, [node])
        # three straight coordinator-hold losses: contention, NOT
        # missing capacity — label it so a red round reads right
        self._bump("failures_total")
        self._bump("failures_hold_contention")

    def _run_gang(self, rng: random.Random) -> None:
        """Multi-host slice job: one pod per member host, admitted
        all-or-nothing under coordinator holds."""
        m = self.gang_hosts
        size = self.chips_per_host  # slice jobs take whole hosts
        gang_id = f"gang-{next(self._seq)}"
        t0 = time.perf_counter()
        nodes = self._pick_hosts(size, m, rng)
        if len(nodes) < m:
            self._bump("gangs_failed")
            self._bump("failures_total")
            self._bump("failures_no_host")
            return
        if not self.coordinator.acquire(gang_id, nodes):
            self._bump("gangs_timed_out")
            self._bump("failures_total")
            return
        placed: List[dict] = []
        gang_span = trace.span(
            "alloc.gang_admit", gang=gang_id, hosts=m
        )
        gang_span.__enter__()
        try:
            if self._stop.is_set():
                return  # shutting down: don't admit into the drain
            for node in nodes:
                agent = self.agents.get(node)
                if agent is None:
                    # member host preempted mid-admission: the gang
                    # rolls back whole (all-or-nothing)
                    raise InsufficientChipsError(f"{node}: host vanished")
                pod = self._make_pod(node, size, gang_id)
                if pod is None:
                    raise InsufficientChipsError(f"{node}: pod create failed")
                placed.append(pod)
                t_alloc = time.perf_counter()
                agent.allocate(size, pod, gang_id=gang_id)
                self.alloc_latency.add(
                    (time.perf_counter() - t_alloc) * 1000.0
                )
            # all members placed: the all-or-nothing half is observable
            held = self.registry.pods_of_gang(gang_id)
            if len(held) != m:
                self._bump("invariant_violations")
                self._bump("partial_gang_violations")
                flight.record(
                    "alloc.partial_gang", gang=gang_id, held=len(held),
                    want=m,
                )
                raise AssertionError(
                    f"{gang_id}: {len(held)}/{m} members hold chips after "
                    f"admission ({held})"
                )
            self.gang_ready_latency.add((time.perf_counter() - t0) * 1000.0)
            self._bump("allocations_total", m)
            self._bump("gangs_admitted")
            self._schedule_reap(placed, rng)
        except Exception as e:
            # rollback on ANY failure — the none half of all-or-nothing
            # must hold for unexpected errors too (a fail-closed
            # preference RuntimeError, a ledger DoubleAllocationError),
            # not just the expected load conditions
            for pod in placed:
                self._terminate(pod)
            if self.registry.pods_of_gang(gang_id):
                self._bump("invariant_violations")
                self._bump("partial_gang_violations")
                flight.record(
                    "alloc.partial_gang", gang=gang_id, phase="rollback"
                )
                raise AssertionError(
                    f"{gang_id}: rollback left members holding chips"
                )
            self._bump("gangs_failed")
            self._bump("failures_total")
            if not isinstance(e, (InsufficientChipsError, PodGoneError)):
                raise  # unexpected: surface to the worker's counters
        finally:
            gang_span.__exit__(None, None, None)
            self.coordinator.release(gang_id, nodes)

    # -- observability ----------------------------------------------------
    def _alloc_hist_observer(self):
        """The alloc-latency histogram's observe hook (no-op stub
        without prometheus; None when metrics are unimportable)."""
        try:
            from tpu_operator.controllers.operator_metrics import (
                OperatorMetrics,
            )

            return OperatorMetrics().alloc_latency_ms_hist.observe
        except Exception:
            return None

    def set_node_health(self, node: str, healthy: bool) -> None:
        """Flip every chip on one simulated host (the churn half of a
        chip-death injection — kubesim's ``kill_node_chips`` covers the
        operator's view; this covers the plugin's)."""
        agent = self.agents.get(node)
        if agent is None:
            return  # host left the fleet: nothing to flip
        for dev in list(agent.servicer.snapshot()):
            if healthy:
                agent.servicer.mark_healthy(dev)
            else:
                agent.servicer.mark_unhealthy(dev)

    def sample_fragmentation(self) -> float:
        with self._fleet_lock:
            agents = list(self.agents.values())
        pct = fragmentation_pct(
            (a.free_ids() for a in agents),
            self.host_topology,
            self.generation,
        )
        self.fragmentation_last_pct = pct
        self.fragmentation_max_pct = max(self.fragmentation_max_pct, pct)
        return pct

    def rate_per_min_observed(self) -> Optional[float]:
        if self._started_at is None:
            return None
        end = self._stopped_at or time.monotonic()
        elapsed = max(end - self._started_at, 1e-6)
        return round(self.allocations_total * 60.0 / elapsed, 1)

    def publish_metrics(self) -> None:
        """Feed the ``alloc_*`` operator gauges (no-op without
        prometheus)."""
        try:
            from tpu_operator.controllers.operator_metrics import (
                HAVE_PROM,
                OperatorMetrics,
            )

            if not HAVE_PROM:
                return
            m = OperatorMetrics()
            m.alloc_requests.set(
                self.allocations_total
                + self.failures_total
                + self.cancelled_total
            )
            m.alloc_failures.set(self.failures_total)
            # gangs actually admitted, NOT coordinator.acquires_total:
            # single jobs are gangs of one and would inflate the gauge
            # an order of magnitude past its help text
            m.alloc_gang_holds.set(self.gangs_admitted)
            m.alloc_fragmentation_pct.set(self.fragmentation_last_pct)
            p99 = self.alloc_latency.percentile(99)
            if p99 is not None:
                m.alloc_latency_ms_p99.set(p99)
        except Exception:
            log.debug("alloc metrics publish failed", exc_info=True)

    def stats(self) -> dict:
        """The ``/debug/vars`` "allocation" payload."""
        return {
            "nodes": len(self.node_names),
            "hosts_attached": self.hosts_attached,
            "hosts_detached": self.hosts_detached,
            "pods_evicted_lifecycle": self.pods_evicted_lifecycle,
            "gangs_rescheduled": self.gangs_rescheduled,
            "allocations_total": self.allocations_total,
            "alloc_per_min": self.rate_per_min_observed(),
            "failures_total": self.failures_total,
            "failures_no_host": self.failures_no_host,
            "failures_insufficient": self.failures_insufficient,
            "failures_hold_contention": self.failures_hold_contention,
            "cancelled_total": self.cancelled_total,
            "errors_total": self.errors_total,
            "invariant_violations": self.invariant_violations,
            "partial_gang_violations": self.partial_gang_violations,
            "pods_created": self.pods_created,
            "pods_reaped": self.pods_reaped,
            "latency_ms": self.alloc_latency.summary(),
            "gangs": {
                "admitted": self.gangs_admitted,
                "failed": self.gangs_failed,
                "timed_out": self.gangs_timed_out,
                "hosts_per_gang": self.gang_hosts,
                "time_to_ready_ms": self.gang_ready_latency.summary(),
            },
            "fragmentation_pct": self.fragmentation_last_pct,
            "fragmentation_max_pct": self.fragmentation_max_pct,
            "registry": self.registry.stats(),
            "coordinator": self.coordinator.stats(),
        }
