"""Fleet-wide chip-allocation ledger + fragmentation math.

The correctness spine of the scheduling-churn engine: every admitted
allocation records its chips here, every pod termination releases them,
and the two invariants the churn harness exists to prove are enforced at
the ledger, not asserted after the fact —

* **no chip double-allocated**: a hold naming a chip another pod already
  holds raises :class:`DoubleAllocationError` (and counts, so a bench
  can assert the counter stayed zero);
* **no leaked reservations**: once every pod of a churn wave terminates,
  ``total_held()`` must read zero — the steady-state check both the
  tier-1 engine test and the 1000-node bench gate on.

Fragmentation is defined over this ledger too (``fragmentation_pct``):
the share of free chips NOT inside their host's largest ICI-connected
free block — 0 when every host's free chips form one connected region,
growing as churn shreds hosts into disconnected leftovers that can only
serve small or non-contiguous requests. See ``docs/allocation.md``.

No k8s imports here: the ledger is shared by the kubelet device-manager
simulator (``kube/kubelet_sim.py``) and the in-process churn agents.
"""

from __future__ import annotations

import threading
from functools import lru_cache
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tpu_operator.workloads import topology as topo


class DoubleAllocationError(AssertionError):
    """A chip was offered/held twice — the invariant violation the churn
    harness exists to catch. AssertionError subclass on purpose: this is
    a bug in the admission path, never a load condition to retry."""


class AllocationRegistry:
    """Thread-safe ledger of (node, resource) → chip → holder."""

    def __init__(self):
        self._lock = threading.Lock()
        # (node, resource) -> {device_id: pod_key}
        self._held: Dict[Tuple[str, str], Dict[str, str]] = {}
        # pod_key -> [(node, resource, ids)]
        self._pods: Dict[str, List[Tuple[str, str, Tuple[str, ...]]]] = {}
        self._gang_of: Dict[str, str] = {}
        # holder generation at record time (observability: a hold must
        # only ever be recorded under the plugin generation that
        # admitted it — the fence lives in the kubelet sim)
        self._gen_of: Dict[str, object] = {}
        self.holds_total = 0
        self.releases_total = 0
        self.chips_held_peak = 0
        self.double_allocation_attempts = 0

    # -- hold / release --------------------------------------------------
    def hold(
        self,
        node: str,
        resource: str,
        pod_key: str,
        device_ids: Iterable[str],
        gang_id: Optional[str] = None,
        generation: object = None,
    ) -> None:
        ids = tuple(str(i) for i in device_ids)
        with self._lock:
            slot = self._held.setdefault((node, resource), {})
            clash = [i for i in ids if i in slot]
            if clash or len(set(ids)) != len(ids):
                self.double_allocation_attempts += 1
                holders = sorted({slot[i] for i in clash}) or [pod_key]
                raise DoubleAllocationError(
                    f"chip(s) {clash or sorted(ids)} on {node} already "
                    f"held by {holders}; refused for {pod_key}"
                )
            for i in ids:
                slot[i] = pod_key
            self._pods.setdefault(pod_key, []).append((node, resource, ids))
            if gang_id:
                self._gang_of[pod_key] = gang_id
            if generation is not None:
                self._gen_of[pod_key] = generation
            self.holds_total += 1
            self.chips_held_peak = max(
                self.chips_held_peak, self._total_held_locked()
            )

    def release_pod(self, pod_key: str) -> int:
        """Free every chip ``pod_key`` holds; returns chips freed (0 when
        the pod held nothing — release is idempotent, termination paths
        race)."""
        with self._lock:
            entries = self._pods.pop(pod_key, [])
            self._gang_of.pop(pod_key, None)
            self._gen_of.pop(pod_key, None)
            freed = 0
            for node, resource, ids in entries:
                slot = self._held.get((node, resource), {})
                for i in ids:
                    if slot.get(i) == pod_key:
                        del slot[i]
                        freed += 1
                if not slot:
                    self._held.pop((node, resource), None)
            if entries:
                self.releases_total += 1
            return freed

    def release_node(self, node: str) -> int:
        """Free every chip held on ``node`` — the host vanished (spot
        preemption, scale-down): its kubelet/plugin sim is being
        detached and a hold on hardware that no longer exists is a
        zombie. Pods left holding chips ONLY on other nodes keep those
        holds (their gang is the engine's problem — it terminates the
        whole job); pods whose last hold this was leave the ledger.
        Returns chips freed."""
        with self._lock:
            freed = 0
            for (n, resource) in [
                k for k in self._held if k[0] == node
            ]:
                slot = self._held.pop((n, resource))
                freed += len(slot)
            if not freed:
                return 0
            for pod_key in list(self._pods):
                kept = [
                    e for e in self._pods[pod_key] if e[0] != node
                ]
                if kept:
                    self._pods[pod_key] = kept
                else:
                    del self._pods[pod_key]
                    self._gang_of.pop(pod_key, None)
                    self._gen_of.pop(pod_key, None)
            self.releases_total += 1
            return freed

    # -- views -----------------------------------------------------------
    def held_ids(self, node: str, resource: str) -> Set[str]:
        with self._lock:
            return set(self._held.get((node, resource), {}))

    def holder_of(self, node: str, resource: str, dev_id: str):
        with self._lock:
            return self._held.get((node, resource), {}).get(str(dev_id))

    def _total_held_locked(self) -> int:
        return sum(len(s) for s in self._held.values())

    def total_held(self) -> int:
        with self._lock:
            return self._total_held_locked()

    def pods_holding(self) -> int:
        with self._lock:
            return len(self._pods)

    def holding_pod_keys(self) -> List[str]:
        """Every pod key currently holding chips (the drain sweep's
        worklist)."""
        with self._lock:
            return sorted(self._pods)

    def pods_of_gang(self, gang_id: str) -> List[str]:
        with self._lock:
            return sorted(
                p for p, g in self._gang_of.items() if g == gang_id
            )

    def pods_on_node(self, node: str) -> List[str]:
        """Pod keys holding any chip on ``node`` — the worklist a
        lifecycle/repartition eviction sweeps (gang-aware: the caller
        expands each pod to its whole gang)."""
        with self._lock:
            return sorted(
                pod_key
                for pod_key, entries in self._pods.items()
                if any(e[0] == node for e in entries)
            )

    def gang_of(self, pod_key: str) -> Optional[str]:
        with self._lock:
            return self._gang_of.get(pod_key)

    def nodes_holding(self) -> Set[str]:
        """Every node with at least one held chip — the zombie-hold
        invariant check compares this against the live fleet."""
        with self._lock:
            return {n for (n, _r), s in self._held.items() if s}

    def generation_of(self, pod_key: str):
        with self._lock:
            return self._gen_of.get(pod_key)

    def stats(self) -> dict:
        with self._lock:
            return {
                "chips_held": self._total_held_locked(),
                "chips_held_peak": self.chips_held_peak,
                "pods_holding": len(self._pods),
                "holds_total": self.holds_total,
                "releases_total": self.releases_total,
                "double_allocation_attempts": self.double_allocation_attempts,
            }


# -- fragmentation math ----------------------------------------------------


@lru_cache(maxsize=32)
def _adjacency(topology: str, generation: str) -> Tuple[Tuple[int, ...], ...]:
    """Chip-index adjacency list for one host mesh, memoized: the BFS
    below runs per candidate host per placement AND per host per
    fragmentation sample — re-parsing the topology string inside every
    neighbors() call was tens of thousands of redundant parses per
    second on the allocation hot path."""
    dims = topo.parse_topology(topology)
    return tuple(
        tuple(
            topo.coord_to_index(nb, dims)
            for nb in topo.neighbors(
                topo.index_to_coord(i, dims), topology, generation
            )
        )
        for i in range(topo.chip_count(topology))
    )


def largest_contiguous_block(
    free_ids: Iterable, topology: str, generation: str
) -> int:
    """Size of the biggest ICI-connected component of ``free_ids`` in the
    host mesh. Ids outside the mesh (fallback registries) count as
    singleton blocks — no geometry means no contiguity to lose."""
    adjacency = _adjacency(topology, generation)
    n_total = len(adjacency)
    free: Set[int] = set()
    strays = 0
    for i in free_ids:
        try:
            idx = int(i)
        except (TypeError, ValueError):
            strays += 1
            continue
        if 0 <= idx < n_total:
            free.add(idx)
        else:
            strays += 1
    best = 1 if strays else 0
    seen: Set[int] = set()
    for seed in free:
        if seed in seen:
            continue
        comp = {seed}
        frontier = [seed]
        while frontier:
            cur = frontier.pop()
            for nb_idx in adjacency[cur]:
                if nb_idx in free and nb_idx not in comp:
                    comp.add(nb_idx)
                    frontier.append(nb_idx)
        seen |= comp
        best = max(best, len(comp))
    return best


def fragmentation_pct(
    free_sets: Iterable[Iterable], topology: str, generation: str
) -> float:
    """Fleet fragmentation over per-host free-chip sets: ``100 × (1 −
    Σ largest_block / Σ free)``. 0.0 when every host's free chips form
    one connected block (an empty fleet reads 0.0 too — nothing free
    means nothing fragmented); approaches 100 as churn strands free
    chips in disconnected singletons."""
    free_total = 0
    contiguous_total = 0
    for free in free_sets:
        free = list(free)
        if not free:
            continue
        free_total += len(free)
        contiguous_total += largest_contiguous_block(
            free, topology, generation
        )
    if free_total == 0:
        return 0.0
    return round(100.0 * (1.0 - contiguous_total / free_total), 2)
