"""Scheduling-churn engine — allocation traffic through the device-plugin path.

The subsystem the convergence/remediation stack never exercised: sustained
foreground *allocation* traffic (short-lived pods requesting
``google.com/tpu`` chips) driven through the real device-plugin admission
sequence, with gang admission for multi-host slice jobs, ICI-topology-aware
placement scoring, and fleet fragmentation accounting. See
``docs/allocation.md``.

Layout:

* ``registry``  — fleet-wide chip ledger (double-allocation detection,
  leak accounting, fragmentation math);
* ``gang``      — bounded hold-and-release gang admission coordinator;
* ``engine``    — the load generator: per-host agents over real plugin
  servicers, placement scoring, latency percentiles, reaper.
"""

from tpu_operator.schedsim.registry import (  # noqa: F401
    AllocationRegistry,
    DoubleAllocationError,
)
