"""Gang admission — bounded hold-and-release over member hosts.

A multi-host slice job is admitted all-or-nothing: every member host
must take the job's chips, or none may keep them. The coordinator
provides the mutual-exclusion half of that contract — per-host admission
*holds* — with a protocol that cannot deadlock:

* **canonical order**: a gang acquires its member hosts' holds one at a
  time in one global order (sorted host name). Two gangs contending for
  overlapping hosts therefore collide at the FIRST shared host in that
  order, never in opposite orders — the circular wait a deadlock needs
  cannot form.
* **release-on-conflict**: a gang that finds its next host held releases
  everything it already holds and retries after a jittered backoff, so a
  half-admitted gang never pins hosts while waiting on another gang.
* **bounded holds**: every hold carries a TTL. A wedged admitter (or a
  crashed worker) cannot fence a host forever — the next acquirer
  reclaims the expired hold and counts the reclaim.
* **bounded admission**: ``acquire`` gives up after ``admit_timeout_s``
  and reports failure; the caller rolls the job back. Admission may
  fail; it may never hang.

Single-chip jobs ride the same gate as gangs of one — holds only protect
anything if *every* admission path honors them.
"""

from __future__ import annotations

import random
import threading
import time
import zlib
from typing import Dict, Iterable, List, Optional, Tuple


class GangCoordinator:
    """Per-host admission holds with TTL + deadlock-free multi-host
    acquisition."""

    def __init__(
        self,
        hold_ttl_s: float = 5.0,
        admit_timeout_s: float = 10.0,
        backoff_s: float = 0.002,
    ):
        self.hold_ttl_s = hold_ttl_s
        self.admit_timeout_s = admit_timeout_s
        self.backoff_s = backoff_s
        self._lock = threading.Lock()
        # node -> (gang_id, expires_at)
        self._holds: Dict[str, Tuple[str, float]] = {}
        self.acquires_total = 0
        self.conflicts_total = 0
        self.timeouts_total = 0
        self.expired_reclaims_total = 0

    # -- single-host primitives ------------------------------------------
    def try_hold(self, node: str, gang_id: str) -> bool:
        """One atomic try-acquire of one host's hold (reclaims expired
        holds). This is the only primitive that takes a hold — acquire()
        builds the multi-host protocol out of it, one host per lock
        acquisition, so contending gangs genuinely interleave."""
        now = time.monotonic()
        with self._lock:
            cur = self._holds.get(node)
            if cur is not None:
                holder, expires = cur
                if holder == gang_id:
                    # re-entrant refresh (same gang re-walks its order)
                    self._holds[node] = (gang_id, now + self.hold_ttl_s)
                    return True
                if expires > now:
                    return False
                self.expired_reclaims_total += 1
            self._holds[node] = (gang_id, now + self.hold_ttl_s)
            return True

    def release(self, gang_id: str, nodes: Iterable[str]) -> None:
        with self._lock:
            for node in nodes:
                if self._holds.get(node, (None, 0.0))[0] == gang_id:
                    del self._holds[node]

    def holder(self, node: str) -> Optional[str]:
        now = time.monotonic()
        with self._lock:
            cur = self._holds.get(node)
            if cur is None or cur[1] <= now:
                return None
            return cur[0]

    # -- the protocol -----------------------------------------------------
    def acquire(
        self,
        gang_id: str,
        nodes: Iterable[str],
        timeout_s: Optional[float] = None,
    ) -> bool:
        """All-or-nothing holds on every member host; True when the gang
        holds them all, False on admission timeout (nothing held)."""
        order: List[str] = sorted(set(nodes))
        deadline = time.monotonic() + (
            self.admit_timeout_s if timeout_s is None else timeout_s
        )
        # deterministic per-gang jitter: no shared RNG contention, and a
        # replay with the same gang ids backs off identically (crc32,
        # not hash() — builtin str hashing is randomized per process)
        rng = random.Random(zlib.crc32(gang_id.encode()))
        while True:
            got: List[str] = []
            blocked = False
            for node in order:
                if self.try_hold(node, gang_id):
                    got.append(node)
                else:
                    blocked = True
                    break
            if not blocked:
                with self._lock:
                    self.acquires_total += 1
                return True
            self.release(gang_id, got)
            with self._lock:
                self.conflicts_total += 1
            if time.monotonic() >= deadline:
                with self._lock:
                    self.timeouts_total += 1
                return False
            time.sleep(self.backoff_s * (0.5 + rng.random()))

    # -- observability ----------------------------------------------------
    def active_holds(self) -> int:
        now = time.monotonic()
        with self._lock:
            return sum(1 for _, exp in self._holds.values() if exp > now)

    def stats(self) -> dict:
        with self._lock:
            now = time.monotonic()
            return {
                "active_holds": sum(
                    1 for _, exp in self._holds.values() if exp > now
                ),
                "acquires_total": self.acquires_total,
                "conflicts_total": self.conflicts_total,
                "timeouts_total": self.timeouts_total,
                "expired_reclaims_total": self.expired_reclaims_total,
                "hold_ttl_s": self.hold_ttl_s,
            }
